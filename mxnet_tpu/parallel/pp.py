"""Pipeline parallelism: GPipe-style microbatched stages over the "pp"
mesh axis.

Reference parity: none — the reference's only model parallelism is manual
per-layer `group2ctx` device assignment executed by the engine (SURVEY.md
§2.4 'Model parallelism (manual)'); the brief makes PP first-class here.

TPU-native design (SURVEY.md §7.2 M8): all pipeline stages must be
structurally identical (the transformer-block case); their parameters are
STACKED along a leading stage axis and sharded over "pp", so each device
holds exactly one stage. A `shard_map` then runs the classic
collective-permute pipeline: each step every device applies its stage to
its current microbatch and `ppermute`s the activation to the next stage,
stage 0 feeding a fresh microbatch per step. The schedule is plain GPipe
— M + (P-1) steps for M microbatches over P stages, bubble fraction
(P-1)/(M+P-1) — compiled into ONE XLA program (a lax.fori_loop of
MXU work + ICI ppermutes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .mesh import AXIS_PP, PartitionSpec, current_mesh, shard_map_compat

__all__ = ["gpipe", "stack_stage_params"]


def stack_stage_params(stage_param_trees):
    """Stack N structurally-identical per-stage pytrees along a new
    leading stage axis (the layout gpipe shards over "pp")."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *stage_param_trees)


def gpipe(stage_fn, stacked_params, x, n_microbatches, mesh=None,
          axis=AXIS_PP):
    """Run `x` through P pipeline stages over the mesh's "pp" axis.

    stage_fn(stage_params, mb) -> mb_out — one stage's computation on one
    microbatch; activations must keep the same shape/dtype through every
    stage (transformer-block contract). stacked_params: pytree with
    leading stage axis (see stack_stage_params). x: (B, ...) global
    batch; B must divide into n_microbatches. Returns (B, ...), equal to
    applying the stages sequentially (GPipe is an exact-compute schedule,
    not an approximation).
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(
            f"gpipe needs an active mesh with a {axis!r} axis")
    n_stages = mesh.shape[axis]
    n_stage_params = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stage_params != n_stages:
        raise MXNetError(
            f"{n_stage_params} stacked stages != pp axis size {n_stages}")
    B = x.shape[0]
    M = int(n_microbatches)
    if B % M:
        raise MXNetError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M

    def local(params, xs):
        # params: this stage's slice, leading dim 1 → squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        xs = xs.reshape((M, mb) + xs.shape[1:])
        state0 = jnp.zeros_like(xs[0])
        ys0 = jnp.zeros_like(xs)

        def step(t, carry):
            state, ys = carry
            # stage 0 feeds microbatch t (mod M keeps indices legal in the
            # drain phase; those outputs are never recorded)
            inp = jnp.where(stage == 0, xs[t % M], state)
            out = stage_fn(params, inp)
            slot = (t - (n_stages - 1)) % M
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            ys = ys.at[slot].set(jnp.where(take, out, ys[slot]))
            state = lax.ppermute(out, axis, perm)
            return state, ys

        _, ys = lax.fori_loop(0, M + n_stages - 1, step, (state0, ys0))
        # result lives on the last stage; one-hot psum replicates it (the
        # cheap exit collective; callers slice further shardings on top)
        ys = lax.psum(jnp.where(stage == n_stages - 1, ys, 0.0), axis)
        return ys.reshape((B,) + ys.shape[2:])

    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(PartitionSpec(axis), PartitionSpec()),
                          out_specs=PartitionSpec(), check_rep=False)
    return fn(stacked_params, x)
