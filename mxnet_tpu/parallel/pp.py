"""Pipeline parallelism: GPipe-style microbatched stages over the "pp"
mesh axis.

Reference parity: none — the reference's only model parallelism is manual
per-layer `group2ctx` device assignment executed by the engine (SURVEY.md
§2.4 'Model parallelism (manual)'); the brief makes PP first-class here.

TPU-native design (SURVEY.md §7.2 M8): all pipeline stages must be
structurally identical (the transformer-block case); their parameters are
STACKED along a leading stage axis and sharded over "pp", so each device
holds exactly one stage. A `shard_map` then runs the classic
collective-permute pipeline: each step every device applies its stage to
its current microbatch and `ppermute`s the activation to the next stage,
stage 0 feeding a fresh microbatch per step. The schedule is plain GPipe
— M + (P-1) steps for M microbatches over P stages, bubble fraction
(P-1)/(M+P-1) — compiled into ONE XLA program (a lax.fori_loop of
MXU work + ICI ppermutes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .mesh import AXIS_PP, PartitionSpec, current_mesh, shard_map_compat

__all__ = ["gpipe", "stack_stage_params", "pipeline_loss",
           "pipeline_loss_and_grads", "pipeline_grads", "PPTrainStep"]


def stack_stage_params(stage_param_trees):
    """Stack N structurally-identical per-stage pytrees along a new
    leading stage axis (the layout gpipe shards over "pp")."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *stage_param_trees)


def gpipe(stage_fn, stacked_params, x, n_microbatches, mesh=None,
          axis=AXIS_PP):
    """Run `x` through P pipeline stages over the mesh's "pp" axis.

    stage_fn(stage_params, mb) -> mb_out — one stage's computation on one
    microbatch; activations must keep the same shape/dtype through every
    stage (transformer-block contract). stacked_params: pytree with
    leading stage axis (see stack_stage_params). x: (B, ...) global
    batch; B must divide into n_microbatches. Returns (B, ...), equal to
    applying the stages sequentially (GPipe is an exact-compute schedule,
    not an approximation).
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(
            f"gpipe needs an active mesh with a {axis!r} axis")
    n_stages = mesh.shape[axis]
    n_stage_params = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stage_params != n_stages:
        raise MXNetError(
            f"{n_stage_params} stacked stages != pp axis size {n_stages}")
    B = x.shape[0]
    M = int(n_microbatches)
    if B % M:
        raise MXNetError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M

    def local(params, xs):
        # params: this stage's slice, leading dim 1 → squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        xs = xs.reshape((M, mb) + xs.shape[1:])
        state0 = jnp.zeros_like(xs[0])
        ys0 = jnp.zeros_like(xs)

        def step(t, carry):
            state, ys = carry
            # stage 0 feeds microbatch t (mod M keeps indices legal in the
            # drain phase; those outputs are never recorded)
            inp = jnp.where(stage == 0, xs[t % M], state)
            out = stage_fn(params, inp)
            slot = (t - (n_stages - 1)) % M
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            ys = ys.at[slot].set(jnp.where(take, out, ys[slot]))
            state = lax.ppermute(out, axis, perm)
            return state, ys

        _, ys = lax.fori_loop(0, M + n_stages - 1, step, (state0, ys0))
        # result lives on the last stage; one-hot psum replicates it (the
        # cheap exit collective; callers slice further shardings on top)
        ys = lax.psum(jnp.where(stage == n_stages - 1, ys, 0.0), axis)
        return ys.reshape((B,) + ys.shape[2:])

    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(PartitionSpec(axis), PartitionSpec()),
                          out_specs=PartitionSpec(), check_rep=False)
    return fn(stacked_params, x)


# ---------------------------------------------------------------------------
# Full-model pipeline: embedding / repeated body / head+loss stage groups
# ---------------------------------------------------------------------------
#
# Real LMs are not identical-stages-only: the first stage embeds tokens,
# the last stage projects to the vocabulary and computes the loss. Here the
# rotating activation keeps ONE shape (mb, ...) — token ids enter stage 0
# as data, the head collapses to a per-microbatch scalar loss on the last
# stage — so embed and head live INSIDE the pipeline without breaking the
# ppermute contract. lax.cond keeps the embed/head work off the stages
# that don't own it (SPMD code, per-device control flow).
#
# Two schedules:
#   * schedule="gpipe": forward pipeline as one scan; XLA autodiff
#     produces the reverse pipeline (all M microbatch activations live —
#     the GPipe memory profile). Differentiable, drop into jax.grad.
#   * pipeline_grads(...): explicit 1F1B with per-stage recompute — the
#     warmup/steady/cooldown schedule, at most P microbatches in flight
#     per device, backward interleaved with forward. Activation memory
#     O(P·mb) instead of O(M·mb); param grads accumulate in the scan
#     carry. Returns (loss, grads) directly (it IS the backward).

def _mb_split(x, M):
    return x.reshape((M, x.shape[0] // M) + x.shape[1:])


def pipeline_loss(embed_fn, stage_fn, head_loss_fn, embed_params,
                  stacked_params, head_params, x, y, n_microbatches,
                  mesh=None, axis=AXIS_PP):
    """Mean loss of embed → P stacked body stages → head, pipelined over
    the mesh's "pp" axis with the GPipe schedule. Differentiable (reverse
    pipeline via XLA autodiff).

    embed_fn(embed_params, x_mb) -> h (mb, ...);
    stage_fn(body_params, h) -> h (same shape);
    head_loss_fn(head_params, h, y_mb) -> scalar mean loss over the
    microbatch. x, y: (B, ...) global batch arrays.
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(f"pipeline needs a mesh with a {axis!r} axis")
    P = mesh.shape[axis]
    n_dp = mesh.shape["dp"] if "dp" in mesh.axis_names else 1
    B = x.shape[0]
    M = int(n_microbatches)
    if B % max(n_dp, 1):
        raise MXNetError(f"batch {B} not divisible over dp={n_dp}")
    if (B // max(n_dp, 1)) % M:
        raise MXNetError(
            f"per-dp-shard batch {B // max(n_dp, 1)} not divisible into "
            f"{M} microbatches")

    def local(eparams, params, hparams, xs, ys):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % P) for i in range(P)]
        xs = _mb_split(xs, M)
        ys = _mb_split(ys, M)
        probe = embed_fn(eparams, xs[0])
        state0 = jnp.zeros_like(probe)

        def step(carry, t):
            state, loss_acc = carry
            # jnp.where, not lax.cond: this scan is differentiated (the
            # gpipe schedule relies on XLA autodiff), and shard_map's
            # transpose of lax.cond is broken both ways on current jax
            # (check_rep=False hits a _SpecError, check_rep=True a
            # branch-replication mismatch). select transposes cleanly;
            # the cost is that every stage runs embed/head each step —
            # acceptable for the simple schedule (1f1b is the perf path)
            h_in = jnp.where(stage == 0, embed_fn(eparams, xs[t % M]),
                             state)
            out = stage_fn(params, h_in)
            take = (stage == P - 1) & (t >= P - 1)
            mb_loss = jnp.where(
                take,
                head_loss_fn(hparams, out,
                             ys[(t - (P - 1)) % M]).astype(jnp.float32),
                jnp.zeros((), jnp.float32))
            state = lax.ppermute(out, axis, perm)
            return (state, loss_acc + mb_loss), None

        (_, loss_sum), _ = lax.scan(step, (state0, jnp.zeros((),
                                                            jnp.float32)),
                                    jnp.arange(M + P - 1))
        # loss lives on the last stage; psum replicates (others hold 0)
        loss = lax.psum(loss_sum, axis) / M
        if dp:
            loss = lax.pmean(loss, dp)
        return loss

    dp = "dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 else None
    bspec = PartitionSpec(dp) if dp else PartitionSpec()
    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec(axis), PartitionSpec(),
                  bspec, bspec),
        out_specs=PartitionSpec(), check_rep=False)
    return fn(embed_params, stacked_params, head_params, x, y)


def pipeline_loss_and_grads(embed_fn, stage_fn, head_loss_fn,
                            embed_params, stacked_params, head_params,
                            x, y, n_microbatches, mesh=None,
                            axis=AXIS_PP):
    """GPipe-schedule training step: (mean_loss, embed_grads,
    stacked_body_grads, head_grads) via XLA autodiff of the forward
    pipeline — the reverse pipeline falls out of the scan's transpose.

    Autodiff runs INSIDE the shard_map region, not through it: current
    jax cannot transpose a shard_map with check_rep=False (the rewrite
    machinery raises _SpecError on the residual specs) and
    check_rep=True rejects the pipeline's per-stage control flow, so
    each shard takes value_and_grad of the (replicated, psum'd) loss
    w.r.t. its LOCAL parameter copies — collectives transpose globally
    (ppermute reverses, psum broadcasts) — and the per-shard partials
    of the replicated embed/head params are psum-reduced back to the
    shared total. Same return convention as pipeline_grads: body grads
    stay sharded over "pp", embed/head grads replicated.
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(f"pipeline needs a mesh with a {axis!r} axis")
    P = mesh.shape[axis]
    n_dp = mesh.shape["dp"] if "dp" in mesh.axis_names else 1
    B = x.shape[0]
    M = int(n_microbatches)
    if B % max(n_dp, 1):
        raise MXNetError(f"batch {B} not divisible over dp={n_dp}")
    if (B // max(n_dp, 1)) % M:
        raise MXNetError(
            f"per-dp-shard batch {B // max(n_dp, 1)} not divisible into "
            f"{M} microbatches")

    def local(eparams, params, hparams, xs, ys):
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % P) for i in range(P)]
        xs_mb = _mb_split(xs, M)
        ys_mb = _mb_split(ys, M)

        def loss_local(e, p_stacked, h):
            # this function's return is each shard's SHARE of the mean
            # loss (nonzero on the last stage only) — deliberately NOT
            # psum-replicated: under check_rep=False, psum transposes
            # back to psum, which would inflate every gradient by the
            # axis size. Keeping collectives out of the differentiated
            # scalar means value_and_grad computes the exact partials
            # of Σ_shards(share) = the true mean loss.
            p = jax.tree_util.tree_map(lambda a: a[0], p_stacked)
            state0 = jnp.zeros_like(embed_fn(e, xs_mb[0]))

            def step(carry, t):
                state, loss_acc = carry
                # jnp.where, not lax.cond: select transposes cleanly
                # under the in-region autodiff (cond does not)
                h_in = jnp.where(stage == 0, embed_fn(e, xs_mb[t % M]),
                                 state)
                out = stage_fn(p, h_in)
                take = (stage == P - 1) & (t >= P - 1)
                mb_loss = jnp.where(
                    take,
                    head_loss_fn(h, out,
                                 ys_mb[(t - (P - 1)) % M]
                                 ).astype(jnp.float32),
                    jnp.zeros((), jnp.float32))
                state = lax.ppermute(out, axis, perm)
                return (state, loss_acc + mb_loss), None

            (_, loss_sum), _ = lax.scan(
                step, (state0, jnp.zeros((), jnp.float32)),
                jnp.arange(M + P - 1))
            share = loss_sum / M
            if dp:
                share = share / n_dp
            return share

        share, (ge, gb, gh) = jax.value_and_grad(
            loss_local, argnums=(0, 1, 2))(eparams, params, hparams)
        # replicate the loss value and the shared-parameter grads OUTSIDE
        # the differentiated function: the true grad of a replicated
        # parameter is the sum of the per-shard partials (the 1/n_dp
        # scaling already lives inside the loss, so dp also sums)
        loss = lax.psum(share, axis)
        if dp:
            loss = lax.psum(loss, dp)

        def repl(g):
            g = lax.psum(g, axis)
            return lax.psum(g, dp) if dp else g

        ge = jax.tree_util.tree_map(repl, ge)
        gh = jax.tree_util.tree_map(repl, gh)
        if dp:  # body params are replicated across dp: sum the partials
            gb = jax.tree_util.tree_map(lambda g: lax.psum(g, dp), gb)
        return loss, ge, gb, gh

    dp = "dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 else None
    bspec = PartitionSpec(dp) if dp else PartitionSpec()
    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec(axis), PartitionSpec(),
                  bspec, bspec),
        out_specs=(PartitionSpec(), PartitionSpec(),
                   PartitionSpec(axis), PartitionSpec()),
        check_rep=False)
    return fn(embed_params, stacked_params, head_params, x, y)


def pipeline_grads(embed_fn, stage_fn, head_loss_fn, embed_params,
                   stacked_params, head_params, x, y, n_microbatches,
                   mesh=None, axis=AXIS_PP):
    """Interleaved forward/backward (1F1B-style) pipeline training step
    with per-stage recompute: returns (mean_loss, embed_grads,
    stacked_body_grads, head_grads) — it IS the backward, no outer
    jax.grad.

    Schedule: stage p forwards microbatch m at step m+p and backwards it
    at step m + 2(P-1) - p; in steady state every device runs one
    forward and one backward per step, cotangents rotating stage→stage-1
    while activations rotate stage→stage+1. Each backward recomputes its
    stage's VJP from the SAVED INPUT activation (Megatron-style
    activation checkpointing), so activation residency is O(P) saved
    microbatch inputs per device instead of GPipe-autodiff's O(M).
    Gradients accumulate in the scan carry in f32: body grads stay
    sharded over "pp" (one stage's slice each), embed/head grads are
    psum-replicated on exit.
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(f"pipeline needs a mesh with a {axis!r} axis")
    P = mesh.shape[axis]
    n_dp = mesh.shape["dp"] if "dp" in mesh.axis_names else 1
    B = x.shape[0]
    M = int(n_microbatches)
    if B % max(n_dp, 1):
        raise MXNetError(f"batch {B} not divisible over dp={n_dp}")
    if (B // max(n_dp, 1)) % M:
        raise MXNetError(
            f"per-dp-shard batch {B // max(n_dp, 1)} not divisible into "
            f"{M} microbatches")
    if M < 1:
        raise MXNetError("need at least one microbatch")
    DEPTH = 2 * P  # stage p holds a microbatch input 2(P-1-p) steps

    def local(eparams, params, hparams, xs, ys):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = lax.axis_index(axis)
        fwd_perm = [(i, (i + 1) % P) for i in range(P)]
        bwd_perm = [((i + 1) % P, i) for i in range(P)]
        xs = _mb_split(xs, M)
        ys = _mb_split(ys, M)
        probe = embed_fn(eparams, xs[0])
        act_shape, act_dtype = probe.shape, probe.dtype

        f32tree = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, jnp.float32), t)
        zero_e, zero_b, zero_h = f32tree(eparams), f32tree(params), \
            f32tree(hparams)
        zero_act = jnp.zeros(act_shape, act_dtype)

        n_steps = M + 2 * P - 2

        def step(carry, t):
            (state_f, state_b, saved, ge, gb, gh, loss_acc) = carry
            # ---- forward: stage p handles microbatch m = t - p --------
            fwd_m = t - stage
            do_fwd = (fwd_m >= 0) & (fwd_m < M)

            def fwd_branch():
                h_in = lax.cond(stage == 0,
                                lambda: embed_fn(eparams, xs[t % M]),
                                lambda: state_f)
                return h_in, stage_fn(params, h_in)

            h_in, out = lax.cond(do_fwd, fwd_branch,
                                 lambda: (zero_act, zero_act))
            saved = lax.cond(do_fwd,
                             lambda: saved.at[fwd_m % DEPTH].set(h_in),
                             lambda: saved)
            state_f_new = lax.ppermute(out, axis, fwd_perm)

            # ---- backward: stage p backs m = t - 2(P-1) + p -----------
            bwd_m = t - 2 * (P - 1) + stage
            do_bwd = (bwd_m >= 0) & (bwd_m < M)

            def bwd_branch():
                h_saved = saved[bwd_m % DEPTH]

                def stage_loss(params_, eparams_, hparams_, h_in_):
                    h_in2 = lax.cond(
                        stage == 0,
                        lambda: embed_fn(eparams_, xs[bwd_m % M]),
                        lambda: h_in_)
                    out_ = stage_fn(params_, h_in2)
                    return lax.cond(
                        stage == P - 1,
                        lambda: head_loss_fn(
                            hparams_, out_,
                            ys[bwd_m % M]).astype(jnp.float32),
                        lambda: jnp.sum(
                            out_.astype(jnp.float32)
                            * state_b.astype(jnp.float32)))

                l, vjp = jax.vjp(stage_loss, params, eparams, hparams,
                                 h_saved)
                db, de, dh, dx = vjp(jnp.ones((), l.dtype))
                cast32 = lambda tr: jax.tree_util.tree_map(  # noqa: E731
                    lambda a: a.astype(jnp.float32), tr)
                return l, cast32(db), cast32(de), cast32(dh), \
                    dx.astype(act_dtype)

            def no_bwd():
                return (jnp.zeros((), jnp.float32), zero_b, zero_e,
                        zero_h, zero_act)

            l, db, de, dh, dx = lax.cond(do_bwd, bwd_branch, no_bwd)
            loss_acc = loss_acc + jnp.where(
                do_bwd & (stage == P - 1), l, 0.0)
            tadd = lambda a, b: jax.tree_util.tree_map(  # noqa: E731
                lambda p_, q_: p_ + q_, a, b)
            ge, gb, gh = tadd(ge, de), tadd(gb, db), tadd(gh, dh)
            state_b_new = lax.ppermute(dx, axis, bwd_perm)
            return (state_f_new, state_b_new, saved, ge, gb, gh,
                    loss_acc), None

        saved0 = jnp.zeros((DEPTH,) + act_shape, act_dtype)
        carry0 = (zero_act, zero_act, saved0, zero_e, zero_b, zero_h,
                  jnp.zeros((), jnp.float32))
        (_, _, _, ge, gb, gh, loss_sum), _ = lax.scan(
            step, carry0, jnp.arange(n_steps))
        loss = lax.psum(loss_sum, axis) / M
        ge = jax.tree_util.tree_map(lambda g: lax.psum(g, axis) / M, ge)
        gh = jax.tree_util.tree_map(lambda g: lax.psum(g, axis) / M, gh)
        gb = jax.tree_util.tree_map(lambda g: g[None] / M, gb)
        if dp:  # data parallelism: mean over the dp replicas
            loss = lax.pmean(loss, dp)
            ge = jax.tree_util.tree_map(lambda g: lax.pmean(g, dp), ge)
            gh = jax.tree_util.tree_map(lambda g: lax.pmean(g, dp), gh)
            gb = jax.tree_util.tree_map(lambda g: lax.pmean(g, dp), gb)
        return loss, ge, gb, gh

    dp = "dp" if "dp" in mesh.axis_names and mesh.shape["dp"] > 1 else None
    bspec = PartitionSpec(dp) if dp else PartitionSpec()
    fn = shard_map_compat(
        local, mesh=mesh,
        in_specs=(PartitionSpec(), PartitionSpec(axis), PartitionSpec(),
                  bspec, bspec),
        out_specs=(PartitionSpec(), PartitionSpec(),
                   PartitionSpec(axis), PartitionSpec()),
        check_rep=False)
    return fn(embed_params, stacked_params, head_params, x, y)


class PPTrainStep:
    """Pipeline-parallel fused training step: pipeline_grads (1F1B with
    recompute) or grad-of-pipeline_loss (GPipe) + the optimizer, compiled
    into ONE program over a pp(×dp) mesh — the pipeline counterpart of
    parallel.TrainStep (SURVEY.md §7.2 M8: "PP composes with the train
    step").

    Functional interface: the model is (embed_fn, stage_fn,
    head_loss_fn) over param pytrees (see models adapters / tests for
    extracting these from Gluon blocks). Parameters stay device-resident
    and donated; body params are sharded over "pp"; the batch shards
    over "dp" when the mesh has one.

    tied: optional list of (embed_path, head_path) leaf-key tuples whose
    gradients are summed and applied once to the EMBED copy, with the
    head copy mirrored (weight tying, e.g. GPT-2's lm head).
    """

    def __init__(self, embed_fn, stage_fn, head_loss_fn, embed_params,
                 stacked_params, head_params, optimizer, n_microbatches,
                 mesh=None, schedule="1f1b", tied=None):
        from .mesh import named_sharding
        self.mesh = mesh if mesh is not None else current_mesh()
        if self.mesh is None or AXIS_PP not in self.mesh.axis_names:
            raise MXNetError("PPTrainStep needs a mesh with a 'pp' axis")
        if schedule not in ("1f1b", "gpipe"):
            raise MXNetError(f"unknown schedule {schedule!r}")
        if not optimizer.fused_supported:
            raise MXNetError(
                f"{type(optimizer).__name__} has no functional path")
        self._fns = (embed_fn, stage_fn, head_loss_fn)
        self.optimizer = optimizer
        self.M = int(n_microbatches)
        self.schedule = schedule
        self.tied = list(tied or [])
        pp_spec = named_sharding(PartitionSpec(AXIS_PP), mesh=self.mesh)
        repl = named_sharding(PartitionSpec(), mesh=self.mesh)
        # own copies: the step DONATES its param buffers, and device_put
        # may alias the caller's arrays (same pattern as TrainStep)
        put = lambda a, s_: jax.device_put(jnp.copy(a), s_)  # noqa: E731
        self._eparams = jax.tree_util.tree_map(
            lambda a: put(a, repl), embed_params)
        self._bparams = jax.tree_util.tree_map(
            lambda a: put(a, pp_spec), stacked_params)
        self._hparams = jax.tree_util.tree_map(
            lambda a: put(a, repl), head_params)
        mkstate = lambda tree, spec: jax.tree_util.tree_map(  # noqa: E731
            lambda a: tuple(jax.device_put(s_, spec)
                            for s_ in optimizer.init_state_arrays_mp(a)),
            tree)
        self._estate = mkstate(embed_params, repl)
        self._bstate = mkstate(stacked_params, pp_spec)
        # tied head copies are MIRRORED from the embed master each step —
        # they carry no optimizer state and skip the (discarded) update
        self._tied_h = {h for _, h in self.tied}
        self._hstate = mkstate({k: v for k, v in head_params.items()
                                if k not in self._tied_h}, repl)
        self._t = jnp.zeros((), jnp.int32)
        self._jitted = None

    def _build(self):
        embed_fn, stage_fn, head_loss_fn = self._fns
        opt = self.optimizer
        mesh, M, schedule, tied = (self.mesh, self.M, self.schedule,
                                   self.tied)
        tied_h = self._tied_h

        def step_fn(eparams, bparams, hparams, estate, bstate, hstate,
                    t, lr, wd, x, y):
            t = t + 1
            if schedule == "1f1b":
                loss, ge, gb, gh = pipeline_grads(
                    embed_fn, stage_fn, head_loss_fn, eparams, bparams,
                    hparams, x, y, M, mesh=mesh)
            else:
                # gpipe: autodiff INSIDE the shard_map region (jax
                # cannot transpose through it — see
                # pipeline_loss_and_grads)
                loss, ge, gb, gh = pipeline_loss_and_grads(
                    embed_fn, stage_fn, head_loss_fn, eparams, bparams,
                    hparams, x, y, M, mesh=mesh)
            for e_key, h_key in tied:
                ge[e_key] = ge[e_key] + gh[h_key].astype(ge[e_key].dtype)
            gh = {k: v for k, v in gh.items() if k not in tied_h}
            h_mirror = {k: v for k, v in hparams.items() if k in tied_h}
            hparams = {k: v for k, v in hparams.items()
                       if k not in tied_h}

            def apply_tree(params, grads, states):
                leaves_p, treedef = jax.tree_util.tree_flatten(params)
                leaves_g = treedef.flatten_up_to(grads)
                leaves_s = treedef.flatten_up_to(states)
                new_p, new_s = [], []
                for p_, g_, s_ in zip(leaves_p, leaves_g, leaves_s):
                    np_, ns_ = opt.apply_arrays_mp(p_, g_,
                                                tuple(s_), lr, wd, t)
                    new_p.append(np_)
                    new_s.append(ns_)
                return (jax.tree_util.tree_unflatten(treedef, new_p),
                        jax.tree_util.tree_unflatten(treedef, new_s))

            eparams, estate = apply_tree(eparams, ge, estate)
            bparams, bstate = apply_tree(bparams, gb, bstate)
            hparams, hstate = apply_tree(hparams, gh, hstate)
            for e_key, h_key in tied:  # mirror the tied master copy
                hparams[h_key] = eparams[e_key].astype(
                    h_mirror[h_key].dtype)
            return (eparams, bparams, hparams, estate, bstate, hstate,
                    t, loss)

        return jax.jit(step_fn, donate_argnums=(0, 1, 2, 3, 4, 5, 6))

    def __call__(self, x, y):
        if self._jitted is None:
            self._jitted = self._build()
        lr = jnp.asarray(float(self.optimizer.learning_rate), jnp.float32)
        wd = jnp.asarray(float(self.optimizer.wd), jnp.float32)
        out = self._jitted(self._eparams, self._bparams, self._hparams,
                           self._estate, self._bstate, self._hstate,
                           self._t, lr, wd, jnp.asarray(x),
                           jnp.asarray(y))
        (self._eparams, self._bparams, self._hparams, self._estate,
         self._bstate, self._hstate, self._t, loss) = out
        return loss

    @property
    def params(self):
        return self._eparams, self._bparams, self._hparams

