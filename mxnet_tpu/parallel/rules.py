"""Sharding rules: pattern → PartitionSpec assignment over a Block's params.

Reference parity: none — the reference's only model parallelism is manual
group2ctx device assignment (SURVEY.md §2.4 'Model parallelism (manual)').
The TPU-native replacement: declarative regex rules mapping parameter paths
to PartitionSpecs, applied once; XLA's SPMD partitioner does the rest. This
is how tp/fsdp/ep sharding attaches to existing Gluon models with no model
code changes.
"""
from __future__ import annotations

import re

from ..base import MXNetError
from .mesh import PartitionSpec

__all__ = ["ShardingRules", "apply_sharding_rules", "megatron_dense_rules",
           "serving_tp_rules", "fsdp_rules", "ep_rules",
           "COL_WEIGHT_PATTERN", "ROW_WEIGHT_PATTERN", "megatron_kind"]

# The megatron column/row weight classifiers, exported so consumers that
# need to KNOW the split (not just apply a spec) share one source of
# truth — the serving w8 weight quantizer keys its scale layout off this
# (column-parallel: per-out-tile scales sharded with the out dim;
# row-parallel: shard-invariant per-out-tile scales applied before the
# psum).
COL_WEIGHT_PATTERN = (r"(query|key|value|qkv|attn_in|ffn?_?1|intermediate"
                      r"|fc1)\.weight$")
ROW_WEIGHT_PATTERN = (r"(proj|attn_out|out_proj|ffn?_?2|output|fc2)"
                      r"\.weight$")
_COL_WEIGHT_RE = re.compile(COL_WEIGHT_PATTERN)
_ROW_WEIGHT_RE = re.compile(ROW_WEIGHT_PATTERN)


def megatron_kind(name):
    """'col' / 'row' / None for a parameter path under the megatron dense
    split (first-match-wins, column checked first like the rules)."""
    if _COL_WEIGHT_RE.search(name):
        return "col"
    if _ROW_WEIGHT_RE.search(name):
        return "row"
    return None


class ShardingRules:
    """Ordered (regex, PartitionSpec) list; first match wins."""

    def __init__(self, rules=None, default=None):
        self.rules = [(re.compile(p), spec) for p, spec in (rules or [])]
        self.default = default  # None = replicated

    def add(self, pattern, spec):
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name, shape=None):
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return self.default

    def __iter__(self):
        return iter(self.rules)


def apply_sharding_rules(net_or_params, rules):
    """Set `param.sharding` for every matching parameter.

    net_or_params: a Block or a ParameterDict. Validates that sharded dims
    exist in the param's shape (a spec longer than the rank is an error)."""
    params = net_or_params
    if hasattr(params, "collect_params"):
        params = params.collect_params()
    for name, p in params.items():
        spec = rules.spec_for(name, p.shape)
        if spec is None:
            continue
        if p.shape is not None and len(spec) > len(p.shape):
            raise MXNetError(
                f"sharding spec {spec} longer than rank of {name} "
                f"{p.shape}")
        p.sharding = spec
    return params


def megatron_dense_rules(tp_axis="tp", fsdp_axis=None):
    """Megatron-style tensor parallelism for transformer blocks built from
    Dense layers: column-parallel QKV/FFN-in (out-dim sharded), row-parallel
    proj/FFN-out (in-dim sharded). Dense weights here are (out, in) —
    reference FullyConnected convention.

    Combined with fsdp_axis, remaining dims shard ZeRO-style."""
    col = PartitionSpec(tp_axis, fsdp_axis)
    row = PartitionSpec(fsdp_axis, tp_axis)
    rules = ShardingRules()
    # attention QKV + first FFN layer: column parallel
    rules.add(COL_WEIGHT_PATTERN, col)
    # attention out-proj + second FFN layer: row parallel
    rules.add(ROW_WEIGHT_PATTERN, row)
    # column-parallel biases follow the out dim
    rules.add(r"(query|key|value|qkv|attn_in|ffn?_?1|intermediate|fc1)"
              r"\.bias$", PartitionSpec(tp_axis))
    # embeddings: shard vocab dim over tp
    rules.add(r"embed\w*\.weight$", PartitionSpec(tp_axis, fsdp_axis))
    if fsdp_axis is not None:
        rules.default = None  # leave rest replicated; fsdp via explicit specs
    return rules


def serving_tp_rules(tp_axis="tp"):
    """Head-wise tensor parallelism for the serving lane.

    The megatron column/row split for qkv + fc1 (out-dim sharded) and
    proj + fc2 (in-dim sharded), with two serving-specific overrides
    layered on top via first-match-wins ordering:

    - embeddings (and the tied LM head) stay REPLICATED: the serving
      dispatch samples in-program from full logits on every shard, so a
      vocab-sharded embed would cost an extra all-gather per step for a
      parameter that is small next to the KV pool.
    - everything unmatched (LayerNorm scales/offsets, row-parallel
      biases) is replicated — the row-parallel bias is added ONCE after
      the psum, not per shard.
    """
    rules = ShardingRules()
    rules.add(r"embed\w*\.weight$", PartitionSpec())
    for pat, spec in megatron_dense_rules(tp_axis):
        rules.rules.append((pat, spec))
    return rules


def ep_rules(ep_axis="ep"):
    """Expert parallelism: MoEFFN's stacked expert weights (leading dim =
    expert index, gluon/nn/moe.py naming `expert_*`) shard dim 0 over
    `ep_axis`; XLA partitions the expert einsums and inserts the
    dispatch/combine collectives (SURVEY.md §2.4 presence matrix: EP)."""
    rules = ShardingRules()
    rules.add(r"expert_\w+$", PartitionSpec(ep_axis))
    return rules


def fsdp_rules(fsdp_axis="fsdp", min_size=1024):
    """ZeRO-3-style fully-sharded data parallelism: every parameter's
    LARGEST dim shards over `fsdp_axis`; XLA's SPMD partitioner inserts the
    all-gather before use and reduce-scatters the gradients (the TPU-native
    equivalent of the reference-absent ZeRO sharded optimizer, SURVEY.md
    §2.4 presence matrix).

    min_size: parameters with fewer elements stay replicated (tiny biases/
    norms cost more in collective latency than they save in HBM).
    Shape-aware, so it is implemented as a ShardingRules subclass whose
    spec_for consults the parameter shape."""

    class _FsdpRules(ShardingRules):
        def spec_for(self, name, shape=None):
            # explicit rules (added by the caller) take precedence
            spec = super().spec_for(name, shape)
            if spec is not None:
                return spec
            if shape is None or not shape or any(d == 0 for d in shape):
                return None
            n = 1
            for d in shape:
                n *= d
            if n < min_size:
                return None
            big = max(range(len(shape)), key=lambda i: shape[i])
            parts = [None] * len(shape)
            parts[big] = fsdp_axis
            return PartitionSpec(*parts)

    return _FsdpRules()
