"""Sequence/context parallelism: ring attention over the mesh's "sp" axis.

Reference parity: none — SURVEY.md §5.7 records that the reference has no
sequence-dimension sharding of any kind; the task brief makes it
first-class here. Design: the (B, H, T, D) attention operands enter
sharded along T over "sp"; a shard_map runs ops.attention.
ring_attention_data per shard, rotating KV (and the key-padding mask)
around the ring with lax.ppermute while accumulating online-softmax
statistics — O(T_local) memory per device and pure ICI traffic, composing
under an outer pjit with dp/tp axes.
"""
from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..ops.attention import ring_attention_data
from .mesh import AXIS_SP, axis_enabled, current_mesh, shard_map_compat

__all__ = ["ring_attention", "ulysses_attention", "sp_enabled"]


def sp_enabled(mesh=None, sp_axis=AXIS_SP):
    """True iff an active mesh has a real (size > 1) sp axis."""
    return axis_enabled(mesh, sp_axis)



def _sp_operands(q, k, v, mask, mesh, sp_axis, batch_axis, heads_axis,
                 kind):
    """Shared validation + spec/arg assembly for the SP attention paths.

    Returns (n_sp, ba, ha, qspec, in_specs, args) — args has the
    canonical (B, Tk) mask appended when one was given."""
    if mesh is None or sp_axis not in mesh.axis_names:
        raise MXNetError(
            f"{kind} attention needs an active mesh with a {sp_axis!r} "
            "axis (make_mesh(sp=...) + mesh_scope/set_default_mesh)")
    n_sp = mesh.shape[sp_axis]
    B, H, T, D = q.shape
    if T % n_sp or k.shape[-2] % n_sp:
        raise MXNetError(
            f"sequence length {T}/{k.shape[-2]} not divisible by sp axis "
            f"size {n_sp}")
    ba = batch_axis if batch_axis in mesh.axis_names else None
    ha = heads_axis if heads_axis in mesh.axis_names else None
    qspec = P(ba, ha, sp_axis, None)
    in_specs = [qspec, qspec, qspec]
    args = [q, k, v]
    if mask is not None:
        import jax.numpy as jnp
        mask2 = mask.reshape(mask.shape[0], mask.shape[-1])
        if mask2.shape[0] != B:  # broadcastable (1, Tk) masks
            mask2 = jnp.broadcast_to(mask2, (B, mask2.shape[-1]))
        in_specs.append(P(ba, sp_axis))
        args.append(mask2)
    return n_sp, ba, ha, qspec, in_specs, args


def ring_attention(q, k, v, mask=None, causal=False, scale=None, mesh=None,
                   sp_axis=AXIS_SP, batch_axis="dp", heads_axis="tp"):
    """Sequence-parallel attention on (B, H, T, D) jax arrays.

    The sequence dim shards over `sp_axis`; batch shards over `batch_axis`
    and heads over `heads_axis` when those axes exist in the mesh (matching
    the activation layout megatron_dense_rules produces, so no resharding
    is inserted around the shard_map). mask: optional key-padding mask,
    (B, Tk) or (B, 1, 1, Tk), True = attend.
    """
    mesh = mesh if mesh is not None else current_mesh()
    n_sp, ba, ha, qspec, in_specs, args = _sp_operands(
        q, k, v, mask, mesh, sp_axis, batch_axis, heads_axis, "ring")
    if mask is not None:
        def local(qb, kb, vb, mb):
            return ring_attention_data(qb, kb, vb, sp_axis, causal=causal,
                                       scale=scale, mask=mb)
    else:
        def local(qb, kb, vb):
            return ring_attention_data(qb, kb, vb, sp_axis, causal=causal,
                                       scale=scale)

    fn = shard_map_compat(local, mesh=mesh, in_specs=tuple(in_specs),
                          out_specs=qspec, check_rep=False)
    return fn(*args)


def ulysses_attention(q, k, v, mask=None, causal=False, scale=None,
                      mesh=None, sp_axis=AXIS_SP, batch_axis="dp",
                      heads_axis="tp"):
    """Ulysses-style sequence parallelism (DeepSpeed-Ulysses; SURVEY.md
    §5.7's 'attention-head all-to-all' alternative to the ring).

    Operands enter sharded along T over `sp_axis` exactly like
    ring_attention (batch over `batch_axis`, heads over `heads_axis`
    when those mesh axes exist — the megatron activation layout); inside
    the shard_map an all-to-all re-shards them HEAD-wise (each sp device
    gets local_H/n_sp heads with the FULL sequence), plain full
    attention runs locally, and a second all-to-all restores the
    T-sharded layout. Two collectives total per call vs the ring's
    n_sp ppermutes — the better trade for moderate context where the
    full (T, T) score matrix still fits; the ring remains the
    O(T_local)-memory choice for very long T. The per-device head count
    (H, or H/tp under tensor parallelism) must divide by the sp size.
    """
    from jax import lax

    from ..ops import nn as _opnn

    mesh = mesh if mesh is not None else current_mesh()
    n_sp, ba, ha, qspec, in_specs, args = _sp_operands(
        q, k, v, mask, mesh, sp_axis, batch_axis, heads_axis, "ulysses")
    H = q.shape[1]
    n_ha = mesh.shape[ha] if ha is not None else 1
    if H % n_ha or (H // n_ha) % n_sp:
        raise MXNetError(
            f"ulysses needs per-device heads {H}/{n_ha} divisible by sp "
            f"axis size {n_sp}; use ring_attention otherwise")

    def local(*xs):
        if mask is not None:
            qb, kb, vb, mb = xs
        else:
            qb, kb, vb = xs
            mb = None
        # (B, H_local, T/n, D) → all-to-all → (B, H_local/n, T, D):
        # scatter heads (axis 1), gather sequence (axis 2)
        def a2a_fwd(x):
            return lax.all_to_all(x, sp_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def a2a_bwd(x):
            return lax.all_to_all(x, sp_axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qf, kf, vf = a2a_fwd(qb), a2a_fwd(kb), a2a_fwd(vb)
        full_mask = None
        if mb is not None:
            # key mask is T-sharded; every device needs the full T
            full_mask = lax.all_gather(mb, sp_axis, axis=1,
                                       tiled=True)[:, None, None, :]
        out = _opnn.dot_product_attention.raw_fn(
            qf, kf, vf, mask=full_mask, causal=causal, scale=scale,
            impl="xla")
        return a2a_bwd(out)

    fn = shard_map_compat(local, mesh=mesh, in_specs=tuple(in_specs),
                          out_specs=qspec, check_rep=False)
    return fn(*args)
