"""Sequence/context parallelism: ring attention over the mesh's "sp" axis.

Reference parity: none — SURVEY.md §5.7 records that the reference has no
sequence-dimension sharding of any kind; the task brief makes it
first-class here. Design: the (B, H, T, D) attention operands enter
sharded along T over "sp"; a shard_map runs ops.attention.
ring_attention_data per shard, rotating KV (and the key-padding mask)
around the ring with lax.ppermute while accumulating online-softmax
statistics — O(T_local) memory per device and pure ICI traffic, composing
under an outer pjit with dp/tp axes.
"""
from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..ops.attention import ring_attention_data
from .mesh import AXIS_SP, current_mesh, shard_map_compat

__all__ = ["ring_attention", "sp_enabled"]


def sp_enabled(mesh=None, sp_axis=AXIS_SP):
    """True iff an active mesh has a real (size > 1) sp axis."""
    mesh = mesh if mesh is not None else current_mesh()
    return (mesh is not None and sp_axis in mesh.axis_names
            and mesh.shape[sp_axis] > 1)


def ring_attention(q, k, v, mask=None, causal=False, scale=None, mesh=None,
                   sp_axis=AXIS_SP, batch_axis="dp", heads_axis="tp"):
    """Sequence-parallel attention on (B, H, T, D) jax arrays.

    The sequence dim shards over `sp_axis`; batch shards over `batch_axis`
    and heads over `heads_axis` when those axes exist in the mesh (matching
    the activation layout megatron_dense_rules produces, so no resharding
    is inserted around the shard_map). mask: optional key-padding mask,
    (B, Tk) or (B, 1, 1, Tk), True = attend.
    """
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or sp_axis not in mesh.axis_names:
        raise MXNetError(
            f"ring attention needs an active mesh with a {sp_axis!r} axis "
            "(make_mesh(sp=...) + mesh_scope/set_default_mesh)")
    n_sp = mesh.shape[sp_axis]
    B, H, T, D = q.shape
    if T % n_sp or k.shape[-2] % n_sp:
        raise MXNetError(
            f"sequence length {T}/{k.shape[-2]} not divisible by sp axis "
            f"size {n_sp}")
    ba = batch_axis if batch_axis in mesh.axis_names else None
    ha = heads_axis if heads_axis in mesh.axis_names else None
    qspec = P(ba, ha, sp_axis, None)
    in_specs = [qspec, qspec, qspec]
    args = [q, k, v]
    if mask is not None:
        mask2 = mask.reshape(mask.shape[0], mask.shape[-1])
        if mask2.shape[0] != B:  # broadcastable (1, Tk) masks
            import jax.numpy as jnp
            mask2 = jnp.broadcast_to(mask2, (B, mask2.shape[-1]))
        in_specs.append(P(ba, sp_axis))
        args.append(mask2)

        def local(qb, kb, vb, mb):
            return ring_attention_data(qb, kb, vb, sp_axis, causal=causal,
                                       scale=scale, mask=mb)
    else:
        def local(qb, kb, vb):
            return ring_attention_data(qb, kb, vb, sp_axis, causal=causal,
                                       scale=scale)

    fn = shard_map_compat(local, mesh=mesh, in_specs=tuple(in_specs),
                          out_specs=qspec, check_rep=False)
    return fn(*args)
