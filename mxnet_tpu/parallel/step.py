"""Fused, sharded training step.

This is the TPU-native performance path (SURVEY.md §7.2 M6/M7): where the
reference runs forward (CachedOp) → backward (engine) → kvstore pushpull →
per-weight optimizer kernels as thousands of engine ops, here the WHOLE
training step — forward, loss, backward, gradient reduction, optimizer —
compiles into ONE XLA program over the device mesh:

  * parameters/optimizer states enter sharded per their PartitionSpec and
    are donated (buffer reuse = the reference's in-place engine updates);
  * the batch enters sharded over the "dp"/"fsdp" (+"sp") axes; gradient
    all-reduce is NOT written anywhere — XLA inserts the collectives that
    the sharding math requires (psum over dp for replicated params,
    reduce-scatter for fsdp-sharded params), executing them on ICI;
  * comm/compute overlap (the reference's priority-scheduled kvstore
    pushes, SURVEY.md §3.2c) falls out of XLA's latency-hiding scheduler.

Gluon semantics preserved: works on any initialized (Hybrid)Block, the
loss is a gluon loss block, BatchNorm running stats update through the
trace side-channel, dropout draws from a per-step key.
"""
from __future__ import annotations

import itertools
import time
from contextlib import nullcontext as _nullcontext

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd, rng as _rng
from ..base import MXNetError
from ..gluon.block import _trace_channel
from ..ndarray.ndarray import NDArray
from ..telemetry import cost as _cost
from ..telemetry import ledger as _ledger
from .mesh import PartitionSpec, current_mesh, mesh_scope, named_sharding

__all__ = ["TrainStep", "EvalStep"]


def _spec_or_replicated(spec):
    return spec if spec is not None else PartitionSpec()


def _mesh_ctx(mesh):
    """Scope for trace-inducing calls: ops (attention impl='auto') consult
    current_mesh() during tracing to pick sharded routes."""
    return mesh_scope(mesh) if mesh is not None else _nullcontext()


_step_ids = itertools.count()


class TrainStep:
    """Compile net+loss+optimizer into one sharded step program.

    Usage:
        step = TrainStep(net, loss_fn, optimizer, mesh=mesh,
                         batch_specs=(P("dp"), P("dp")))
        loss = step(data, label)          # one fused device step
        step.sync_params()                # reflect weights into the Block
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, batch_specs=None,
                 donate=True, loss_reduce="mean", n_net_inputs=1,
                 loss_scale=None, scale_window=2000, compression=None,
                 compression_threshold=0.5):
        """loss_scale: None (bf16/f32 path), a float (static scaling), or
        'dynamic' — fp16-style dynamic loss scaling run ENTIRELY inside
        the compiled step: the loss is scaled before backward, gradients
        unscaled before the optimizer, non-finite gradients skip the
        update via jnp.where, and the scale halves on overflow / doubles
        after scale_window clean steps — zero host synchronization (the
        reference's LossScaler pays a device→host check per step).

        compression='2bit': gradient reduction over the "dp" axis runs
        through the reference's 2-bit wire (quantize → all_gather of
        packed uint32 at 1/16 the f32 bytes → dequantize+sum) INSIDE the
        compiled step, with per-device error-feedback residuals in the
        step carry (donated like optimizer state) — the in-program
        successor of src/kvstore/gradient_compression.cc
        (parallel/compression.py; SURVEY §5.8 EQuARX analog). Requires a
        mesh whose only model sharding is dp replication (pure data
        parallelism) and makes BatchNorm statistics per-device (pmean'd
        into the carried moving stats — the reference's dist-kvstore BN
        behaves the same way)."""
        self.net = net
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else current_mesh()
        self.batch_specs = batch_specs
        self.donate = donate
        self.loss_reduce = loss_reduce
        self.n_net_inputs = n_net_inputs  # batch[:n] → net, batch[n:] → loss
        self._dynamic_scale = loss_scale == "dynamic"
        self._static_scale = (float(loss_scale)
                              if loss_scale not in (None, "dynamic")
                              else None)
        self._scale_window = int(scale_window)
        if compression not in (None, "2bit"):
            raise MXNetError(f"unknown compression {compression!r}")
        self._compression = compression
        self._compression_threshold = float(compression_threshold)
        if compression is not None:
            if self.mesh is None or "dp" not in self.mesh.axis_names \
                    or self.mesh.shape["dp"] < 2:
                raise MXNetError(
                    "compression='2bit' needs a mesh with a dp axis of "
                    "size >= 2 (it compresses the dp gradient exchange)")
            if any(ax != "dp" and n > 1
                   for ax, n in self.mesh.shape.items()):
                raise MXNetError(
                    "compression='2bit' supports pure data parallelism "
                    "(params replicated); drop tp/sp/pp/fsdp axes")
            if loss_reduce != "mean":
                raise MXNetError(
                    "compression='2bit' requires loss_reduce='mean' "
                    "(the compressed collective mean-reduces over dp)")
        if not optimizer.fused_supported:
            raise MXNetError(
                f"{type(optimizer).__name__} has no functional path for the "
                "fused step; use SGD/Adam/AdamW/LAMB or the eager Trainer")
        params = net.collect_params()
        self._params = [p for p in params.values()]
        self._trainable = [p.grad_req != "null" for p in self._params]
        # per-parameter lr/wd multipliers are static. Parity with the eager
        # Trainer: it sets optimizer.param_dict, so _get_lr/_get_wd use the
        # Parameter's own lr_mult/wd_mult and never consult the name-keyed
        # set_lr_mult/set_wd_mult dicts — mirror exactly that.
        self._lr_mults = [p.lr_mult for p in self._params]
        self._wd_mults = [p.wd_mult for p in self._params]
        for p in self._params:
            if p._data is None:
                raise MXNetError(
                    f"parameter {p.name} not initialized; run one forward "
                    "or set shapes before building TrainStep")
        # own copies: step buffers are DONATED to XLA each call, and the
        # source NDArrays may be aliased elsewhere (donating a shared
        # buffer would delete it under the other holder's feet)
        self._param_arrays = [jnp.copy(p.data()._data)
                              for p in self._params]
        self._opt_states = tuple(
            optimizer.init_state_arrays_mp(a) if tr else ()
            for a, tr in zip(self._param_arrays, self._trainable))
        self._t = jnp.zeros((), jnp.int32)
        # per-device error-feedback residuals (leading dp axis, sharded)
        self._residuals = ()
        if self._compression is not None:
            n_dp = self.mesh.shape["dp"]
            with mesh_scope(self.mesh):
                rspec = named_sharding(PartitionSpec("dp"))
                self._residuals = tuple(
                    jax.device_put(
                        jnp.zeros((n_dp,) + a.shape, jnp.float32), rspec)
                    for a, tr in zip(self._param_arrays, self._trainable)
                    if tr)
        # dynamic loss-scaler state lives ON DEVICE in the step carry
        self._scale_state = (jnp.asarray(2.0 ** 16, jnp.float32),
                             jnp.zeros((), jnp.int32)) \
            if self._dynamic_scale else None
        self._host_t = 0
        self._base_key = None
        self._lr_cache = None
        self._wd_cache = None
        # program cache keyed on the batch signature (shapes, dtypes,
        # arity) — the BucketingModule story (SURVEY.md §3.3): each padded
        # bucket size gets its own compiled program, parameters shared
        self._programs = {}
        self._last_sig = None
        self._last_single_sig = None
        self._meta = {}
        # device-cost + HBM-ledger integration (docs/OBSERVABILITY.md):
        # per-dispatch wall attribution is always on (cheap);
        # register_cost_analysis() adds the XLA FLOP/byte figures (it
        # re-traces, so it is an explicit call, not a hot-path default)
        self._cost_key = f"train_step{next(_step_ids)}"
        _ledger.register(self._cost_key, self._hbm_ledger)
        if self.mesh is not None:
            self._place_sharded()

    def _hbm_ledger(self):
        """telemetry.ledger provider: the step's donated device state —
        its own parameter copies, optimizer state, compression
        residuals (ledger dedupes anything shared elsewhere)."""
        return {
            "params": list(self._param_arrays),
            "optimizer_state": list(
                jax.tree_util.tree_leaves(self._opt_states)),
            "residuals": list(self._residuals),
        }

    def register_cost_analysis(self, sig=None):
        """Register the compiled step's XLA cost analysis with
        telemetry.cost (keyed `<cost_key>/step` or `/run_steps`), so
        the dispatch walls already being attributed turn into live MFU
        and roofline gauges. Re-traces the program once — call it from
        a bench/startup path, not per step. Returns the cost record or
        None when the backend reports no costs."""
        if sig is None:
            sig = self._last_single_sig or self._last_sig
        ca = self.compiled_cost_analysis(sig=sig)
        if not ca:
            return None
        d = dict(ca)
        multi = isinstance(sig, tuple) and sig and sig[0] == "multi"
        program = self._cost_key + ("/run_steps" if multi else "/step")
        flops, nbytes = d.get("flops"), d.get("bytes accessed")
        if multi:
            # compiled_cost_analysis normalizes a K-chained program to
            # per-step figures; the program record costs ONE DISPATCH,
            # so scale back up to the K-step total
            k = sig[2] if sig[2] is not None else sig[3][0][0]
            flops = flops * k if flops else flops
            nbytes = nbytes * k if nbytes else nbytes
        return _cost.register_program(program, flops, nbytes)

    # -- sharding placement ------------------------------------------------
    def _place_sharded(self):
        with mesh_scope(self.mesh):
            placed = []
            for p, a in zip(self._params, self._param_arrays):
                s = named_sharding(_spec_or_replicated(p.sharding))
                placed.append(jax.device_put(a, s))
            self._param_arrays = placed
            self._opt_states = tuple(
                tuple(jax.device_put(
                    s, named_sharding(_spec_or_replicated(p.sharding)))
                    for s in states)
                for p, states in zip(self._params, self._opt_states))

    def param_sharding_specs(self):
        return [_spec_or_replicated(p.sharding) for p in self._params]

    # -- build -------------------------------------------------------------
    def _make_core(self, n_batch):
        """The one-training-step function shared by the per-call program
        and the device-chained multi-step program:
        core(tr, opt, t, scale_state, nt, resid, key, lr, wd, batch) ->
        (new_tr, new_opt, t, new_scale, new_resid, loss, aux)."""
        net, loss_fn, opt = self.net, self.loss_fn, self.optimizer
        params = self._params
        trainable = self._trainable
        reduce = self.loss_reduce
        meta = self._meta

        def forward_loss(param_datas, batch_datas, key):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_datas):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                args = [NDArray(d) for d in batch_datas]
                n_net_in = self.n_net_inputs
                with autograd._Scope(False, True), _rng.key_scope(key):
                    out = net.forward(*args[:n_net_in])
                    outs = out if isinstance(out, tuple) else (out,)
                    loss = loss_fn(*outs, *args[n_net_in:])
            finally:
                updates = _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            meta["state_updates"] = updates
            ldata = loss._data if isinstance(loss, NDArray) else loss
            if reduce == "mean":
                ldata = jnp.mean(ldata)
            elif reduce == "sum":
                ldata = jnp.sum(ldata)
            aux = tuple(u for _, u in updates)
            return ldata.astype(jnp.float32), aux

        dynamic = self._dynamic_scale
        static_scale = self._static_scale
        scale_window = self._scale_window

        # trainable params are DONATED (buffer reuse on the hot path);
        # non-trainable params (BN running stats, frozen weights) ride in
        # a separate NON-donated argument, so the returned stat updates
        # are contract-fresh buffers the Parameters can own directly — no
        # per-stat copy dispatches (106/step on ResNet-50, ruinous over a
        # remote tunnel) and no reliance on XLA preserving in-program
        # copies of equal values as distinct output buffers
        nt_pos = {}  # full-list index -> position in the nt tuple
        tr_pos = {}  # full-list index -> position in the tr tuple
        for i, tr in enumerate(trainable):
            if tr:
                tr_pos[i] = len(tr_pos)
            else:
                nt_pos[i] = len(nt_pos)
        tr_lr_mults = [m for m, tr in zip(self._lr_mults, trainable) if tr]
        tr_wd_mults = [m for m, tr in zip(self._wd_mults, trainable) if tr]

        self._nt_pos, self._tr_pos = nt_pos, tr_pos

        compression = self._compression
        comp_thr = self._compression_threshold

        def core(tr_datas, opt_states, t, scale_state, nt_datas, resid,
                 base_key, lr, wd, batch_datas):
            t = t + 1
            # per-step randomness derived INSIDE the program (no host RNG
            # round-trip per step; the reference's engine-managed Philox
            # streams achieve the same "no host in the loop" property)
            key = jax.random.fold_in(base_key, t)
            if compression is not None:
                # per-device dropout streams under the dp shard_map
                key = jax.random.fold_in(key, lax.axis_index("dp"))
            if dynamic:
                scale, good = scale_state
            elif static_scale is not None:
                scale, good = jnp.asarray(static_scale, jnp.float32), None
            else:
                scale, good = None, None

            def assemble(tr_tuple):
                full, it_tr, it_nt = [], iter(tr_tuple), iter(nt_datas)
                for tr in trainable:
                    full.append(next(it_tr) if tr else next(it_nt))
                return tuple(full)

            def loss_of(trainable_params):
                ldata, aux = forward_loss(assemble(trainable_params),
                                          batch_datas, key)
                if scale is not None:  # fp16 path: backward on scaled loss
                    return ldata * scale, (ldata, aux)
                return ldata, (ldata, aux)

            (_, (loss, aux)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(tr_datas)
            if scale is not None:
                inv = 1.0 / scale
                grads = tuple(
                    (g.astype(jnp.float32) * inv).astype(g.dtype)
                    for g in grads)
            ok = None
            if dynamic:
                # overflow detection runs on the RAW local grads: after
                # 2-bit quantization NaN/Inf would vanish (they compare
                # False against both thresholds → code 0) and the
                # overflow would both apply and poison the residual
                ok = jnp.asarray(True)
                for g in grads:
                    ok = ok & jnp.isfinite(g.astype(jnp.float32)).all()
                if compression is not None:
                    ok = lax.pmin(ok.astype(jnp.int32), "dp") > 0
            if compression is not None:
                # the dp gradient exchange through the 2-bit wire; the
                # reduced grads come back identical on every device
                from .compression import compressed_psum_mean
                red, new_resid = [], []
                for g, r in zip(grads, resid):
                    rg, nr = compressed_psum_mean(g, r[0], "dp",
                                                  comp_thr)
                    if ok is not None:  # overflow: residual keeps its
                        nr = jnp.where(ok, nr, r[0])  # pre-step value
                    red.append(rg.astype(g.dtype))
                    new_resid.append(nr[None])
                grads = tuple(red)
                new_resid = tuple(new_resid)
                loss = lax.pmean(loss, "dp")
                aux = tuple(lax.pmean(a.astype(jnp.float32), "dp")
                            .astype(a.dtype) for a in aux)
            else:
                new_resid = resid
            if dynamic:
                # `ok` was computed from the RAW grads above
                # an overflow step must not poison mutable layer state
                # either (BN running stats from the same corrupted
                # forward): keep each stat's incoming value
                if aux:
                    olds = []
                    for sp_param, _ in meta["state_updates"]:
                        idx = next(i for i, pp in enumerate(params)
                                   if pp is sp_param)
                        # state updates usually target non-trainable
                        # params (BN stats), but push_state_update is an
                        # open extension point — a trainable target lives
                        # in the tr tuple instead
                        olds.append(nt_datas[nt_pos[idx]]
                                    if idx in nt_pos
                                    else tr_datas[tr_pos[idx]])
                    aux = tuple(jnp.where(ok, a, o.astype(a.dtype))
                                for a, o in zip(aux, olds))

            new_params, new_states = [], []
            git = iter(grads)
            for d, st, mlr, mwd in zip(tr_datas, opt_states, tr_lr_mults,
                                       tr_wd_mults):
                g = next(git)
                plr = lr * mlr if mlr != 1.0 else lr
                pwd = wd * mwd if mwd != 1.0 else wd
                nw, ns = opt.apply_arrays_mp(d, g, st, plr, pwd, t)
                if dynamic:
                    # overflow: keep the old weights/states (skip update)
                    nw = jnp.where(ok, nw, d)
                    ns = tuple(jnp.where(ok, n, o)
                               for n, o in zip(ns, st))
                new_params.append(nw)
                new_states.append(ns)
            if dynamic:
                # in-program dynamic adjustment (reference LossScaler
                # semantics, zero host syncs)
                good = jnp.where(ok, good + 1, 0)
                grow = good >= scale_window
                # growth capped at 2^24 so a perpetually-clean run can
                # never double the scale into f32 inf (which would wedge
                # training with every update skipped)
                scale = jnp.where(
                    ok, jnp.where(grow, jnp.minimum(scale * 2.0, 2.0 ** 24),
                                  scale),
                    jnp.maximum(scale * 0.5, 1.0))
                good = jnp.where(grow, 0, good)
                new_scale_state = (scale, good)
            else:
                new_scale_state = scale_state
            return (tuple(new_params), tuple(new_states), t,
                    new_scale_state, new_resid, loss, aux)

        if compression is None:
            return core
        # compressed path: the whole step runs SPMD inside a shard_map
        # over "dp" — params/states replicated (P() prefix specs), batch
        # and residuals sharded — so the dp gradient exchange is OUR
        # 2-bit collective, not XLA's f32 psum
        from .mesh import shard_map_compat
        repl = PartitionSpec()
        dp = PartitionSpec("dp")
        bspecs = tuple(self.batch_specs or [dp] * n_batch)

        def global_core(tr_datas, opt_states, t, scale_state, nt_datas,
                        resid, base_key, lr, wd, batch_datas):
            wrapped = shard_map_compat(
                core, mesh=self.mesh,
                in_specs=(repl, repl, repl, repl, repl, dp, repl, repl,
                          repl, bspecs),
                out_specs=(repl, repl, repl, repl, dp, repl, repl),
                check_rep=False)
            return wrapped(tr_datas, opt_states, t, scale_state,
                           nt_datas, resid, base_key, lr, wd,
                           batch_datas)

        return global_core

    def _jit_shardings(self, n_batch, stacked=False):
        """(in_shardings tuple, or None when no mesh) for the step args
        (tr, opt_states, t, scale_state, nt, key, lr, wd, *batch).
        stacked=True prepends an unsharded leading steps axis to each
        batch spec (the run_steps layout)."""
        if self.mesh is None:
            return None
        trainable = self._trainable
        with mesh_scope(self.mesh):
            pspecs = [named_sharding(s)
                      for s in self.param_sharding_specs()]
            tr_pspecs = tuple(s for s, tr in zip(pspecs, trainable) if tr)
            nt_pspecs = tuple(s for s, tr in zip(pspecs, trainable)
                              if not tr)
            sspecs = tuple(
                tuple(pspecs[i] for _ in st)
                for i, st in enumerate(self._opt_states)
                if trainable[i])
            repl = named_sharding(PartitionSpec())
            raw_bspecs = (self.batch_specs or
                          [PartitionSpec("dp")] * n_batch)
            if stacked:
                raw_bspecs = [PartitionSpec(None, *tuple(s))
                              for s in raw_bspecs]
            bspecs = tuple(named_sharding(s) for s in raw_bspecs)
            sscale = jax.tree_util.tree_map(
                lambda _: repl, self._scale_state) \
                if self._scale_state is not None else ()
            rspecs = tuple(named_sharding(PartitionSpec("dp"))
                           for _ in self._residuals)
            return (tr_pspecs, sspecs, repl, sscale,
                    nt_pspecs, rspecs, repl, repl, repl) + bspecs

    def _build(self, n_batch):
        core = self._make_core(n_batch)

        def step_fn(tr_datas, opt_states, t, scale_state, nt_datas,
                    resid, base_key, lr, wd, *batch_datas):
            return core(tr_datas, opt_states, t, scale_state, nt_datas,
                        resid, base_key, lr, wd, batch_datas)

        donate = (0, 1, 2, 5) if self.donate else ()
        shardings = self._jit_shardings(n_batch)
        if shardings is not None:
            with mesh_scope(self.mesh):
                jitted = jax.jit(step_fn, in_shardings=shardings,
                                 donate_argnums=donate)
        else:
            jitted = jax.jit(step_fn, donate_argnums=donate)
        return jitted

    def _build_multi(self, n_batch, repeat_steps=None):
        """Device-chained multi-step program: lax.scan over K stacked
        batches (or the SAME batch repeat_steps times when repeat_steps
        is set), ONE dispatch for K optimizer steps. The TPU-native
        analog of the reference's engine bulk mode (MXNET_ENGINE_BULK /
        engine.bulk batching many engine ops per scheduling round,
        SURVEY.md §2.1): host dispatch cost is paid once per K steps
        instead of per step, which matters when the host link has
        latency (remote TPU) or the per-step pytree is large.

        Mutable layer state (BN stats) is threaded through the scan
        carry, so K chained steps accumulate stats exactly like K
        single-step calls. lr/wd are captured once per dispatch —
        host-side schedulers take effect between run_steps() calls."""
        core = self._make_core(n_batch)
        trainable = self._trainable
        params = self._params
        meta = self._meta
        nt_pos, tr_pos = self._nt_pos, self._tr_pos
        n_rep = repeat_steps

        def multi_fn(tr_datas, opt_states, t, scale_state, nt_datas,
                     resid, base_key, lr, wd, *stacked):
            def body(carry, xs):
                tr_c, opt_c, t_c, scale_c, nt_c, rs_c = carry
                (tr_n, opt_n, t_n, scale_n, rs_n, loss, aux) = core(
                    tr_c, opt_c, t_c, scale_c, nt_c, rs_c, base_key, lr,
                    wd, stacked if n_rep else xs)
                if aux:
                    # thread state updates (BN stats) into the carry the
                    # same way __call__ threads them into _param_arrays:
                    # the update wins over the optimizer write
                    nt_n = list(nt_c)
                    tr_n = list(tr_n)
                    for (p, _), new in zip(meta["state_updates"], aux):
                        idx = next(i for i, pp in enumerate(params)
                                   if pp is p)
                        if idx in nt_pos:
                            nt_n[nt_pos[idx]] = new.astype(
                                nt_c[nt_pos[idx]].dtype)
                        else:
                            tr_n[tr_pos[idx]] = new.astype(
                                tr_c[tr_pos[idx]].dtype)
                    nt_n, tr_n = tuple(nt_n), tuple(tr_n)
                else:
                    nt_n = nt_c
                return (tr_n, opt_n, t_n, scale_n, nt_n, rs_n), loss

            init = (tr_datas, opt_states, t, scale_state, nt_datas,
                    resid)
            (tr_f, opt_f, t_f, scale_f, nt_f, rs_f), losses = \
                jax.lax.scan(body, init, None if n_rep else stacked,
                             length=n_rep if n_rep else None)
            return tr_f, opt_f, t_f, scale_f, nt_f, rs_f, losses

        # nt is NOT donated even here: its input buffers may be the very
        # arrays the Parameters hold (after a prior stat write-back), and
        # they are tiny
        donate = (0, 1, 2, 5) if self.donate else ()
        shardings = self._jit_shardings(n_batch,
                                        stacked=repeat_steps is None)
        if shardings is not None:
            with mesh_scope(self.mesh):
                return jax.jit(multi_fn, in_shardings=shardings,
                               donate_argnums=donate)
        return jax.jit(multi_fn, donate_argnums=donate)

    # -- run ---------------------------------------------------------------
    def __call__(self, *batch):
        datas = tuple(b._data if isinstance(b, NDArray) else jnp.asarray(b)
                      for b in batch)
        sig = tuple((tuple(d.shape), str(d.dtype)) for d in datas)
        entry = self._programs.get(sig)
        if entry is None:
            entry = {"jitted": self._build(len(datas)), "lower_args": None}
            self._programs[sig] = entry
        self._last_sig = sig
        self._last_single_sig = sig
        if self.mesh is not None:
            with mesh_scope(self.mesh):
                bspecs = (self.batch_specs or
                          [PartitionSpec("dp")] * len(datas))
                datas = tuple(
                    jax.device_put(d, named_sharding(s))
                    for d, s in zip(datas, bspecs))
        (tr_arrays, tr_states, scale_state, nt_arrays, key, lr,
         wd) = self._prepare_dispatch(entry, datas)
        t0 = time.perf_counter()
        with _mesh_ctx(self.mesh):
            out = entry["jitted"](tr_arrays, tr_states, self._t,
                                  scale_state, nt_arrays,
                                  self._residuals, key, lr, wd, *datas)
        # host dispatch wall (async — device time only when the caller
        # syncs on the loss); turns into MFU once
        # register_cost_analysis() has run
        _cost.note_dispatch(self._cost_key + "/step",
                            time.perf_counter() - t0)
        (new_tr_arrays, new_tr_states, self._t, new_scale,
         self._residuals, loss, aux) = out
        self._write_back(new_tr_arrays, new_tr_states)
        if self._scale_state is not None:
            self._scale_state = new_scale
        self._host_t += 1  # mirror of t — no device fetch in the hot loop
        self.optimizer.num_update = self._host_t
        # mutable layer state (BN stats) written back into BOTH the
        # Parameter (eager/eval visibility) AND the step's own param
        # arrays — the next step's forward reads param_datas, so without
        # the second write the stats would re-accumulate against their
        # initial values forever. Stats ride in the NON-donated nt arg,
        # so each aux output is a fresh buffer the Parameter can own
        # outright — no copies, no use-after-donate hazard.
        updates = self._meta.get("state_updates", ())
        if updates:
            idx_of = {id(p): i for i, p in enumerate(self._params)}
            for (p, _), new in zip(updates, aux):
                i = idx_of.get(id(p))
                if i is not None:
                    self._param_arrays[i] = new
                # a TRAINABLE state-update target (unusual, but
                # push_state_update is open) re-enters the donated tr
                # tuple next step — the Parameter needs its own buffer
                p._data._rebind(jnp.copy(new)
                                if (self.donate and i is not None
                                    and self._trainable[i]) else new)
        return NDArray(loss)

    def _prepare_dispatch(self, entry, datas):
        """Common per-dispatch state: (tr_arrays, tr_states, scale_state,
        nt_arrays, key, lr, wd). Also fills entry["lower_args"] on first
        use (shape structs for AOT lowering — the real arrays may be
        donated by the call)."""
        if self._base_key is None:
            self._base_key = _rng.next_key()
        # cache device scalars for lr/wd — refresh only when the host
        # value changes (schedulers); avoids 2 H2D transfers per step
        lr_v = float(self.optimizer.learning_rate)
        wd_v = float(self.optimizer.wd)
        if self._lr_cache is None or self._lr_cache[0] != lr_v:
            self._lr_cache = (lr_v, jnp.asarray(lr_v, jnp.float32))
        if self._wd_cache is None or self._wd_cache[0] != wd_v:
            self._wd_cache = (wd_v, jnp.asarray(wd_v, jnp.float32))
        key, lr, wd = self._base_key, self._lr_cache[1], self._wd_cache[1]
        scale_state = self._scale_state if self._scale_state is not None \
            else ()
        tr_arrays = tuple(a for a, tr in zip(self._param_arrays,
                                             self._trainable) if tr)
        nt_arrays = tuple(a for a, tr in zip(self._param_arrays,
                                             self._trainable) if not tr)
        tr_states = tuple(st for st, tr in zip(self._opt_states,
                                               self._trainable) if tr)
        if entry["lower_args"] is None:
            entry["lower_args"] = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (tr_arrays, tr_states, self._t, scale_state, nt_arrays,
                 self._residuals, key, lr, wd) + datas)
        return tr_arrays, tr_states, scale_state, nt_arrays, key, lr, wd

    def _write_back(self, new_tr, new_states):
        """Fold trainable step outputs into _param_arrays/_opt_states."""
        it_p, it_s = iter(new_tr), iter(new_states)
        for i, tr in enumerate(self._trainable):
            if tr:
                self._param_arrays[i] = next(it_p)
        self._opt_states = tuple(
            next(it_s) if tr else st
            for st, tr in zip(self._opt_states, self._trainable))

    def run_steps(self, *stacked_batch, steps=None):
        """Run K chained optimizer steps in ONE device dispatch.

        Default: each argument is the per-call batch with an extra
        leading steps axis — shapes [K, ...] where a plain __call__
        takes [...]. With steps=K given, the arguments are ordinary
        single-step batches and the SAME batch is reused K times
        (steady-state benchmarking / overfit smokes — no stacked upload).
        Returns the per-step losses as an NDArray of shape (K,).
        Equivalent to K sequential __call__s (BN stats and the RNG
        stream thread through identically), except lr/wd are sampled
        once per dispatch — host-side LR schedulers take effect between
        run_steps calls.

        TPU-native analog of the reference's engine bulk execution
        (MXNET_ENGINE_BULK, SURVEY.md §2.1): amortizes host dispatch over
        K steps, which dominates wall time on high-latency device links."""
        datas = tuple(b._data if isinstance(b, NDArray) else jnp.asarray(b)
                      for b in stacked_batch)
        if steps is None:
            if not datas or any(d.ndim < 1 for d in datas):
                raise MXNetError("run_steps needs batches with a leading "
                                 "steps axis (or pass steps=K)")
            k = datas[0].shape[0]
            for d in datas:
                if d.shape[0] != k:
                    raise MXNetError(
                        f"run_steps: inconsistent steps axis "
                        f"{d.shape[0]} vs {k}")
        else:
            k = int(steps)
            if k <= 0:
                raise MXNetError("run_steps: steps must be positive")
        sig = ("multi", steps is None, k if steps is not None else None) \
            + tuple((tuple(d.shape), str(d.dtype)) for d in datas)
        entry = self._programs.get(sig)
        if entry is None:
            entry = {"jitted": self._build_multi(
                len(datas), repeat_steps=None if steps is None else k),
                "lower_args": None}
            self._programs[sig] = entry
        self._last_sig = sig
        if self.mesh is not None:
            with mesh_scope(self.mesh):
                raw = (self.batch_specs or
                       [PartitionSpec("dp")] * len(datas))
                if steps is None:  # stacked layout: leading K unsharded
                    raw = [PartitionSpec(None, *tuple(s)) for s in raw]
                datas = tuple(
                    jax.device_put(d, named_sharding(s))
                    for d, s in zip(datas, raw))
        (tr_arrays, tr_states, scale_state, nt_arrays, key, lr,
         wd) = self._prepare_dispatch(entry, datas)
        t0 = time.perf_counter()
        with _mesh_ctx(self.mesh):
            out = entry["jitted"](tr_arrays, tr_states, self._t,
                                  scale_state, nt_arrays,
                                  self._residuals, key, lr, wd, *datas)
        _cost.note_dispatch(self._cost_key + "/run_steps",
                            time.perf_counter() - t0)
        (new_tr, new_states, self._t, new_scale, new_nt,
         self._residuals, losses) = out
        self._write_back(new_tr, new_states)
        it_n = iter(new_nt)
        for i, tr in enumerate(self._trainable):
            if not tr:
                self._param_arrays[i] = next(it_n)
        if self._scale_state is not None:
            self._scale_state = new_scale
        self._host_t += k
        self.optimizer.num_update = self._host_t
        # stat write-back: the final nt values are fresh (non-donated-
        # input) output buffers — Parameters can own them directly
        updates = self._meta.get("state_updates", ())
        if updates:
            idx_of = {id(p): i for i, p in enumerate(self._params)}
            for p, _ in updates:
                i = idx_of.get(id(p))
                if i is not None:
                    p._data._rebind(jnp.copy(self._param_arrays[i])
                                    if (self.donate and self._trainable[i])
                                    else self._param_arrays[i])
        return NDArray(losses)

    def sync_params(self):
        """Write the step's device arrays back into the Block's Parameters
        (so save_parameters / eager eval see current weights)."""
        for p, a in zip(self._params, self._param_arrays):
            p._data._rebind(a)

    @property
    def step_count(self):
        return self._host_t

    @property
    def loss_scale(self):
        """Current dynamic loss scale (host fetch), or the static scale,
        or None on the unscaled path."""
        if self._scale_state is not None:
            return float(self._scale_state[0])
        return self._static_scale

    def compiled_cost_analysis(self, sig=None):
        """XLA's cost analysis for a compiled step program (a dict with
        'flops' etc.), or None before the first call / when the backend
        does not report costs. This is the authoritative PER-STEP flop
        count for MFU math — no hand-derived estimates. sig selects a
        program from the bucket cache; default = the last SINGLE-step
        program called. A K-chained run_steps program reports PER-STEP
        figures too: XLA's HloCostAnalysis counts a while/scan body
        once regardless of trip count, so the lax.scan-chained program
        already costs like one step (verified against the single-step
        program; no division needed)."""
        if sig is None and self._last_single_sig is not None:
            sig = self._last_single_sig
        if sig is None:
            sig = self._last_sig
        try:
            compiled = self._lowered(sig).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            return ca
        except Exception:
            return None

    def _lowered(self, sig=None):
        """AOT-lower one cached step program (re-traces; mesh scope active
        so the trace takes the same op routes as the live step)."""
        entry = self._programs[sig if sig is not None else self._last_sig]
        with _mesh_ctx(self.mesh):
            return entry["jitted"].lower(*entry["lower_args"])


class EvalStep:
    """Jitted inference step over the mesh (forward only)."""

    def __init__(self, net, mesh=None, batch_specs=None):
        self.net = net
        self.mesh = mesh if mesh is not None else current_mesh()
        self.batch_specs = batch_specs
        self._params = list(net.collect_params().values())
        self._programs = {}

    def _build(self, n_batch):
        net, params = self.net, self._params

        def fwd(param_datas, key, *batch_datas):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_datas):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                args = [NDArray(d) for d in batch_datas]
                with autograd._Scope(False, False), _rng.key_scope(key):
                    out = net.forward(*args)
            finally:
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            outs = out if isinstance(out, tuple) else (out,)
            return tuple(o._data for o in outs)

        if self.mesh is not None:
            with mesh_scope(self.mesh):
                repl = named_sharding(PartitionSpec())
                pspecs = tuple(
                    named_sharding(_spec_or_replicated(p.sharding))
                    for p in params)
                bspecs = tuple(named_sharding(s) for s in (
                    self.batch_specs or [PartitionSpec("dp")] * n_batch))
                return jax.jit(fwd, in_shardings=(pspecs, repl) + bspecs)
        return jax.jit(fwd)

    def __call__(self, *batch):
        datas = tuple(b._data if isinstance(b, NDArray) else jnp.asarray(b)
                      for b in batch)
        sig = tuple((tuple(d.shape), str(d.dtype)) for d in datas)
        jitted = self._programs.get(sig)
        if jitted is None:
            jitted = self._build(len(datas))
            self._programs[sig] = jitted
        key = _rng.next_key()
        param_datas = tuple(p.data()._data for p in self._params)
        with _mesh_ctx(self.mesh):
            outs = jitted(param_datas, key, *datas)
        res = tuple(NDArray(o) for o in outs)
        return res[0] if len(res) == 1 else res
