"""mx.profiler — scoped ranges, per-op aggregate stats, device traces.

Reference parity: python/mxnet/profiler.py over src/profiler/profiler.cc
(SURVEY.md §5.1): `set_config` / `set_state('run'|'stop')` /
`pause`/`resume` / `dumps` (aggregate per-op table, the
MXAggregateProfileStatsPrint analog) / scope objects
(ProfileTask/ProfileEvent analogs) / chrome-trace output.

TPU-native mapping: the device timeline comes from `jax.profiler`
(XPlane → TensorBoard/perfetto, started and stopped by set_state when a
trace dir is configured) — XLA already records every fused kernel, which
is what the reference's per-engine-op timestamps were. The MXNet-parity
work is the API: scoped ranges annotate the jax trace via
TraceAnnotation, and the per-op aggregate table is measured at the eager
dispatch funnel (ops/registry.apply_op) — per-op wall times with a sync
per op when `aggregate_stats=True`, the same serialization the
reference's NaiveEngine profiling mode accepts for accurate attribution.
"""
from __future__ import annotations

import json
import threading
import time

import jax

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dumps",
           "dump", "Scope", "scope", "Task", "Event", "Counter",
           "record_counter", "server_trace_dir"]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "trace_dir": None,          # jax device-trace output (TensorBoard)
    "aggregate_stats": True,
    "profile_all": False,
    "profile_imperative": True,
}
_state = {"running": False, "paused": False, "jax_trace": False}
_agg = {}       # op name -> [count, total_s, min_s, max_s]
_counters = {}  # profiler.Counter values — their OWN table, never _agg


def set_config(**kwargs):
    """Parity: profiler.set_config(filename=..., profile_all=...,
    aggregate_stats=...). Extra TPU-native knob: trace_dir=<dir> enables
    the jax/XLA device trace (viewable in TensorBoard/perfetto)."""
    unknown = set(kwargs) - {"filename", "trace_dir", "aggregate_stats",
                             "profile_all", "profile_imperative",
                             "profile_symbolic", "profile_memory",
                             "profile_api", "continuous_dump"}
    if unknown:
        raise MXNetError(f"unknown profiler config keys {sorted(unknown)}")
    for k in ("profile_symbolic", "profile_memory", "profile_api",
              "continuous_dump"):
        kwargs.pop(k, None)  # accepted for parity; subsumed by the device trace
    _config.update(kwargs)


def set_state(state_name="stop"):
    """'run' starts collection (and the jax device trace when trace_dir is
    configured); 'stop' ends it. Parity: profiler.set_state."""
    if state_name not in ("run", "stop"):
        raise MXNetError(f"profiler state must be run|stop, got "
                         f"{state_name!r}")
    if state_name == "run" and not _state["running"]:
        _state["running"], _state["paused"] = True, False
        with _lock:
            _agg.clear()
            _counters.clear()
        if _config["trace_dir"]:
            jax.profiler.start_trace(_config["trace_dir"])
            _state["jax_trace"] = True
    elif state_name == "stop" and _state["running"]:
        _state["running"] = False
        if _state["jax_trace"]:
            jax.profiler.stop_trace()
            _state["jax_trace"] = False


def state():
    return "run" if _state["running"] else "stop"


def pause():
    _state["paused"] = True


def resume():
    _state["paused"] = False


def is_active():
    return _state["running"] and not _state["paused"]


def record_op(name, seconds):
    """Called from the op dispatch funnel (ops/registry.apply_op)."""
    with _lock:
        ent = _agg.get(name)
        if ent is None:
            _agg[name] = [1, seconds, seconds, seconds]
        else:
            ent[0] += 1
            ent[1] += seconds
            ent[2] = min(ent[2], seconds)
            ent[3] = max(ent[3], seconds)


def record_counter(name, value):
    """profiler.Counter values — kept out of the per-op TIME table (they
    are not durations) in their own section of dumps()."""
    with _lock:
        _counters[name] = value


def dumps(reset=False, format="table"):
    """The aggregate per-op stats table (parity:
    MXAggregateProfileStatsPrint / profiler.dumps), plus a Counters
    section when profiler.Counter objects recorded values."""
    with _lock:
        items = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        counters = dict(_counters)
        if reset:
            _agg.clear()
            _counters.clear()
    if format == "json":
        out = {k: {"count": c, "total_ms": t * 1e3,
                   "min_ms": mn * 1e3, "max_ms": mx * 1e3}
               for k, (c, t, mn, mx) in items}
        if counters:
            out["_counters"] = counters
        return json.dumps(out)
    header = (f"{'Name':<40}{'Total Count':>12}{'Time (ms)':>14}"
              f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}")
    lines = ["Profile Statistics:", header, "-" * len(header)]
    for name, (c, t, mn, mx) in items:
        lines.append(f"{name[:39]:<40}{c:>12}{t * 1e3:>14.3f}"
                     f"{mn * 1e3:>12.3f}{mx * 1e3:>12.3f}"
                     f"{t / c * 1e3:>12.3f}")
    if counters:
        lines.append("Counters:")
        for name, v in sorted(counters.items()):
            lines.append(f"{name[:39]:<40}{v:>12}")
    return "\n".join(lines)


def dump(finished=True):
    """Write a chrome://tracing JSON of the aggregate events to
    config.filename (parity: profiler.dump)."""
    with _lock:
        items = list(_agg.items())
    events = []
    ts = 0.0
    for name, (c, t, mn, mx) in items:
        events.append({"name": name, "ph": "X", "ts": ts * 1e6,
                       "dur": t * 1e6, "pid": 0, "tid": 0,
                       "args": {"count": c}})
        ts += t
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return _config["filename"]


def server_trace_dir():
    return _config["trace_dir"]


class Scope:
    """Named range: annotates the jax device trace and accrues into the
    aggregate table (parity: profiler.Scope / ProfileTask)."""

    def __init__(self, name="<unk>"):
        self._name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        # construct the jax annotation only while the profiler is live:
        # an inactive profiler must cost nothing per scope (previously
        # every scope paid annotation construction even when stopped)
        if is_active():
            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if is_active():
            record_op(f"scope::{self._name}", dt)
        return False


scope = Scope


class Task(Scope):
    """Parity: profiler.Task — start()/stop() object form."""

    def __init__(self, name="<unk>", domain=None):
        super().__init__(name)

    def start(self):
        self.__enter__()

    def stop(self):
        self.__exit__(None, None, None)


class Event(Task):
    pass


class Counter:
    """Parity: profiler.Counter — named counter recorded into its own
    Counters section of dumps() (previously each set_value() pushed a
    bogus 0.0-duration row into the per-op TIME table, polluting
    min/avg stats)."""

    def __init__(self, name, domain=None, value=0):
        self._name = name
        self.value = value

    def set_value(self, v):
        self.value = v
        if is_active():
            record_counter(f"counter::{self._name}", v)

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)
