"""RNG state management.

Reference parity: src/common/random_generator.h (per-device Philox streams,
engine-managed) + mx.random.seed. JAX's threefry/Philox keys are the TPU
analog; this module owns the ambient key stream.

Two modes:
  * Eager: a process-global key advanced per draw (`next_key`), seeded by
    `mx.random.seed(n)` — matching the reference's global-seed semantics.
  * Traced (inside hybridize/jit): RNG must be functional, so the tracing
    wrapper installs a `key_scope(base_key)`; draws fold an incrementing
    counter into the scoped key, keeping the traced program pure while the
    per-call base key is supplied as a runtime argument (so two calls of a
    hybridized dropout net differ, as in the reference).
"""
from __future__ import annotations

import threading

import jax
import jax.random as jrandom


class _RngState(threading.local):
    # key is created LAZILY: materializing a PRNGKey initializes the XLA
    # backend, which must not happen at import time (it would break
    # jax.distributed.initialize for multi-process users — kvstore.py)
    def __init__(self):
        self.key = None
        self.scopes = []  # list of [base_key, counter]


_state = _RngState()


def seed(seed_state: int, ctx=None):
    """Parity: mx.random.seed. ctx accepted for API compat (keys are
    device-agnostic in JAX; placement follows the op)."""
    _state.key = jrandom.PRNGKey(int(seed_state))


def next_key():
    if _state.scopes:
        scope = _state.scopes[-1]
        scope[1] += 1
        return jrandom.fold_in(scope[0], scope[1])
    if _state.key is None:
        _state.key = jrandom.PRNGKey(0)
    _state.key, sub = jrandom.split(_state.key)
    return sub


class key_scope:
    """Install a functional base key for draws inside a traced region."""

    def __init__(self, base_key):
        self.base_key = base_key

    def __enter__(self):
        _state.scopes.append([self.base_key, 0])
        return self

    def __exit__(self, *exc):
        _state.scopes.pop()


def in_traced_scope() -> bool:
    return bool(_state.scopes)
