"""mx.runtime — feature introspection.

Reference parity: python/mxnet/runtime.py — Features / feature_list()
backed by src/libinfo.cc compile-time flags (SURVEY.md §2.1 "Init &
lifecycle", §5.6 layer 3). Here the "build flags" are runtime properties
of the JAX/XLA stack, probed once on first access.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["Feature", "Features", "feature_list", "jit_cache_stats",
           "reset_jit_cache_stats"]


def jit_cache_stats():
    """Process-wide trace-cache counters ({'retraces', 'evictions'}) for
    the bounded LRU jit caches (HybridBlock._jit_cache and
    GPT2._generate_cache). A steadily climbing retrace count in steady
    state means shape churn is defeating the caches — pad or bucket the
    inputs. Bound sizes: MXNET_TPU_JIT_CACHE_SIZE (default 64) and
    MXNET_TPU_GENERATE_CACHE_SIZE (default 16)."""
    from .gluon.block import jit_cache_stats as _stats
    return _stats()


def reset_jit_cache_stats():
    from .gluon.block import reset_jit_cache_stats as _reset
    _reset()


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe():
    import jax

    feats = {}

    def have_platform(p):
        try:
            return len(jax.devices(p)) > 0
        except RuntimeError:
            return False

    feats["CPU"] = True
    feats["TPU"] = have_platform("tpu")
    feats["CUDA"] = have_platform("gpu")  # parity name for the flag
    feats["BF16"] = True                  # first-class on every XLA backend
    feats["F16C"] = True
    feats["INT64_TENSOR_SIZE"] = True     # jax uses 64-bit sizes natively
    feats["SIGNAL_HANDLER"] = True        # python default faulthandler path
    try:
        import jax.experimental.pallas  # noqa: F401
        feats["PALLAS"] = True
    except ImportError:
        feats["PALLAS"] = False
    feats["DIST_KVSTORE"] = True          # kvstore.py + jax.distributed
    feats["X64"] = bool(jax.config.read("jax_enable_x64"))
    # de-scoped reference features, reported disabled for honest probing
    for off in ("CUDNN", "NCCL", "TENSORRT", "ONEDNN", "MKLDNN", "OPENCV",
                "BLAS_MKL", "TVM_OP", "CAFFE", "PROFILER_NVTX"):
        feats[off] = False
    return feats


class Features(dict):
    """Parity: mx.runtime.Features — dict of Feature with is_enabled()."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            cls.instance.update(
                {k: Feature(k, v) for k, v in _probe().items()})
        return cls.instance

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise MXNetError(f"unknown feature '{feature_name}'; known: "
                             f"{sorted(self)}")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
