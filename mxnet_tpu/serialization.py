"""Parameter/array serialization.

Reference parity: src/ndarray/ndarray.cc — NDArray::Save/Load and the
`.params` container written by MXNDArraySave (a dmlc stream of
Map<string, NDArray>), consumed by gluon save_parameters/load_parameters.

Native format here: NumPy `.npz` (zip of arrays keyed by name) with a
`__format__` marker entry — self-describing, fast, and readable by any
NumPy — plus a best-effort READER for the reference's binary `.params`
format so existing MXNet model-zoo weights can be imported
(`load_mxnet_params`). Writing the legacy format is out of scope.
"""
from __future__ import annotations

import struct

import numpy as _np
import jax.numpy as jnp

from .base import MXNetError

FORMAT_KEY = "__mxnet_tpu_format__"
FORMAT_VERSION = 1


DTYPE_SIDECAR = "__dtype__:"
# Non-native-to-NumPy dtypes stored as a raw integer view + a sidecar entry
# recording the real dtype; np.savez would otherwise silently write them as
# void ('|V2') records that cannot be loaded back.
_RAW_VIEWS = {"bfloat16": _np.uint16, "float8_e4m3fn": _np.uint8,
              "float8_e5m2": _np.uint8}
_RAW_BY_SIZE = {1: _np.uint8, 2: _np.uint16, 4: _np.uint32, 8: _np.uint64}


def _loadable_raw_view(name, dtype):
    """Raw integer view for any void-kind dtype that load_ndarray_dict's
    `getattr(ml_dtypes, name)` path can restore; None otherwise (so save
    fails loudly instead of load failing later)."""
    try:
        import ml_dtypes
    except ImportError:
        return None
    restored = getattr(ml_dtypes, name, None)
    if restored is None or _np.dtype(restored) != dtype:
        return None
    return _RAW_BY_SIZE.get(dtype.itemsize)


def save_ndarray_dict(filename, arrays: dict):
    """Save {name: NDArray|np.ndarray} (parity: mx.nd.save)."""
    out = {}
    for k, v in arrays.items():
        if k.startswith(DTYPE_SIDECAR) or k == FORMAT_KEY:
            raise MXNetError(
                f"array name {k!r} collides with the reserved "
                f"{DTYPE_SIDECAR!r}/{FORMAT_KEY!r} namespace")
        a = _np.asarray(getattr(v, "asnumpy", lambda: v)())
        name = a.dtype.name
        if name in _RAW_VIEWS or a.dtype.kind == "V":
            # only dtypes load_ndarray_dict can restore (via ml_dtypes) may
            # take the sidecar path; fail at save time, not load time
            view = _RAW_VIEWS.get(name) or _loadable_raw_view(name, a.dtype)
            if view is None:
                raise MXNetError(
                    f"cannot serialize array {k!r} of unsupported dtype "
                    f"{a.dtype} (not an ml_dtypes dtype)")
            out[DTYPE_SIDECAR + k] = _np.asarray(name)
            a = a.view(view)
        out[k] = a
    out[FORMAT_KEY] = _np.asarray(FORMAT_VERSION)
    with open(filename, "wb") as f:
        _np.savez(f, **out)


def _restore_dtype(arr, dtype_name):
    import ml_dtypes
    return arr.view(_np.dtype(getattr(ml_dtypes, dtype_name)))


def load_ndarray_dict(filename) -> dict:
    """Load a dict of NDArrays (parity: mx.nd.load). Transparently reads
    either the native .npz format or a legacy MXNet .params binary."""
    from .ndarray.ndarray import NDArray
    try:
        with _np.load(filename, allow_pickle=False) as z:
            sidecars = {k[len(DTYPE_SIDECAR):]: str(z[k])
                        for k in z.files if k.startswith(DTYPE_SIDECAR)}
            out = {}
            for k in z.files:
                if k == FORMAT_KEY or k.startswith(DTYPE_SIDECAR):
                    continue
                a = z[k]
                if k in sidecars:
                    a = _restore_dtype(a, sidecars[k])
                out[k] = NDArray(jnp.asarray(a))
            return out
    except (OSError, ValueError):
        pass  # not a zip — try the legacy binary format
    raw = load_mxnet_params(filename)
    return {k: NDArray(jnp.asarray(v)) for k, v in raw.items()}


def save_parameter_dict(filename, params, strip_prefix=""):
    arrays = {}
    for name, p in params.items():
        if strip_prefix and name.startswith(strip_prefix):
            name = name[len(strip_prefix):]
        arrays[name] = p.data()
    save_ndarray_dict(filename, arrays)


def load_parameter_dict(filename, params, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
    loaded = load_ndarray_dict(filename)
    # strip legacy 'arg:'/'aux:' prefixes from Module-era checkpoints
    loaded = {k.split(":", 1)[-1] if k.startswith(("arg:", "aux:")) else k: v
              for k, v in loaded.items()}
    for name, p in params.items():
        if name not in loaded:
            if allow_missing:
                continue
            raise MXNetError(
                f"parameter {name} missing in file {filename} "
                "(set allow_missing=True to skip)")
        arr = loaded[name]
        if cast_dtype:
            arr = arr.astype(p.dtype)
        p.set_data(arr)
    if not ignore_extra:
        extra = set(loaded) - set(params)
        if extra:
            raise MXNetError(
                f"file {filename} has extra parameters {sorted(extra)[:8]}… "
                "(set ignore_extra=True to skip)")


# ---------------------------------------------------------------------------
# Legacy MXNet .params binary reader (best-effort import path)
# ---------------------------------------------------------------------------
# Format (src/ndarray/ndarray.cc NDArray::Save/Load + c_api MXNDArraySave):
#   uint64 kMXAPINDArrayListMagic = 0x112
#   uint64 reserved
#   uint64 ndarray-count N; N × NDArray records
#   uint64 key-count K;     K × (uint64 len + bytes) names
# Each NDArray record starts with a uint32 magic:
#   0xF993FAC8 (v1, int64 TShape):  shape (u32 ndim + i64[ndim]),
#       i32 dev_type, i32 dev_id, i32 type_flag, raw data
#   0xF993FAC9 / 0xF993FACA (v2 "+storage type" / v3 "np shape semantics"):
#       i32 stype (dense = kDefaultStorage = 0; sparse rejected),
#       shape (i32 ndim + i64[ndim]; v3 may store ndim = -1 for unknown),
#       i32 dev_type, i32 dev_id, i32 type_flag, raw data
#   any other value: v0 layout — the u32 just read IS ndim, followed by
#       u32[ndim] dims, i32 dev_type, i32 dev_id, i32 type_flag, raw data

_MX_LIST_MAGIC = 0x112
_MX_ND_V1_MAGIC = 0xF993FAC8
_MX_ND_V2_MAGIC = 0xF993FAC9
_MX_ND_V3_MAGIC = 0xF993FACA
_MX_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
              4: "int32", 5: "int8", 6: "int64", 7: "bool",
              12: "bfloat16"}


class _Reader:
    def __init__(self, data):
        self.d = data
        self.o = 0

    def u32(self):
        v = struct.unpack_from("<I", self.d, self.o)[0]
        self.o += 4
        return v

    def i32(self):
        v = struct.unpack_from("<i", self.d, self.o)[0]
        self.o += 4
        return v

    def u64(self):
        v = struct.unpack_from("<Q", self.d, self.o)[0]
        self.o += 8
        return v

    def i64s(self, n):
        v = struct.unpack_from(f"<{n}q", self.d, self.o)
        self.o += 8 * n
        return v

    def raw(self, n):
        v = self.d[self.o:self.o + n]
        self.o += n
        return v


def _read_legacy_ndarray(r: _Reader):
    magic = r.u32()
    if magic in (_MX_ND_V2_MAGIC, _MX_ND_V3_MAGIC):
        stype = r.i32()
        # NDArrayStorageType: kUndefinedStorage=-1, kDefaultStorage=0,
        # kRowSparseStorage=1, kCSRStorage=2
        if stype not in (-1, 0):
            raise MXNetError(
                "legacy .params contains a sparse NDArray (stype="
                f"{stype}); sparse import is not supported on TPU "
                "(dense-only)")
        ndim = r.i32()
        if ndim < 0:  # v3 np semantics: unknown shape — cannot hold data
            raise MXNetError("legacy .params NDArray has unknown shape")
        shape = r.i64s(ndim)
    elif magic == _MX_ND_V1_MAGIC:
        ndim = r.u32()
        shape = r.i64s(ndim)
    else:
        # v0 layout: the u32 just read was ndim, dims are u32
        ndim = magic
        if ndim > 32:
            raise MXNetError(
                f"legacy .params record has implausible ndim {ndim} — "
                "corrupt file or unsupported layout")
        shape = tuple(r.u32() for _ in range(ndim))
    _dev_type, _dev_id = r.i32(), r.i32()
    type_flag = r.i32()
    dtype = _MX_DTYPES.get(type_flag)
    if dtype is None:
        raise MXNetError(f"unknown MXNet dtype flag {type_flag}")
    if dtype == "bfloat16":
        import ml_dtypes
        npdt = _np.dtype(ml_dtypes.bfloat16)
    else:
        npdt = _np.dtype(dtype)
    count = int(_np.prod(shape)) if shape else 1
    buf = r.raw(count * npdt.itemsize)
    return _np.frombuffer(buf, dtype=npdt).reshape(shape).copy()


def load_mxnet_params(filename) -> dict:
    """Read a legacy Apache-MXNet `.params`/`.nd` file into numpy arrays.

    Best-effort importer for model-zoo weights (SURVEY.md §5.4: 'keep
    .params import for ecosystem compatibility')."""
    with open(filename, "rb") as f:
        data = f.read()
    r = _Reader(data)
    magic = r.u64()
    if magic != _MX_LIST_MAGIC:
        raise MXNetError(
            f"{filename}: not an MXNet NDArray-list file (magic {magic:#x})")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_legacy_ndarray(r) for _ in range(n)]
    k = r.u64()
    names = []
    for _ in range(k):
        ln = r.u64()
        names.append(r.raw(ln).decode("utf-8"))
    if names and len(names) == len(arrays):
        return dict(zip(names, arrays))
    return {str(i): a for i, a in enumerate(arrays)}
