"""mxnet_tpu.serving — continuous-batching inference engine.

The serving-side counterpart of parallel.TrainStep: where training
compiles the whole optimizer step into one XLA program, serving compiles
ONE fixed-shape unified dispatch — prompt chunks, single-token decode,
and speculative verify are all rows of the same (B, W) program — and
keeps the host out of the token loop. Requests are admitted into fixed
slots between compiled dispatches; each slot consumes its own query
span against its own live length through the ragged span-attention
kernel (ops/pallas_attention.ragged_span_attention), so finished
sequences stop costing HBM the moment their slot is freed and a
4k-token prompt streams page-sized chunks next to everyone else's
decode instead of monopolizing a dispatch.

Page ownership is explicit: serving/page_pool.py is a host-side
ref-counted allocator over the PagedKVCache page axis, and
serving/prefix_cache.py is a radix tree over token-id prefixes whose
nodes own full KV pages — ServingEngine(prefix_cache=True) attaches a
new request's cached prompt prefix by page-table surgery and prefills
only the uncached suffix (O(prompt) → O(suffix)).

ServingEngine(speculative=True) amortizes the decode forward over
several tokens: a host-side prompt-lookup drafter (serving/
speculative.py) proposes up to spec_tokens-1 candidates from the
request's own history and ONE multi-query ragged-attention forward
(ops/pallas_attention.ragged_mq_decode_attention) verifies them all —
greedy output bit-identical to spec-off, sampled output distribution-
preserving via rejection sampling on the per-request RNG streams.

The serving loop is overload-hardened (docs/SERVING.md "Robustness"):
requests carry deadlines and priority classes, a SheddingPolicy
(serving/policy.py) sheds or down-prioritizes work from live telemetry
before it queues, step() supervises dispatch faults (audit, rollback,
retry, quarantine) instead of propagating them, and a seeded FaultPlan
(serving/faults.py) drives all of it deterministically in tests.

Above the single engine, `ServingRouter` (serving/router.py) fronts N
replicas: radix-prefix-affinity placement with load-aware spill,
health-driven failover (a killed or wedged replica's queued and
in-flight requests migrate to survivors bit-identically via the
restart continuation), p99-hedged dispatch with loser cancellation,
and drain()/rejoin() rolling restarts — with `ReplicaFaultPlan`
injecting replica-level kill/hang/degrade for fleet-wide chaos
(docs/SERVING.md "Multi-replica serving & failover").

Multi-tenant serving (docs/SERVING.md "Multi-tenant LoRA serving"):
`AdapterPool` (serving/adapters.py) pages per-layer low-rank (A, B)
LoRA deltas for many registered adapters in and out of ONE
device-resident slab, ref-counted and LRU-evicted like KV pages;
`Request(adapter_id=, tenant=)` rides through admission, migration
and restart, per-slot slab indices are runtime data inside the one
compiled program (zero retraces across adapter churn), and
`TenantQuota` + the scheduler's deficit-weighted fair pick keep one
tenant from starving the rest.

The HTTP ingress (docs/SERVING.md "HTTP front-end"):
`ServingFrontend` (serving/frontend.py) exposes an engine or router
as `POST /v1/generate` with SSE token streaming over the stdlib
HTTP stack — bounded per-stream buffers (`TokenStream`) with a
slow-client overflow-cancel policy, client disconnects wired to
idempotent `cancel()`, structured rejections mapped to 429/503 +
`Retry-After`, and graceful drain; `tools/http_soak.py` is the
open-loop chaos soak over real sockets.

See docs/SERVING.md for the architecture and slot lifecycle.
"""
from .sampling import filtered_logits, sample_tokens, slot_keys  # noqa: F401
from .scheduler import (Request, SlotScheduler, RejectedError,  # noqa: F401
                        QueueFullError, ShedError, TenantQuota,
                        TenantQuotaError, TERMINAL_STATUSES)
from .page_pool import PagePool, PagePoolExhausted  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .host_tier import HostPagePool  # noqa: F401
from .adapters import (AdapterPool, AdapterPoolExhausted,  # noqa: F401
                       merged_weights, random_lora)
from .speculative import PromptLookupProposer, verify_tokens  # noqa: F401
from .policy import SheddingPolicy  # noqa: F401
from .faults import FaultError, FaultPlan, ReplicaFaultPlan  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .weight_quant import (QuantizedWeight, build_weight_plan,  # noqa: F401
                           dequantize, quantize_dense_weights,
                           quantize_weight)
from .router import ServingRouter  # noqa: F401
from .frontend import ServingFrontend, TokenStream  # noqa: F401

__all__ = ["Request", "SlotScheduler", "RejectedError", "QueueFullError",
           "ShedError", "TenantQuota", "TenantQuotaError",
           "TERMINAL_STATUSES",
           "ServingEngine", "ServingRouter",
           "ServingFrontend", "TokenStream",
           "SheddingPolicy", "PagePool", "PagePoolExhausted",
           "AdapterPool", "AdapterPoolExhausted", "merged_weights",
           "random_lora",
           "PrefixCache", "HostPagePool", "PromptLookupProposer",
           "FaultPlan",
           "FaultError", "ReplicaFaultPlan",
           "filtered_logits", "sample_tokens", "slot_keys",
           "verify_tokens",
           "QuantizedWeight", "build_weight_plan", "dequantize",
           "quantize_dense_weights", "quantize_weight"]
