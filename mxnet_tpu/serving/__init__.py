"""mxnet_tpu.serving — continuous-batching inference engine.

The serving-side counterpart of parallel.TrainStep: where training
compiles the whole optimizer step into one XLA program, serving compiles
prefill (per prompt bucket) and a K-step decode block (lax.scan) into
cached programs and keeps the host out of the token loop. Requests are
admitted into fixed decode slots between compiled dispatches; each slot
decodes against its own live length through the ragged paged-attention
kernel (ops/pallas_attention.ragged_decode_attention), so finished
sequences stop costing HBM the moment their slot is freed.

See docs/SERVING.md for the architecture and slot lifecycle.
"""
from .sampling import sample_tokens, slot_keys  # noqa: F401
from .scheduler import Request, SlotScheduler, QueueFullError  # noqa: F401
from .engine import ServingEngine  # noqa: F401

__all__ = ["Request", "SlotScheduler", "QueueFullError", "ServingEngine",
           "sample_tokens", "slot_keys"]
