"""Paged LoRA adapter pool — many fine-tuned variants over one base.

The engine serves ONE resident base model; fine-tuned variants are
low-rank (A, B) deltas on the four attention projections
(query/key/value/proj) of every layer.  ``AdapterPool`` packs the
deltas of up to ``slots - 1`` adapters into one device-resident slab
(slot 0 is reserved as the NULL adapter: all zeros, scale 0), with
each adapter's rank zero-padded to a fixed ``max_rank`` so the slab —
and every program that reads it — has one shape forever:

    A     (4, num_layers, slots, units, max_rank)   model dtype
    B     (4, num_layers, slots, max_rank, units)   model dtype
    scale (slots,)                                  float32 = alpha/rank

Inside the batched forward each decode slot gathers its own rows
(``x @ A_s @ B_s * alpha/r``), so one fixed-shape program serves any
adapter mix; which adapter a slot wears is runtime data (a per-slot
int in the device slot state), never a shape axis — adapter churn
causes zero retraces.

Residency is managed exactly like KV pages in ``page_pool.py``: the
host-side pool is a ref-counted ledger over slab slots.

  * ``register(id, weights)`` — host-side only; weights stay on the
                                host until a request needs them.
  * ``acquire(id)``           — pin the adapter for a slot's lifetime;
                                pages it into a free slab slot on a
                                miss, LRU-evicting an unpinned
                                resident if the slab is full.  When
                                every slot is pinned this raises
                                ``AdapterPoolExhausted`` — the engine
                                supervisor treats that as BACKPRESSURE
                                (requeue, nobody's fault), mirroring
                                ``PagePoolExhausted``.
  * ``release(id)``           — drop the pin.  Zero-pin adapters stay
                                resident (warm) until LRU eviction
                                needs the slot.
  * ``audit(assignments)``    — loud invariant check, run by the
                                supervisor next to ``PagePool.audit``.

Page-in is ONE jitted donated scatter into the slab (a data update at
a traced slot index — never a recompile).  All bookkeeping is O(slots)
host work between compiled dispatches.
"""
from __future__ import annotations

import functools

import numpy as np

from ..base import MXNetError
from ..analysis import loop_only, thread_safe

__all__ = ["AdapterPool", "AdapterPoolExhausted", "random_lora",
           "merged_weights"]

# projection axis order of the slab's leading dim — gpt2.py indexes it
PROJ = ("query", "key", "value", "proj")


class AdapterPoolExhausted(MXNetError):
    """acquire() found every slab slot pinned by an active request. A
    distinct type because the engine supervisor treats exhaustion as
    BACKPRESSURE (requeue the admission and retry once a slot drains —
    nobody's fault), exactly like PagePoolExhausted."""


def random_lora(config, rank, alpha=None, seed=0, scale=0.02):
    """Host-side random LoRA weights for tests/benches: dict with
    ``A`` (4, L, units, rank), ``B`` (4, L, rank, units), ``alpha``,
    ``rank``.  B is deliberately non-zero (real checkpoints start B=0,
    which would make every adapter a no-op oracle)."""
    rng = np.random.default_rng(seed)
    L, U = config.num_layers, config.units
    return {
        "A": rng.normal(0.0, scale, (4, L, U, rank)).astype(np.float32),
        "B": rng.normal(0.0, scale, (4, L, rank, U)).astype(np.float32),
        "alpha": float(alpha if alpha is not None else rank),
        "rank": int(rank),
    }


def merged_weights(base_w, weights, proj, layer):
    """Dense merged-weight oracle for one projection of one layer:
    ``W + (B A)^T * alpha/rank`` on the host.  ``base_w`` is the Dense
    kernel ((units, units), out-major as Dense stores it); the delta
    transposes because the forward computes x @ A @ B = x @ (A B) and
    Dense computes x @ W^T."""
    p = PROJ.index(proj)
    a = weights["A"][p, layer]          # (U, r)
    b = weights["B"][p, layer]          # (r, U)
    delta = (a @ b) * (weights["alpha"] / weights["rank"])
    return base_w + delta.T.astype(base_w.dtype)


class AdapterPool:
    """Device-resident LoRA slab + host-side ref-counted slot ledger."""

    def __init__(self, config, slots=8, max_rank=8, dtype=None):
        import jax.numpy as jnp
        if slots < 2:
            raise MXNetError("AdapterPool needs at least 2 slots "
                             "(slot 0 is the reserved null adapter)")
        if max_rank < 1:
            raise MXNetError("AdapterPool needs max_rank >= 1")
        self.config = config
        self.slots = int(slots)
        self.max_rank = int(max_rank)
        L, U = config.num_layers, config.units
        self.dtype = jnp.dtype(dtype or getattr(config, "dtype", "float32"))
        # dtype="int8" packs the slab quantized: per-(proj, layer, slot)
        # absmax/127 dequant scales ride next to it and gpt2._lora
        # widens the gathered slot slices in-register — LoRA deltas are
        # tiny and tolerance-friendly, so the slab drops to a quarter
        # (fp32) of its bytes in the HBM ledger's adapter_slab entry
        self.quantized = self.dtype == jnp.int8
        self.A = jnp.zeros((4, L, self.slots, U, self.max_rank),
                           self.dtype)
        self.B = jnp.zeros((4, L, self.slots, self.max_rank, U),
                           self.dtype)
        self.scale = jnp.zeros((self.slots,), jnp.float32)
        if self.quantized:
            self.a_scale = jnp.zeros((4, L, self.slots), jnp.float32)
            self.b_scale = jnp.zeros((4, L, self.slots), jnp.float32)
        else:
            self.a_scale = self.b_scale = None
        self._registry = {}             # adapter_id -> host weights
        self._slot_of = {}              # adapter_id -> resident slot
        self._adapter_at = [None] * self.slots   # slot -> adapter_id
        self._pins = np.zeros(self.slots, np.int64)
        self._last_used = np.zeros(self.slots, np.int64)
        self._tick = 0
        self.page_ins = 0
        self.evictions = 0

    # -- queries -----------------------------------------------------------
    @property
    def num_resident(self):
        return len(self._slot_of)

    @property
    def num_registered(self):
        return len(self._registry)

    @property
    def num_pinned(self):
        return int((self._pins[1:] > 0).sum())

    def has(self, adapter_id):
        """True when ``adapter_id`` can be served (registered, or the
        always-available null adapter None/0)."""
        return adapter_id in (None, 0) or adapter_id in self._registry

    def slot_of(self, adapter_id):
        """Resident slab slot of an adapter (None on a miss; 0 for the
        null adapter)."""
        if adapter_id in (None, 0):
            return 0
        return self._slot_of.get(adapter_id)

    def pins(self, adapter_id):
        slot = self._slot_of.get(adapter_id)
        return int(self._pins[slot]) if slot is not None else 0

    def slab_bytes(self):
        n = self.A.nbytes + self.B.nbytes + self.scale.nbytes
        if self.quantized:
            n += self.a_scale.nbytes + self.b_scale.nbytes
        return int(n)

    # -- host-side registry ------------------------------------------------
    def register(self, adapter_id, weights):
        """Register host-side LoRA weights under ``adapter_id``.  No
        device work happens here — the slab is touched on first
        acquire().  Re-registering a resident adapter re-pages it on
        its next miss (the resident copy is invalidated)."""
        if adapter_id in (None, 0):
            raise MXNetError("adapter ids None and 0 are reserved for "
                             "the null adapter")
        L, U = self.config.num_layers, self.config.units
        a, b = np.asarray(weights["A"]), np.asarray(weights["B"])
        r = int(weights["rank"])
        if r > self.max_rank:
            raise MXNetError(f"adapter {adapter_id!r} rank {r} exceeds "
                             f"pool max_rank {self.max_rank}")
        if a.shape != (4, L, U, r) or b.shape != (4, L, r, U):
            raise MXNetError(
                f"adapter {adapter_id!r} shapes A{a.shape} B{b.shape} "
                f"do not match (4, {L}, {U}, {r}) / (4, {L}, {r}, {U})")
        slot = self._slot_of.get(adapter_id)
        if slot is not None and self._pins[slot]:
            raise MXNetError(f"re-registering adapter {adapter_id!r} "
                             "while pinned by active requests")
        self._registry[adapter_id] = {
            "A": a.astype(np.float32), "B": b.astype(np.float32),
            "alpha": float(weights["alpha"]), "rank": r,
        }
        if slot is not None:            # invalidate the stale resident
            self._slot_of.pop(adapter_id)
            self._adapter_at[slot] = None

    def weights(self, adapter_id):
        """The registered host weights (for the merged-weight oracle)."""
        return self._registry[adapter_id]

    # -- residency ---------------------------------------------------------
    @functools.cached_property
    def _upload(self):
        import jax
        # donate the slab so page-in updates in place; `slot` is traced —
        # one compile serves every slot forever
        if self.quantized:
            def upload_q(A, B, scale, a_sc, b_sc, slot, a_pad, b_pad, s,
                         sa, sb):
                return (A.at[:, :, slot].set(a_pad),
                        B.at[:, :, slot].set(b_pad),
                        scale.at[slot].set(s),
                        a_sc.at[:, :, slot].set(sa),
                        b_sc.at[:, :, slot].set(sb))
            return jax.jit(upload_q, donate_argnums=(0, 1, 2, 3, 4))

        def upload(A, B, scale, slot, a_pad, b_pad, s):
            return (A.at[:, :, slot].set(a_pad),
                    B.at[:, :, slot].set(b_pad),
                    scale.at[slot].set(s))
        return jax.jit(upload, donate_argnums=(0, 1, 2))

    @staticmethod
    def _quantize_proj(w):
        """Host-side symmetric int8 quantization of a padded (4, L, …)
        delta slab slice: one absmax/127 scale per (proj, layer)."""
        sa = np.abs(w).max(axis=tuple(range(2, w.ndim))) / 127.0  # (4, L)
        s = sa[..., None, None]
        q = np.where(s > 0, np.round(w / np.maximum(s, 1e-30)), 0.0)
        return np.clip(q, -127, 127).astype(np.int8), \
            sa.astype(np.float32)

    def _page_in(self, slot, adapter_id):
        w = self._registry[adapter_id]
        L, U, R = self.config.num_layers, self.config.units, self.max_rank
        r = w["rank"]
        a_pad = np.zeros((4, L, U, R), np.float32)
        b_pad = np.zeros((4, L, R, U), np.float32)
        a_pad[..., :r] = w["A"]
        b_pad[:, :, :r, :] = w["B"]
        if self.quantized:
            qa, sa = self._quantize_proj(a_pad)
            qb, sb = self._quantize_proj(b_pad)
            (self.A, self.B, self.scale, self.a_scale,
             self.b_scale) = self._upload(
                self.A, self.B, self.scale, self.a_scale, self.b_scale,
                np.int32(slot), qa, qb, np.float32(w["alpha"] / r),
                sa, sb)
        else:
            self.A, self.B, self.scale = self._upload(
                self.A, self.B, self.scale, np.int32(slot),
                a_pad.astype(self.dtype), b_pad.astype(self.dtype),
                np.float32(w["alpha"] / r))
        self._slot_of[adapter_id] = slot
        self._adapter_at[slot] = adapter_id
        self.page_ins += 1

    def effective_weights(self, adapter_id):
        """The weights a served request actually sees: the registered
        host weights, round-tripped through the slab's int8
        quantization when the pool is quantized — feed these to
        ``merged_weights`` to build the dense oracle for a quantized
        pool."""
        w = self._registry[adapter_id]
        if not self.quantized:
            return w
        qa, sa = self._quantize_proj(w["A"])
        qb, sb = self._quantize_proj(w["B"])
        return {
            "A": qa.astype(np.float32) * sa[..., None, None],
            "B": qb.astype(np.float32) * sb[..., None, None],
            "alpha": w["alpha"], "rank": w["rank"],
        }

    def _find_slot(self):
        """A slab slot for a page-in: a never-used slot, else LRU-evict
        an unpinned resident.  None when every slot is pinned."""
        victim, victim_tick = None, None
        for slot in range(1, self.slots):
            if self._adapter_at[slot] is None:
                return slot
            if self._pins[slot] == 0:
                t = self._last_used[slot]
                if victim is None or t < victim_tick:
                    victim, victim_tick = slot, t
        if victim is None:
            return None
        self._slot_of.pop(self._adapter_at[victim], None)
        self._adapter_at[victim] = None
        self.evictions += 1
        return victim

    @loop_only
    def acquire(self, adapter_id):
        """Pin ``adapter_id`` for the lifetime of one active request and
        return its slab slot (paging it in on a miss).  None/0 is the
        null adapter: slot 0, never pinned, never paged."""
        if adapter_id in (None, 0):
            return 0
        if adapter_id not in self._registry:
            raise MXNetError(f"adapter {adapter_id!r} is not registered")
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            slot = self._find_slot()
            if slot is None:
                raise AdapterPoolExhausted(
                    f"adapter slab exhausted: all {self.slots - 1} slots "
                    f"pinned by active requests (adapter {adapter_id!r} "
                    "must wait for a slot to drain)")
            self._page_in(slot, adapter_id)
        self._pins[slot] += 1
        self._tick += 1
        self._last_used[slot] = self._tick
        return slot

    @loop_only
    def release(self, adapter_id):
        """Drop one pin.  The adapter stays resident (warm) until LRU
        eviction needs its slot."""
        if adapter_id in (None, 0):
            return
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            raise MXNetError(f"release of non-resident adapter "
                             f"{adapter_id!r}")
        if self._pins[slot] < 1:
            raise MXNetError(f"pin underflow on adapter {adapter_id!r} "
                             f"(slot {slot})")
        self._pins[slot] -= 1

    @loop_only
    def evict(self, adapter_id):
        """Explicitly drop a resident adapter from the slab (refused
        while pinned).  The slab data is left in place — slot reuse
        overwrites it; correctness only reads slots named by the
        per-request slot ids."""
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            return False
        if self._pins[slot]:
            raise MXNetError(f"evicting adapter {adapter_id!r} with "
                             f"{int(self._pins[slot])} live pin(s)")
        self._slot_of.pop(adapter_id)
        self._adapter_at[slot] = None
        self.evictions += 1
        return True

    @thread_safe
    def audit(self, assignments=None, raise_on_error=False):
        """O(slots) invariant check — the supervisor runs this after
        every caught dispatch fault (next to ``PagePool.audit``) and
        the chaos soak runs it at drain.

        assignments: optional iterable of the adapter_ids currently
        worn by active engine slots (None/0 entries ignored).  When
        given, every assigned adapter must be resident and its pin
        count must equal its assignment count exactly — anything else
        is a leaked or double-counted pin.

        Returns the list of violation strings ([] = clean); with
        raise_on_error=True a non-empty list raises MXNetError.
        """
        v = []
        if self._adapter_at[0] is not None or self._pins[0]:
            v.append("slot 0 (null adapter) is occupied or pinned")
        seen = {}
        for slot in range(1, self.slots):
            aid = self._adapter_at[slot]
            pins = int(self._pins[slot])
            if pins < 0:
                v.append(f"slot {slot}: negative pin count {pins}")
            if aid is None:
                if pins:
                    v.append(f"slot {slot}: {pins} pin(s) on an empty "
                             "slot")
                continue
            if aid in seen:
                v.append(f"adapter {aid!r} resident in slots "
                         f"{seen[aid]} and {slot}")
            seen[aid] = slot
            if self._slot_of.get(aid) != slot:
                v.append(f"slot {slot}: adapter {aid!r} not in the "
                         "resident map (or mapped elsewhere)")
            if aid not in self._registry:
                v.append(f"slot {slot}: resident adapter {aid!r} has no "
                         "host registration")
        for aid, slot in self._slot_of.items():
            if self._adapter_at[slot] != aid:
                v.append(f"resident map says adapter {aid!r} in slot "
                         f"{slot} but the slot holds "
                         f"{self._adapter_at[slot]!r}")
        if assignments is not None:
            want = {}
            for aid in assignments:
                if aid in (None, 0):
                    continue
                want[aid] = want.get(aid, 0) + 1
            for aid, n in want.items():
                slot = self._slot_of.get(aid)
                if slot is None:
                    v.append(f"adapter {aid!r}: {n} active slot(s) but "
                             "not resident")
                    continue
                pins = int(self._pins[slot])
                if pins != n:
                    v.append(f"adapter {aid!r}: pin count {pins} != {n} "
                             "active slot assignment(s)")
            for slot in range(1, self.slots):
                aid = self._adapter_at[slot]
                if aid is not None and aid not in want \
                        and self._pins[slot]:
                    v.append(f"adapter {aid!r}: {int(self._pins[slot])} "
                             "pin(s) with no active slot assignment "
                             "(leaked pin)")
        if v and raise_on_error:
            raise MXNetError("adapter pool audit failed: " + "; ".join(v))
        return v

    def snapshot(self):
        """Introspection block for /statusz."""
        return {
            "slots": self.slots, "max_rank": self.max_rank,
            "registered": self.num_registered,
            "resident": sorted(
                (str(a) for a in self._slot_of), key=str),
            "pinned": {str(a): int(self._pins[s])
                       for a, s in sorted(self._slot_of.items(),
                                          key=lambda kv: kv[1])
                       if self._pins[s]},
            "page_ins": self.page_ins, "evictions": self.evictions,
            "slab_bytes": self.slab_bytes(),
        }

    def __repr__(self):
        return (f"AdapterPool(slots={self.slots}, max_rank="
                f"{self.max_rank}, registered={self.num_registered}, "
                f"resident={self.num_resident}, "
                f"pinned={self.num_pinned})")
