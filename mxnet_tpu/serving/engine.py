"""Continuous-batching serving engine.

Execution model (docs/SERVING.md):

  * B fixed decode SLOTS share one PagedKVCache page pool. Each slot has
    its own live length; the decode forward runs all B slots through the
    ragged paged-attention kernel, so per-token HBM traffic is the sum
    of LIVE lengths, not B × max_length.
  * PREFILL is one compiled program per prompt-length bucket: it writes
    the prompt's KV into the slot's pages (batch-1, attention only over
    the bucket) and samples the request's first token.
  * DECODE runs K steps per host dispatch via lax.scan — the
    TrainStep.run_steps pattern applied to serving. PERF_NOTES measured
    ~24 ms/step of host dispatch tax over a remote tunnel; at one
    token per step that tax would dominate decode, so the block size K
    amortizes it K-fold.
  * Between dispatches the host frees finished slots and admits queued
    requests (FIFO) — continuous batching: nobody waits for the slowest
    sequence in a fixed batch.

Everything per-request (sampling knobs, seeds, eos, budgets) is a
per-slot ARRAY in the compiled program, so admission never recompiles;
the only shape-churn axis is the prefill bucket, and those programs live
in a bounded LRU (gluon.block.LRUTraceCache).
"""
from __future__ import annotations

import itertools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from ..base import MXNetError
from ..gluon.block import LRUTraceCache, _trace_channel
from ..models.kv_cache import PagedKVCache
from ..ndarray.ndarray import NDArray
from ..telemetry import span
from .sampling import sample_tokens, slot_keys
from .scheduler import Request, SlotScheduler

__all__ = ["ServingEngine"]

_engine_ids = itertools.count()

# Engine metrics live as per-engine labeled children (engine=<ordinal>)
# of process-global instruments: `ServingEngine.stats` reads this
# engine's children, the registry/prometheus view aggregates across
# engines. docs/OBSERVABILITY.md catalogs each one.
_E = ("engine",)


def _engine_metrics(eid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    m = {
        "prefills": c("serving_prefill_total",
                      "prefill dispatches (one per admitted request)", _E),
        "decode_dispatches": c("serving_decode_dispatch_total",
                               "compiled K-step decode blocks run", _E),
        "decode_steps": c("serving_decode_steps_total",
                          "decode steps run (dispatches x K)", _E),
        "tokens_emitted": c("serving_tokens_emitted_total",
                            "tokens sampled and handed to requests", _E),
        "requests_finished": c("serving_requests_finished_total",
                               "requests completed (eos or budget)", _E),
        "requests_rejected": c(
            "serving_requests_rejected_total",
            "submissions refused (queue full / prompt too long)", _E),
        "queue_depth": g("serving_queue_depth",
                         "requests waiting for a slot", _E),
        "slot_occupancy": g("serving_slot_occupancy",
                            "slots decoding right now", _E),
        "num_slots": g("serving_slots", "configured decode slots", _E),
        "admission_wait": h("serving_admission_wait_seconds",
                            "submit -> slot admission wait", _E),
        "ttft": h("serving_ttft_seconds",
                  "submit -> first token (queue wait + prefill)", _E),
        "token_latency": h(
            "serving_token_latency_seconds",
            "per-token decode latency at decode-block resolution "
            "(dispatch wall / K, weighted by tokens emitted)", _E),
        "prefill_seconds": h("serving_prefill_seconds",
                             "prefill dispatch wall time", _E),
        "decode_seconds": h("serving_decode_dispatch_seconds",
                            "K-step decode block wall time", _E),
        "drain_seconds": h("serving_drain_seconds",
                           "serve(): last submit -> queue+slots empty", _E),
    }
    return {k: inst.labels(eid) for k, inst in m.items()}


class ServingEngine:
    """Continuous-batching generation over a model with the GPT-2 cache
    contract (forward(ids, cache) -> (logits, cache), make_cache()).

    num_slots: concurrent decode sequences (the compiled batch).
    max_length: per-slot KV capacity (prompt + generated), rounded down
        to a whole number of pages; defaults to the model's max_length.
    page_size: KV page granularity. decode_block: decode steps fused
    into one dispatch. attn_impl: 'auto' (ragged Pallas kernel on TPU,
    dense XLA elsewhere), 'pallas', 'pallas_interpret' (the kernel in
    interpret mode — CPU tests), or 'xla'. max_queue bounds the
    admission queue (None = unbounded); a full queue rejects submit()
    with QueueFullError and counts serving_requests_rejected_total.

    Every engine reports into mx.telemetry as per-engine labeled
    children (docs/OBSERVABILITY.md): TTFT, admission wait, per-token
    decode latency, queue depth, slot occupancy, dispatch counts/wall
    times. `stats` is a dict view of this engine's children;
    `reset_stats()` zeroes them.
    """

    def __init__(self, model, num_slots, max_length=None, page_size=64,
                 decode_block=8, attn_impl="auto", prefill_bucket=None,
                 dtype=None, max_queue=None):
        self.model = model
        cfg = model.config
        self.num_slots = int(num_slots)
        max_length = int(max_length or cfg.max_length)
        max_length -= max_length % page_size
        if max_length < page_size:
            raise MXNetError(f"max_length {max_length} < one page "
                             f"({page_size})")
        if max_length > cfg.max_length:
            raise MXNetError(f"max_length {max_length} exceeds the "
                             f"model's position range {cfg.max_length}")
        self.max_length = max_length
        self.page_size = int(page_size)
        self.decode_block = int(decode_block)
        if self.decode_block < 1:
            raise MXNetError("decode_block must be >= 1")
        self.attn_impl = attn_impl
        self.prefill_bucket = int(prefill_bucket or page_size)
        self.scheduler = SlotScheduler(num_slots, max_queue=max_queue)

        self._params = list(model.collect_params().values())
        B = self.num_slots
        P = max_length // page_size
        dt = dtype or jnp.dtype(cfg.dtype)
        pool_shape = (cfg.num_layers, B * P, page_size, cfg.num_heads,
                      cfg.units // cfg.num_heads)
        self._kp = jnp.zeros(pool_shape, dt)
        self._vp = jnp.zeros(pool_shape, dt)
        self._table = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)
        # per-slot host state (tiny; uploaded per dispatch, fetched back
        # with the decoded tokens — one round trip per K tokens)
        self._lengths = np.zeros(B, np.int32)
        self._cur_tok = np.zeros(B, np.int32)
        self._done = np.ones(B, bool)          # free slots are inactive
        self._remaining = np.zeros(B, np.int32)
        self._counters = np.zeros(B, np.int32)
        self._seeds = np.zeros(B, np.int32)
        self._temp = np.ones(B, np.float32)
        self._top_k = np.zeros(B, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._eos = np.full(B, -1, np.int32)

        self._prefill_programs = LRUTraceCache(
            max(2 * (max_length // self.prefill_bucket), 8))
        self._decode_program = None
        self._eid = str(next(_engine_ids))
        self._metrics = _engine_metrics(self._eid)
        self._metrics["num_slots"].set(self.num_slots)

    # -- telemetry ---------------------------------------------------------
    @property
    def stats(self):
        """This engine's counters/gauges as a plain dict (a live read of
        the telemetry children — the PR-1 bare-dict keys kept intact)."""
        m = self._metrics
        return {
            "prefills": int(m["prefills"].value),
            "decode_dispatches": int(m["decode_dispatches"].value),
            "decode_steps": int(m["decode_steps"].value),
            "tokens_emitted": int(m["tokens_emitted"].value),
            "requests_finished": int(m["requests_finished"].value),
            "requests_rejected": int(m["requests_rejected"].value),
            "queue_depth": int(m["queue_depth"].value),
            "slot_occupancy": int(m["slot_occupancy"].value),
        }

    def reset_stats(self):
        """Zero this engine's telemetry children (other engines and the
        rest of the registry are untouched)."""
        for inst in self._metrics.values():
            inst.reset()
        self._metrics["num_slots"].set(self.num_slots)

    def _set_load_gauges(self):
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        self._metrics["slot_occupancy"].set(self.scheduler.num_active)

    # -- public API --------------------------------------------------------
    def submit(self, request):
        """Queue a Request (validated against this engine's capacity).
        Rejections — over-long prompt, full admission queue — count into
        serving_requests_rejected_total before raising."""
        if request.prompt_len > self.max_length:
            self._metrics["requests_rejected"].inc()
            raise MXNetError(
                f"prompt of {request.prompt_len} tokens exceeds slot "
                f"capacity {self.max_length}")
        request.t_submit = time.perf_counter()
        request.output_tokens = []
        request.token_times = []
        try:
            out = self.scheduler.submit(request)
        except MXNetError:
            self._metrics["requests_rejected"].inc()
            raise
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        return out

    @property
    def has_work(self):
        return self.scheduler.has_work

    def step(self):
        """One scheduling round: admit free slots (prefill), run one
        K-step decode block, free finished slots. Returns the requests
        that finished this round."""
        finished = []
        for slot, req in self.scheduler.admit():
            fin = self._admit(slot, req)
            if fin is not None:
                finished.append(fin)
        self._set_load_gauges()
        if self.scheduler.num_active:
            finished.extend(self._decode_block())
            self._set_load_gauges()
        return finished

    def serve(self, requests=()):
        """Submit `requests`, run until the queue and all slots drain,
        and return every finished request (submission order). Drain wall
        time (last submit -> empty) lands in serving_drain_seconds."""
        for r in requests:
            self.submit(r)
        t_drain0 = time.perf_counter()
        done = []
        with span("serving.drain", engine=self._eid):
            while self.has_work:
                done.extend(self.step())
        self._metrics["drain_seconds"].observe(
            time.perf_counter() - t_drain0)
        done.sort(key=lambda r: r.t_submit)
        return done

    def generate(self, prompts, max_new_tokens, **request_kw):
        """Convenience: serve a list of prompts with shared settings and
        return their generated token lists in order."""
        reqs = [Request(p, max_new_tokens, **request_kw) for p in prompts]
        by_id = {r.id: r for r in reqs}
        self.serve(reqs)
        return [by_id[r.id].output_tokens for r in reqs]

    # -- prefill -----------------------------------------------------------
    def _bucket(self, n):
        b = self.prefill_bucket
        return min(((n + b - 1) // b) * b, self.max_length)

    def _build_prefill(self, t_bucket):
        model, params = self.model, self._params
        table = self._table
        n_pages = t_bucket // self.page_size

        def prefill(param_arrays, kp, vp, ids, slot, true_len, seed,
                    temp, top_k, top_p, do_sample, eos):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                row = jnp.take(table, slot, axis=0)       # (P,)
                cache = PagedKVCache(kp, vp, row[None, :n_pages],
                                     jnp.zeros((), jnp.int32),
                                     attn_impl=self.attn_impl)
                logits, cache = model.forward(NDArray(ids), cache)
            finally:
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            last = jnp.take(logits._data[0], true_len - 1, axis=0)
            key = slot_keys(seed[None], jnp.zeros((1,), jnp.int32))
            first = sample_tokens(last[None], key, do_sample[None],
                                  temp[None], top_k[None], top_p[None])[0]
            done0 = (first == eos) & (eos >= 0)
            return cache.k_pages, cache.v_pages, first, done0

        return jax.jit(prefill, donate_argnums=(1, 2))

    def _admit(self, slot, req):
        Tp = req.prompt_len
        Tb = self._bucket(Tp)
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :Tp] = req.prompt
        fn = self._prefill_programs.get(Tb)
        if fn is None:
            fn = self._build_prefill(Tb)
            self._prefill_programs[Tb] = fn
        param_datas = tuple(p.data()._data for p in self._params)
        i32 = lambda v: jnp.asarray(v, jnp.int32)  # noqa: E731
        t0 = time.perf_counter()
        with span("serving.prefill", engine=self._eid, bucket=Tb):
            kp, vp, first, done0 = fn(
                param_datas, self._kp, self._vp, jnp.asarray(ids),
                i32(slot), i32(Tp), i32(req.seed),
                jnp.asarray(req.temperature, jnp.float32),
                i32(req.top_k), jnp.asarray(req.top_p, jnp.float32),
                jnp.asarray(req.do_sample), i32(
                    -1 if req.eos_token_id is None else req.eos_token_id))
            self._kp, self._vp = kp, vp
            first = int(first)      # host sync: the prefill is done here
        now = time.perf_counter()
        req.t_admit = now
        req.output_tokens.append(first)
        req.token_times.append(now)
        m = self._metrics
        m["prefills"].inc()
        m["tokens_emitted"].inc()
        m["admission_wait"].observe(t0 - req.t_submit)
        m["ttft"].observe(now - req.t_submit)
        m["prefill_seconds"].observe(now - t0)
        # budget: every decode step writes one KV; the last sampled token
        # is never written, so a prompt of Tp supports up to
        # max_length - Tp + 1 generated tokens
        cap = min(req.max_new_tokens, self.max_length - Tp + 1)
        self._lengths[slot] = Tp
        self._cur_tok[slot] = first
        self._remaining[slot] = cap - 1
        self._counters[slot] = 1
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = bool(done0) or cap <= 1
        if self._done[slot]:
            return self._finish(slot)
        return None

    # -- decode ------------------------------------------------------------
    def _build_decode(self):
        model, params = self.model, self._params
        table, K = self._table, self.decode_block
        impl = self.attn_impl

        def decode(param_arrays, kp, vp, lengths, cur_tok, done,
                   remaining, counters, seeds, temp, top_k, top_p,
                   do_sample, eos):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr

                def body(carry, _):
                    (kp, vp, lengths, cur_tok, done, remaining,
                     counters) = carry
                    active = (~done) & (remaining > 0)
                    cache = PagedKVCache(kp, vp, table, lengths,
                                         attn_impl=impl)
                    tok_in = jnp.where(active, cur_tok, 0)
                    logits, cache = model.forward(
                        NDArray(tok_in[:, None]), cache)
                    keys = slot_keys(seeds, counters)
                    nxt = sample_tokens(logits._data[:, -1, :], keys,
                                        do_sample, temp, top_k, top_p)
                    new_len = jnp.where(active, cache.length, lengths)
                    new_rem = jnp.where(active, remaining - 1, remaining)
                    hit_eos = (nxt == eos) & (eos >= 0)
                    new_done = done | (active & (hit_eos
                                                 | (new_rem <= 0)))
                    carry = (cache.k_pages, cache.v_pages, new_len,
                             jnp.where(active, nxt, cur_tok), new_done,
                             new_rem,
                             jnp.where(active, counters + 1, counters))
                    return carry, (jnp.where(active, nxt, -1), active)

                init = (kp, vp, lengths, cur_tok, done, remaining,
                        counters)
                final, (toks, valid) = lax.scan(body, init, None,
                                                length=K)
            finally:
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            return final + (toks, valid)

        return jax.jit(decode, donate_argnums=(1, 2))

    def _decode_block(self):
        if self._decode_program is None:
            self._decode_program = self._build_decode()
        param_datas = tuple(p.data()._data for p in self._params)
        t0 = time.perf_counter()
        with span("serving.decode_block", engine=self._eid,
                  active=self.scheduler.num_active):
            out = self._decode_program(
                param_datas, self._kp, self._vp,
                jnp.asarray(self._lengths),
                jnp.asarray(self._cur_tok), jnp.asarray(self._done),
                jnp.asarray(self._remaining), jnp.asarray(self._counters),
                jnp.asarray(self._seeds), jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._do_sample), jnp.asarray(self._eos))
            (self._kp, self._vp, lengths, cur_tok, done, remaining,
             counters, toks, valid) = out
            # ONE host sync per K decoded tokens: everything small fetches
            # together (the pools stay on device, donated through)
            (self._lengths, self._cur_tok, self._done, self._remaining,
             self._counters) = (
                np.array(lengths), np.array(cur_tok), np.array(done),
                np.array(remaining), np.array(counters))
            toks, valid = np.asarray(toks), np.asarray(valid)
        now = time.perf_counter()
        dt = now - t0
        m = self._metrics
        m["decode_dispatches"].inc()
        m["decode_steps"].inc(self.decode_block)
        m["decode_seconds"].observe(dt)
        finished = []
        n_emitted = 0
        for slot in self.scheduler.active_slots:
            req = self.scheduler.request_at(slot)
            emitted = toks[valid[:, slot], slot]
            req.output_tokens.extend(int(t) for t in emitted)
            req.token_times.extend([now] * emitted.size)
            n_emitted += int(emitted.size)
            if self._done[slot] or self._remaining[slot] <= 0:
                finished.append(self._finish(slot))
        m["tokens_emitted"].inc(n_emitted)
        # block resolution (same convention as the bench): each of the
        # block's tokens cost dt/K of dispatch wall time
        if n_emitted:
            m["token_latency"].observe(dt / self.decode_block, n_emitted)
        return finished

    def _finish(self, slot):
        req = self.scheduler.release(slot)
        req.t_finish = time.perf_counter()
        # freed slots stay inactive (and write nothing) until re-admitted
        self._done[slot] = True
        self._remaining[slot] = 0
        self._metrics["requests_finished"].inc()
        return req
