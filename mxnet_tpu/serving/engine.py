"""Continuous-batching serving engine.

Execution model (docs/SERVING.md):

  * B fixed decode SLOTS share one PagedKVCache page pool. Each slot has
    its own live length; the decode forward runs all B slots through the
    ragged paged-attention kernel, so per-token HBM traffic is the sum
    of LIVE lengths, not B × max_length.
  * PAGE OWNERSHIP is explicit: a host-side ref-counted allocator
    (serving/page_pool.py) hands each admitted request its pages, and a
    radix-tree prefix cache (serving/prefix_cache.py) lets requests
    SHARE the pages of a common prompt prefix — admission does a
    longest-prefix match, maps the cached pages into the slot's table
    by page-table surgery, and prefills only the uncached suffix.
    Shared pages are read-only through the page table (the decode
    kernel is unchanged); the in-program page_lock mask plus a host
    copy-on-write split for fully-cached prompts guarantee no write
    ever lands in a shared page.
  * EVERY dispatch is ONE fixed-shape unified program of width W =
    max(chunk_tokens, spec_tokens, 2): each slot consumes q_counts[b]
    of its W query positions — a PREFILL CHUNK (C tokens of the prompt
    streamed through the span kernel's per-slot query counts), a
    DECODE step (1), a SPECULATIVE VERIFY (1 + drafts), or idle (0).
    Admission never runs a forward: it maps pages, parks the prompt as
    a host-side chunk queue, and the regular dispatch loop feeds
    chunk_tokens of it per tick next to everyone else's decode — so a
    4k-token prompt never monopolizes a dispatch, and prompt length is
    DATA, not a program shape axis (zero prefill retraces, ever).
  * The final chunk of a prompt samples the request's first token in
    the same dispatch; prefill_chunk_budget caps the prompt tokens fed
    per dispatch across all slots (round-robin), bounding every other
    slot's inter-token latency to one dispatch period.
  * SPECULATIVE mode (speculative=True) rides the same program: a
    host-side prompt-lookup drafter (serving/speculative.py) proposes
    up to spec_tokens-1 candidates from each request's own history,
    the span kernel verifies all of them under per-position causal
    offsets, and only the accepted count advances the slot's length —
    greedy output bit-identical to spec-off, sampled output
    distribution-preserving. A degraded engine keeps dispatching the
    same program with zero drafts (bit-identical to plain decode).
  * Per-slot scalar state (lengths, budgets, sampling knobs, tables,
    page_lock) is DEVICE-RESIDENT between dispatches; admission/finish/
    cancel upload one slot's delta in one jitted scatter (_sync_slot),
    so a decode dispatch pays zero host->device state uploads.
  * Between dispatches the host frees finished slots (releasing page
    leases back to the pool/prefix cache) and admits queued requests
    (FIFO) — continuous batching: nobody waits for the slowest
    sequence in a fixed batch.

Everything per-request (sampling knobs, seeds, eos, budgets, chunk
cursors) is a per-slot ARRAY in the compiled program, so admission
never recompiles: the engine owns at most two programs (greedy-only
and mixed-sampling flavors of the one unified dispatch) for its whole
lifetime — there is no prefill program family and no bucket axis.

ROBUSTNESS (docs/SERVING.md "Robustness"): step() is supervised — a
dispatch exception no longer wedges the engine. The supervisor catches
it, audits the page pool, rolls the implicated slots back (leases
released, state parked), re-queues innocents with backoff, and
quarantines a request whose dispatches fail `max_retries` times
(terminal reason="error"). Requests carry deadlines (expired queued
work is shed before admission; running work past deadline is cancelled
at the next dispatch boundary) and priority classes; an attached
SheddingPolicy (serving/policy.py) sheds or down-prioritizes work
before it queues and latches graceful degradation under sustained
overload. A re-queued, partially-decoded request restarts by
prefilling prompt+emitted and resuming its RNG counter at the next
token index — per-request streams are keyed (seed, token_index), so
restarted outputs are bit-identical to an uninterrupted run.
"""
from __future__ import annotations

import inspect
import itertools
import time
import weakref
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry
from ..analysis import loop_only, supervised, thread_safe
from ..telemetry import cost as _cost
from ..telemetry import ledger as _ledger
from ..base import MXNetError
from ..gluon.block import _trace_channel
from ..models.kv_cache import (PagedKVCache, gather_kv_pages,
                               scatter_kv_pages)
from ..ndarray.ndarray import NDArray
from ..telemetry import server as _tserver
from ..telemetry import span
from ..models.gpt2 import set_adapter_ctx as _set_adapter_ctx
from ..models.gpt2 import set_tp_ctx as _set_tp_ctx
from ..parallel.mesh import (AXIS_TP, PartitionSpec, named_sharding,
                             serving_tp_mesh, shard_map_compat)
from ..parallel.rules import serving_tp_rules
from .adapters import AdapterPoolExhausted
from .host_tier import HostPagePool
from .page_pool import PagePool, PagePoolExhausted
from .prefix_cache import PrefixCache
from .sampling import sample_tokens, slot_keys
from .weight_quant import (build_weight_plan, deregister_w8_weight,
                           register_w8_weight)
from .scheduler import (QueueFullError, Request, ShedError,
                        SlotScheduler, TenantQuotaError, _seq_counter)
from .speculative import PromptLookupProposer, verify_tokens

__all__ = ["ServingEngine"]

_engine_ids = itertools.count()

# Engine metrics live as per-engine labeled children (engine=<ordinal>)
# of process-global instruments: `ServingEngine.stats` reads this
# engine's children, the registry/prometheus view aggregates across
# engines. docs/OBSERVABILITY.md catalogs each one.
_E = ("engine",)


def _engine_metrics(eid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    m = {
        "prefills": c("serving_prefill_total",
                      "prompts fully prefilled — final chunk landed and "
                      "the first token sampled (one per admission)", _E),
        "prefill_tokens": c(
            "serving_prefill_tokens_total",
            "prompt tokens actually computed by prefill chunks (the "
            "uncached suffix only when the prefix cache hits)", _E),
        "prefill_chunks": c(
            "serving_prefill_chunks_total",
            "prompt chunks fed through the unified dispatch (a prompt "
            "of T uncached tokens streams in ceil(T / chunk_tokens) "
            "chunks, budget permitting)", _E),
        "prefill_pending": g(
            "serving_prefill_pending_tokens",
            "chunk-queue depth: admitted prompt tokens not yet fed to "
            "a dispatch, summed over slots", _E),
        "decode_dispatches": c("serving_decode_dispatch_total",
                               "unified dispatches run (one fixed-shape "
                               "program per tick)", _E),
        "decode_steps": c("serving_decode_steps_total",
                          "decode steps run (== dispatches: one "
                          "forward per tick)", _E),
        "tokens_emitted": c("serving_tokens_emitted_total",
                            "tokens sampled and handed to requests", _E),
        "requests_finished": c("serving_requests_finished_total",
                               "requests completed (eos or budget)", _E),
        "requests_rejected": c(
            "serving_requests_rejected_total",
            "submissions refused (queue full / prompt too long)", _E),
        "requests_cancelled": c(
            "serving_requests_cancelled_total",
            "requests aborted via cancel() (queued or running)", _E),
        "prefix_hits": c(
            "serving_prefix_cache_hits_total",
            "admissions whose prompt matched >= 1 cached page", _E),
        "prefix_misses": c(
            "serving_prefix_cache_misses_total",
            "admissions with no cached prefix", _E),
        "prefix_tokens_saved": c(
            "serving_prefix_tokens_saved_total",
            "prompt tokens skipped at prefill (attached from cache)", _E),
        "prefix_evicted_pages": c(
            "serving_prefix_cache_evicted_pages_total",
            "cached pages reclaimed by the LRU-by-leaf policy", _E),
        "spec_draft_tokens": c(
            "serving_spec_draft_tokens_total",
            "draft tokens proposed by the prompt-lookup drafter", _E),
        "spec_accepted_tokens": c(
            "serving_spec_accepted_tokens_total",
            "draft tokens accepted by verification and emitted", _E),
        "spec_rollbacks": c(
            "serving_spec_rollbacks_total",
            "draft tokens rejected by verification (their KV stays "
            "invisible and is overwritten in place)", _E),
        "model_flops": c(
            "serving_model_flops_total",
            "registered cost_analysis FLOPs of every dispatched "
            "prefill/decode/verify program (goodput numerator)", _E),
        "wasted_flops": c(
            "serving_wasted_flops_total",
            "FLOPs spent on drafted-but-rejected speculative "
            "positions (program FLOPs x rejected share)", _E),
        "flops_per_token": g(
            "serving_flops_per_token",
            "model FLOPs per emitted token (goodput: "
            "model_flops_total / tokens_emitted_total)", _E),
        "admission_capacity": g(
            "serving_admission_capacity",
            "estimated max concurrent requests at the current page "
            "budget: active slots + (free + idle cached pages) / "
            "pages per slot", _E),
        "queue_depth": g("serving_queue_depth",
                         "requests waiting for a slot", _E),
        "slot_occupancy": g("serving_slot_occupancy",
                            "slots decoding right now", _E),
        "num_slots": g("serving_slots", "configured decode slots", _E),
        "prefix_cache_pages": g(
            "serving_prefix_cache_pages",
            "KV pages held by the prefix-cache radix tree", _E),
        "prefix_pages_shared": g(
            "serving_prefix_pages_shared",
            "pool pages currently mapped by more than one lease", _E),
        "pool_free_pages": g("serving_page_pool_free",
                             "unallocated pages in the KV page pool", _E),
        "admission_wait": h("serving_admission_wait_seconds",
                            "submit -> slot admission wait", _E),
        "ttft": h("serving_ttft_seconds",
                  "submit -> first token (queue wait + prefill)", _E),
        "token_latency": h(
            "serving_token_latency_seconds",
            "per-token decode latency at dispatch resolution "
            "(dispatch wall / tokens the slot emitted, weighted)", _E),
        "prefill_seconds": h("serving_prefill_seconds",
                             "wall time of unified dispatches that "
                             "carried at least one prefill chunk", _E),
        "decode_seconds": h("serving_decode_dispatch_seconds",
                            "unified dispatch wall time", _E),
        "drain_seconds": h("serving_drain_seconds",
                           "serve(): last submit -> queue+slots empty", _E),
        "dispatch_errors": c(
            "serving_dispatch_errors_total",
            "dispatch faults the engine supervisor caught (batch rolled "
            "back, engine kept serving)", _E),
        "dispatch_retries": c(
            "serving_dispatch_retries_total",
            "requests re-queued with backoff after a caught dispatch "
            "fault or transient allocation failure", _E),
        "requests_failed": c(
            "serving_requests_failed_total",
            "requests quarantined after max_retries failed dispatches "
            "(terminal reason=\"error\")", _E),
        "overload_level": g(
            "serving_overload_level",
            "shedding-policy assessment: 0 ok, 1 elevated, "
            "2 overloaded", _E),
        "degraded": g(
            "serving_degraded",
            "1 while the engine is gracefully degraded (speculation "
            "suspended, /healthz flagged)", _E),
        "retry_after": g(
            "serving_retry_after_seconds",
            "drain-rate estimate of when a rejected submission could "
            "succeed (attached to shed / queue-full rejections)", _E),
        "adapter_page_ins": c(
            "serving_adapter_page_ins_total",
            "LoRA adapters paged into the device slab (slab-slot scatter "
            "on an acquire miss)", _E),
        "adapter_evictions": c(
            "serving_adapter_evictions_total",
            "resident LoRA adapters LRU-evicted to make room for a "
            "page-in (plus explicit evict() calls)", _E),
        "adapter_resident": g(
            "serving_adapter_resident",
            "LoRA adapters currently resident in the device slab", _E),
        "adapter_pinned": g(
            "serving_adapter_pinned",
            "slab slots pinned by active requests (unevictable)", _E),
        "adapter_slab_bytes": g(
            "serving_adapter_slab_bytes",
            "device bytes held by the LoRA adapter slab (A + B + "
            "scale)", _E),
        "kv_quant_enabled": g(
            "serving_kv_quant_enabled",
            "1 when the KV page pools store int8 codes with per-page "
            "dequant scales (kv_dtype=\"int8\"), else 0", _E),
        "kv_page_bytes": g(
            "serving_kv_page_bytes",
            "HBM bytes one KV page really costs: k+v slabs across all "
            "layers plus the per-page dequant scales when quantized", _E),
        "kv_bytes_per_token": g(
            "serving_kv_bytes_per_token",
            "KV-cache HBM bytes per token position "
            "(kv_page_bytes / page_size) — the capacity headline int8 "
            "pages shrink ~4x", _E),
        "tp_shards": g(
            "serving_tp_shards",
            "tensor-parallel shards the unified dispatch runs across "
            "(head-wise shard_map over the tp mesh axis; 1 = "
            "unsharded)", _E),
        "weight_quant_enabled": g(
            "serving_weight_quant_enabled",
            "1 when the engine serves the megatron col/row dense "
            "weights as int8 codes with fused per-out-tile dequant "
            "(weight_dtype=\"int8\"), else 0", _E),
        "kv_spill_pages": c(
            "serving_kv_spill_pages_total",
            "KV pages whose payload moved device -> host RAM "
            "(prefix-cache eviction spills plus whole-request "
            "preemption swaps)", _E),
        "kv_spill_bytes": c(
            "serving_kv_spill_bytes_total",
            "bytes admitted to the host spill tier", _E),
        "kv_pagein_pages": c(
            "serving_kv_pagein_pages_total",
            "KV pages restored host -> device (radix hits on spilled "
            "nodes plus preemption resumes)", _E),
        "kv_pagein_bytes": c(
            "serving_kv_pagein_bytes_total",
            "bytes read back from the host tier by page-ins", _E),
        "kv_host_evictions": c(
            "serving_kv_host_evictions_total",
            "spilled payloads LRU-dropped by the host tier to admit "
            "newer spills (that state re-prefills if hit again)", _E),
        "preempts": c(
            "serving_preempt_total",
            "running requests preempted by the shedding policy to "
            "free a slot for more-urgent queued work", _E),
        "preempt_resumed": c(
            "serving_preempt_resumed_total",
            "preempted requests spliced straight back into decode "
            "from their swapped KV (no re-prefill)", _E),
        "preempt_restarted": c(
            "serving_preempt_restarted_total",
            "preempted requests that fell back to the replay/restart "
            "path (swap payload or prefix nodes gone) — output still "
            "bit-identical, compute is not saved", _E),
        "kv_spill_seconds": h(
            "serving_kv_spill_seconds",
            "wall time of one spill batch (device page gather + host "
            "copy)", _E),
        "kv_pagein_seconds": h(
            "serving_kv_pagein_seconds",
            "wall time of one page-in batch (host read + device page "
            "scatter)", _E),
        "kv_host_pages": g(
            "serving_kv_host_pages",
            "payload entries resident in the host spill tier", _E),
        "kv_host_bytes": g(
            "serving_kv_host_bytes",
            "host-RAM bytes the spill tier currently holds", _E),
        "prefix_resident_pages": g(
            "serving_prefix_resident_pages",
            "radix-tree nodes whose KV page is device-resident "
            "(published even with the spill tier off, so tier "
            "occupancy is always observable)", _E),
        "prefix_spilled_pages": g(
            "serving_prefix_spilled_pages",
            "radix-tree nodes whose KV payload lives in the host "
            "tier", _E),
    }
    _shed_family()                  # registered per-process; children
    _tenant_families()
    _ttft_family()
    _ttft_phase_family()
    _weight_bytes_family()
    return {k: inst.labels(eid) for k, inst in m.items()}


def _ttft_family():
    """TTFT split by power-of-two prompt-length bucket AND the KV tier
    the admission landed on: the chunked-prefill TTFT model
    (docs/SERVING.md) predicts TTFT grows with ceil(prompt /
    chunk_tokens) dispatch periods, and the tier label is how
    p99-under-tiered-load is attributed — a host page-in admission
    (`spilled`) pays transfer latency a `resident` radix hit never
    sees."""
    return telemetry.histogram(
        "serving_ttft_by_prompt_seconds",
        "submit -> first token, split by power-of-two prompt-length "
        "bucket (label prompt_bucket=le<N>) and KV tier "
        "(kv_tier=resident|spilled|cold)",
        ("engine", "prompt_bucket", "kv_tier"))


def _ttft_phase_family():
    """The TTFT phase budget, aggregated: every first token observes
    one sample per recorded phase (queue_wait, prefix_match,
    host_pagein, prefill_chunks, first_decode — telemetry.PHASES),
    labeled with the admission's KV tier, so p99 TTFT decomposes into
    WHERE the time went without reading per-request timelines."""
    return telemetry.histogram(
        "serving_ttft_phase_seconds",
        "per-request TTFT phase durations (label phase=one of "
        "telemetry.PHASES, kv_tier=resident|spilled|cold)",
        ("engine", "phase", "kv_tier"))


def _weight_bytes_family():
    """Served weight bytes split by storage dtype (ISSUE 19): with
    weight_dtype="int8" the `int8` child is the code slabs and the
    `float32` child is everything still full-width (embeddings, the
    tied LM head, norms, biases, the dequant scales); w8-off puts the
    whole slab under `float32`. The dtype split IS the capacity
    headline — `bench.py gpt2_serving_w8` gates on the ~4x shrink."""
    return telemetry.gauge(
        "serving_weight_bytes",
        "device bytes of the served weight operands, by storage dtype "
        "(int8 code slabs vs float32 params + dequant scales)",
        ("engine", "dtype"))


def _shed_family():
    """The one three-label family: shed traffic split by reason AND the
    shed request's priority class (aggregate reads stay cheap; the
    split is what capacity debugging needs)."""
    return telemetry.counter(
        "serving_shed_total",
        "requests shed by the robustness layer, by reason (queue_full, "
        "overload, deadline, deadline_queued, deadline_running) and "
        "priority class", ("engine", "reason", "priority"))


def _tenant_families():
    """Per-tenant families (labeled {engine, tenant}); children are
    created lazily as tenants appear in traffic, so an engine without
    tenant_quotas pays nothing."""
    return {
        "admitted": telemetry.counter(
            "serving_tenant_admitted_total",
            "requests admitted to a decode slot, split by tenant",
            ("engine", "tenant")),
        "shed": telemetry.counter(
            "serving_tenant_shed_total",
            "requests shed or rejected, split by tenant and reason "
            "(tenant_quota adds the per-tenant queue bound to the "
            "engine-wide taxonomy)", ("engine", "tenant", "reason")),
        "active": telemetry.gauge(
            "serving_tenant_active_slots",
            "decode slots currently held by each tenant",
            ("engine", "tenant")),
        "queued": telemetry.gauge(
            "serving_tenant_queued",
            "queued (admitted-but-waiting) requests per tenant",
            ("engine", "tenant")),
    }


class ServingEngine:
    """Continuous-batching generation over a model with the GPT-2 cache
    contract (forward(ids, cache) -> (logits, cache), make_cache()).

    num_slots: concurrent decode sequences (the compiled batch).
    max_length: per-slot KV capacity (prompt + generated), rounded down
        to a whole number of pages; defaults to the model's max_length.
    page_size: KV page granularity. chunk_tokens: prompt tokens one
    slot feeds per dispatch while prefilling (default page_size) — the
    dispatch width is W = max(chunk_tokens, spec_tokens, 2), fixed for
    the engine's lifetime. prefill_chunk_budget: prompt tokens per
    dispatch across ALL slots (default chunk_tokens), round-robined so
    concurrent long prompts share the prefill lane fairly while decode
    rows ride every dispatch untouched. decode_block / prefill_bucket
    are accepted for compatibility and ignored — there is no K-step
    scan and no bucket axis anymore. attn_impl: 'auto' (ragged Pallas
    kernel on TPU, dense XLA elsewhere), 'pallas', 'pallas_interpret'
    (the kernel in interpret mode — CPU tests), or 'xla'. max_queue
    bounds the admission queue (None = unbounded); a full queue rejects
    submit() with QueueFullError and counts
    serving_requests_rejected_total.

    prefix_cache=True turns on radix-tree prompt reuse: admission
    longest-prefix-matches each prompt against previously served ones
    and attaches the shared KV pages instead of recomputing them.
    prefix_cache_pages sizes BOTH the extra physical pages added to the
    pool for retained prefixes and the tree's eviction budget (default:
    one full slot-set, num_slots * pages_per_slot). Sampled output is
    bit-identical with the cache on or off.

    speculative=True turns on prompt-lookup speculative decoding
    (serving/speculative.py, docs/SERVING.md): each dispatch feeds up
    to spec_tokens positions per decoding slot — the current token
    plus up to spec_tokens-1 n-gram drafts from the request's own
    history — and the same unified forward verifies all of them.
    Greedy output is bit-identical to speculative=False; sampled output
    is distribution-preserving and reproducible across schedules.
    spec_max_ngram/spec_min_ngram bound the lookup n-gram sizes.

    Every engine reports into mx.telemetry as per-engine labeled
    children (docs/OBSERVABILITY.md): TTFT, admission wait, per-token
    decode latency, queue depth, slot occupancy, dispatch counts/wall
    times, prefix-cache hits/misses/tokens-saved/evictions. `stats` is
    a dict view of this engine's children; `reset_stats()` zeroes them.
    """

    def __init__(self, model, num_slots, max_length=None, page_size=64,
                 decode_block=None, attn_impl="auto", prefill_bucket=None,
                 chunk_tokens=None, prefill_chunk_budget=None,
                 dtype=None, max_queue=None, prefix_cache=False,
                 prefix_cache_pages=None, speculative=False,
                 spec_tokens=4, spec_max_ngram=3, spec_min_ngram=1,
                 num_priorities=3, policy=None, max_retries=3,
                 retry_backoff_s=0.02, clock=None, adapter_pool=None,
                 tenant_quotas=None, kv_dtype=None,
                 hbm_budget_bytes=None, host_kv_bytes=None, tp=1,
                 tp_devices=None, weight_dtype=None,
                 hbm_budget_includes_weights=False):
        self.model = model
        cfg = model.config
        self.num_slots = int(num_slots)
        max_length = int(max_length or cfg.max_length)
        max_length -= max_length % page_size
        if max_length < page_size:
            raise MXNetError(f"max_length {max_length} < one page "
                             f"({page_size})")
        if max_length > cfg.max_length:
            raise MXNetError(f"max_length {max_length} exceeds the "
                             f"model's position range {cfg.max_length}")
        self.max_length = max_length
        self.page_size = int(page_size)
        # legacy knobs of the bucketed/K-step engine: accepted so old
        # configs keep constructing, but the unified dispatch has no
        # bucket axis and no step fusion for them to tune
        self.decode_block = decode_block
        self.prefill_bucket = prefill_bucket
        self.attn_impl = attn_impl
        # tensor-parallel serving (docs/SERVING.md "Tensor-parallel
        # serving"): tp > 1 runs the ONE unified program shard_map'ed
        # over a {tp: N} mesh — qkv/fc1 column-parallel, proj/fc2
        # row-parallel, KV pages split on the HEAD axis, one psum per
        # projection reassembling full activations so the in-program
        # sampler sees full logits on every shard. Shard count is a
        # construction-time MODE, never a program shape axis: tp=1
        # builds the exact pre-tp program, and a tp=N engine still owns
        # at most two compiled programs for its lifetime.
        self._tp = int(tp or 1)
        if self._tp < 1:
            raise MXNetError(f"tp must be >= 1, got {tp}")
        if self._tp > 1:
            if cfg.num_heads % self._tp:
                raise MXNetError(
                    f"tp={self._tp} must divide num_heads "
                    f"({cfg.num_heads}) — the KV pool and the qkv/proj "
                    "weights shard head-wise")
            if cfg.hidden_size % self._tp:
                raise MXNetError(
                    f"tp={self._tp} must divide the FFN hidden size "
                    f"({cfg.hidden_size}) — fc1/fc2 shard on it")
        self._mesh = serving_tp_mesh(self._tp, devices=tp_devices)
        self.chunk_tokens = int(chunk_tokens or page_size)
        if self.chunk_tokens < 1:
            raise MXNetError("chunk_tokens must be >= 1")
        self.prefill_chunk_budget = int(
            prefill_chunk_budget or self.chunk_tokens)
        if self.prefill_chunk_budget < 1:
            raise MXNetError("prefill_chunk_budget must be >= 1")
        self.speculative = bool(speculative)
        self.spec_tokens = int(spec_tokens)
        if self.speculative:
            if self.spec_tokens < 2:
                raise MXNetError("spec_tokens must be >= 2 (the current "
                                 "token + at least one draft)")
            self._proposer = PromptLookupProposer(
                self.spec_tokens - 1, max_ngram=spec_max_ngram,
                min_ngram=spec_min_ngram)
            # per-slot token history (prompt + emitted) the prompt-lookup
            # drafter matches against — the request's OWN history only,
            # so drafting is schedule-independent
            self._hist = [None] * int(num_slots)
        # ONE dispatch width forever: wide enough for a prefill chunk,
        # a speculative verify window, or a decode step (>= 2 keeps
        # every dispatch on the span kernel's multi-query path)
        self._width = max(self.chunk_tokens,
                          self.spec_tokens if self.speculative else 0, 2)
        self.scheduler = SlotScheduler(num_slots, max_queue=max_queue,
                                       num_priorities=num_priorities,
                                       tenant_quotas=tenant_quotas)
        # robustness layer (docs/SERVING.md "Robustness"): supervisor
        # retry budget + backoff, optional shedding policy, and an
        # injectable clock so deadline/backoff behavior is testable
        # without wall-time races (the default IS perf_counter)
        self.policy = policy
        self.max_retries = int(max_retries)
        if self.max_retries < 1:
            raise MXNetError("max_retries must be >= 1")
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock if clock is not None else time.perf_counter
        self._degraded = False
        self._draining = False
        self._finish_times = deque(maxlen=64)   # drain-rate window
        # extra lease rows audit_pages() should account for (the
        # fault-injection harness registers pages it holds here)
        self.audit_extra_leases = []

        self._params = list(model.collect_params().values())
        if self._mesh is not None:
            # per-param layout from the serving tp rules (embeddings +
            # LM head replicated, qkv/fc1 column-, proj/fc2 row-
            # parallel; unmatched leaves replicated). Weights are
            # placed onto the mesh ONCE and cached by array identity
            # (_placed) — a dispatch never re-shards them.
            rules = serving_tp_rules(AXIS_TP)
            self._param_specs = tuple(
                rules.spec_for(name) or PartitionSpec()
                for name in model.collect_params().keys())
            self._placed = {}
        else:
            self._param_specs = None
            self._placed = None
        self._slab_cache = None
        # w8 weight serving (docs/SERVING.md "Weight quantization"): the
        # megatron col/row dense weights are quantized ONCE here to int8
        # codes with per-out-tile f32 scales (per shard for the column
        # split, shard-invariant for the row split — see
        # serving/weight_quant.py). The code arrays ride the SAME
        # dispatch operand positions and PartitionSpecs the fp32 weights
        # did, the scales travel as extra operands, and the dequant is
        # fused into FullyConnected as an output epilogue. Weight
        # identity stays runtime data: w8 on/off never adds a program
        # shape axis, and w8-off builds the exact pre-w8 program.
        if weight_dtype is not None:
            try:
                w8_ok = jnp.dtype(weight_dtype) == jnp.int8
            except TypeError:
                w8_ok = False
            if not w8_ok:
                raise MXNetError(f"weight_dtype {weight_dtype!r} "
                                 "unsupported (int8 or None)")
        self._w8 = weight_dtype is not None
        self.weight_dtype = "int8" if self._w8 \
            else str(jnp.dtype(dtype or jnp.dtype(cfg.dtype)))
        self._w8_plan = ()
        self._w8_codes = {}
        self._w8_scale_ops = ()
        if self._w8:
            plan = build_weight_plan(model.collect_params().items(),
                                     tp=self._tp, tp_axis=AXIS_TP,
                                     max_shards=cfg.num_heads)
            if not plan:
                raise MXNetError(
                    "weight_dtype='int8' found no megatron col/row "
                    "dense weights to quantize on this model")
            self._w8_plan = tuple(plan)
            self._w8_codes = {q.index: q.codes for q in plan}
            if self._mesh is not None:
                self._w8_scale_ops = tuple(
                    jax.device_put(
                        q.scale,
                        named_sharding(q.scale_spec, mesh=self._mesh))
                    for q in plan)
            else:
                self._w8_scale_ops = tuple(q.scale for q in plan)
        # byte-denominated weight accounting (feeds the
        # serving_weight_bytes{dtype} gauges, /statusz, the HBM ledger
        # and — when hbm_budget_includes_weights — the page budget):
        # int8 = code slabs, float32 = everything else incl. the dequant
        # scales; per-chip divides sharded arrays by tp.
        wb_int8 = wb_fp = wb_chip = 0
        w8_by_idx = {q.index: q for q in self._w8_plan}
        for i, p in enumerate(self._params):
            d = p.data()._data
            spec = self._param_specs[i] if self._param_specs else None
            div = self._tp if (spec is not None
                               and any(a is not None for a in spec)) \
                else 1
            q = w8_by_idx.get(i)
            if q is not None:
                cb = int(q.codes.size)          # 1 B/element
                sb = int(q.scale.size) * 4
                s_div = self._tp if any(a is not None
                                        for a in q.scale_spec) else 1
                wb_int8 += cb
                wb_fp += sb
                wb_chip += cb // div + sb // s_div
            else:
                nb = int(d.size) * jnp.dtype(d.dtype).itemsize
                wb_fp += nb
                wb_chip += nb // div
        self._weight_bytes = {"int8": int(wb_int8),
                              "float32": int(wb_fp)}
        self._weight_bytes_per_chip = int(wb_chip)
        B = self.num_slots
        P = self._pages_per_slot = max_length // page_size
        # pool sizing: every slot can always claim a full P exclusive
        # pages (worst case, zero sharing) + `extra` pages so the prefix
        # cache can retain prefixes across request lifetimes
        extra = 0
        if prefix_cache:
            extra = B * P if prefix_cache_pages is None \
                else int(prefix_cache_pages)
            if extra < 0:
                raise MXNetError("prefix_cache_pages must be >= 0")
        total_pages = B * P + extra
        dt = dtype or jnp.dtype(cfg.dtype)
        # quantized page mode (docs/SERVING.md "Quantized KV pages"):
        # int8 codes + per-(layer, page, head) f32 dequant scales kept
        # as separate pool leaves. page_bytes is the HONEST per-page
        # HBM cost (k+v slabs across all layers, plus scales) — the
        # byte-denominated budget below trades the ~4x smaller pages
        # for MORE pages, i.e. real admitted capacity.
        if kv_dtype is not None:
            try:
                ok = jnp.dtype(kv_dtype) == jnp.int8
            except TypeError:
                ok = False
            if not ok:
                raise MXNetError(f"kv_dtype {kv_dtype!r} unsupported "
                                 "(int8 or None)")
        self._quant = kv_dtype is not None
        self.kv_dtype = "int8" if self._quant else str(jnp.dtype(dt))
        store = jnp.dtype(jnp.int8) if self._quant else jnp.dtype(dt)
        L, H = cfg.num_layers, cfg.num_heads
        Dh = cfg.units // cfg.num_heads
        page_bytes = 2 * L * page_size * H * Dh * store.itemsize
        if self._quant:
            page_bytes += 2 * L * H * 4    # f32 scales ride each page
        self._hbm_budget = None if hbm_budget_bytes is None \
            else int(hbm_budget_bytes)
        self._hbm_includes_weights = bool(hbm_budget_includes_weights)
        if self._hbm_budget is not None:
            # under tp each CHIP holds 1/tp of every page (the head
            # axis shards), so the budget — the quantity that actually
            # OOMs — is per chip and buys tp x the pages
            page_budget = self._hbm_budget
            if self._hbm_includes_weights:
                # the served weight slab comes out of the same per-chip
                # HBM the pages do: charging it here is what turns the
                # w8 ~4x weight shrink into ADMITTED pages (the
                # gpt2_serving_w8 bench runs both engines at one fixed
                # budget where fp32 weights are the binding constraint)
                page_budget -= self._weight_bytes_per_chip
                if page_budget <= 0:
                    raise MXNetError(
                        f"hbm_budget_bytes {self._hbm_budget} is below "
                        f"the {self._weight_bytes_per_chip} B/chip the "
                        f"{self.weight_dtype} weights alone need")
            chip_page = page_bytes // self._tp
            afford = page_budget // chip_page
            if afford < P:
                raise MXNetError(
                    f"hbm_budget_bytes {self._hbm_budget} affords "
                    f"{afford} pages at {chip_page} B/page/chip — below "
                    f"the {P} pages one full-length slot needs")
            total_pages = min(total_pages, afford)
        pool_shape = (L, total_pages, page_size, H, Dh)
        self._kp = jnp.zeros(pool_shape, store)
        self._vp = jnp.zeros(pool_shape, store)
        if self._quant:
            self._ks = jnp.zeros((L, total_pages, H), jnp.float32)
            self._vs = jnp.zeros((L, total_pages, H), jnp.float32)
        else:
            self._ks = self._vs = None
        if self._mesh is not None:
            # the pools LIVE sharded (global shape above, head axis
            # split over the mesh): every eager page op — scrub, CoW
            # copy, scale zeroing — follows the input layout, and the
            # unified dispatch's donation keeps the shards in place
            kv_sh = named_sharding(self._kv_pspec(), mesh=self._mesh)
            self._kp = jax.device_put(self._kp, kv_sh)
            self._vp = jax.device_put(self._vp, kv_sh)
            if self._quant:
                sc_sh = named_sharding(self._scale_pspec(),
                                       mesh=self._mesh)
                self._ks = jax.device_put(self._ks, sc_sh)
                self._vs = jax.device_put(self._vs, sc_sh)
        self.page_pool = PagePool(total_pages, page_bytes=page_bytes)
        self.prefix_cache = PrefixCache(self.page_pool, page_size,
                                        budget_pages=extra) \
            if prefix_cache else None
        # host-RAM KV spill tier (docs/SERVING.md "Tiered KV cache"):
        # an evicted prefix page spills its payload (codes AND the int8
        # scale leaves) to host RAM instead of vanishing, a radix hit
        # on a spilled node pages it back in, and preemption swaps
        # whole requests out through the same tier. All tier traffic
        # runs OUTSIDE the traced dispatch — two tiny fixed-shape
        # jitted page programs plus explicit transfers — so the
        # unified program and steady_state_compiles never see it.
        self._host_kv_bytes = None if host_kv_bytes is None \
            else int(host_kv_bytes)
        self.host_pool = None
        if self._host_kv_bytes is not None:
            if self.prefix_cache is None:
                raise MXNetError("host_kv_bytes needs prefix_cache=True "
                                 "— the spill tier is keyed by radix "
                                 "nodes")
            self.host_pool = HostPagePool(self._host_kv_bytes,
                                          evict_cb=self._host_evict)
            self.prefix_cache.evict_hook = self._spill_node
            self.prefix_cache.pagein_hook = self._pagein_nodes
        # tier transfer programs: ONE fixed index width (P = pages
        # per slot) however many pages move. Gather pads its index
        # with page 0 and the host slices the valid prefix after
        # device_get; scatter pads with an out-of-range id that
        # mode="drop" ignores. Gather must NOT donate (the pools
        # live on); scatter donates them like every dispatch.
        # under tp>1 the scatter pins out_shardings to the pools'
        # own shardings: the donated outputs must come back in
        # EXACTLY the layout the dispatch expects (XLA would
        # otherwise return a spec-normalized NamedSharding that
        # misses the dispatch cache key). tp=1 must NOT pin — the
        # pool chain is uncommitted end to end, and committing it
        # here would mint a second pjit entry in every downstream
        # page program. Built whether or not a host tier is on: the
        # same movers carry the cross-process prefill->decode handoff
        # (export_handoff / _adopt_payload, serving/fleet) — jit is
        # lazy, so an engine that never moves a page never traces them.
        pin = self._tp > 1
        if self._quant:
            def _tier_gather_q(kp, vp, ks, vs, idx):
                return gather_kv_pages(kp, vp, idx, ks, vs)

            def _tier_scatter_q(kp, vp, ks, vs, idx, kv, vv,
                                ksv, vsv):
                return scatter_kv_pages(kp, vp, idx, kv, vv,
                                        ks, vs, ksv, vsv)

            self._tier_gather_fn = jax.jit(_tier_gather_q)
            self._tier_scatter_fn = jax.jit(
                _tier_scatter_q, donate_argnums=(0, 1, 2, 3),
                out_shardings=(
                    (self._kp.sharding, self._vp.sharding,
                     self._ks.sharding, self._vs.sharding)
                    if pin else None))
        else:
            def _tier_gather_f(kp, vp, idx):
                return gather_kv_pages(kp, vp, idx)[:2]

            def _tier_scatter_f(kp, vp, idx, kv, vv):
                return scatter_kv_pages(kp, vp, idx, kv, vv)[:2]

            self._tier_gather_fn = jax.jit(_tier_gather_f)
            self._tier_scatter_fn = jax.jit(
                _tier_scatter_f, donate_argnums=(0, 1),
                out_shardings=(
                    (self._kp.sharding, self._vp.sharding)
                    if pin else None))
        # per-slot page tables are HOST state now (page-table surgery at
        # admission); uploaded with each dispatch
        self._table_host = np.zeros((B, P), np.int32)
        self._mapped = np.zeros(B, bool)   # slot holds page leases
        # per-slot host state (tiny; uploaded per dispatch, fetched back
        # with the decoded tokens — one round trip per K tokens).
        # Unmapped slots park at length == max_length: their in-program
        # decode writes fall off the page table and DROP, so a freed
        # slot can never scribble on pages that were recycled to a new
        # owner or retained by the prefix cache.
        self._lengths = np.full(B, self.max_length, np.int32)
        self._cur_tok = np.zeros(B, np.int32)
        self._done = np.ones(B, bool)          # free slots are inactive
        self._remaining = np.zeros(B, np.int32)
        self._counters = np.zeros(B, np.int32)
        self._seeds = np.zeros(B, np.int32)
        self._temp = np.ones(B, np.float32)
        self._top_k = np.zeros(B, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._eos = np.full(B, -1, np.int32)
        # multi-tenant LoRA (serving/adapters.py, docs/SERVING.md
        # "Multi-tenant LoRA serving"): the pool's slab is device-
        # resident; each slot carries its adapter's SLAB SLOT index as
        # one more per-slot scalar (0 = null adapter = exact zeros), so
        # adapter identity is runtime data — never a program shape axis
        self.adapter_pool = adapter_pool
        self._aslot = np.zeros(B, np.int32)
        self._adapter_of = [None] * B   # slot -> pinned adapter_id

        # per-slot chunk queues: the not-yet-fed tail of each admitted
        # prompt (np.int32; None = slot has no prefill work). The
        # dispatch loop drains them chunk_tokens at a time under the
        # prefill_chunk_budget, starting at a rotating slot cursor.
        self._pending = [None] * B
        self._base = np.zeros(B, np.int32)   # resume offset per slot
        # quantized restart replay: when a slot re-prefills a request
        # that already emitted tokens, this holds the exact chunk sizes
        # to feed (deque; None = feed on the natural chunk_tokens
        # grid). See _admit — per-page dequant scales make deep-layer
        # KV codes chunk-boundary-dependent, so only replaying the
        # recorded write schedule keeps the continuation bit-identical.
        self._replay = [None] * B
        self._chunk_rr = 0
        # the unified program comes in two flavors selected PER
        # DISPATCH: the general mixed-sampling one and a greedy-only
        # one that skips the filtered-distribution sort and the RNG
        # draws entirely (greedy batches dominate production serving;
        # greedy rows are bit-identical through either program). These
        # two keys are the engine's ENTIRE program registry.
        self._programs = {}

        if self._quant:
            def _copy_page(kp, vp, ks, vs, src, dst):
                # CoW split: the dequant scales are part of a page's
                # identity — they travel with the slab on every clone
                return (kp.at[:, dst].set(kp[:, src]),
                        vp.at[:, dst].set(vp[:, src]),
                        ks.at[:, dst].set(ks[:, src]),
                        vs.at[:, dst].set(vs[:, src]))

            self._copy_page_fn = jax.jit(_copy_page,
                                         donate_argnums=(0, 1, 2, 3))

            def _zero_scales(ks, vs, idx):
                # fresh pages must start from scale 0 or the monotone
                # max-update would inherit a recycled page's old scale;
                # idx is FIXED-length (padded with an out-of-range id
                # that mode="drop" ignores) so admissions never mint
                # new program shapes in steady state
                z = jnp.zeros((), jnp.float32)
                return (ks.at[:, idx].set(z, mode="drop"),
                        vs.at[:, idx].set(z, mode="drop"))

            self._zero_scales_fn = jax.jit(_zero_scales,
                                           donate_argnums=(0, 1))
        else:
            def _copy_page(kp, vp, src, dst):
                # CoW split: clone one physical page's (L, S, H, D) slab
                return (kp.at[:, dst].set(kp[:, src]),
                        vp.at[:, dst].set(vp[:, src]))

            self._copy_page_fn = jax.jit(_copy_page,
                                         donate_argnums=(0, 1))
        # the per-slot scalar state is DEVICE-RESIDENT between decode
        # dispatches: the decode program reads these arrays directly and
        # returns the updated ones, and the host uploads deltas only on
        # admission/finish/cancel (_sync_slot) — not ~12 small
        # jnp.asarray transfers on every dispatch
        self._upload_fn = self._build_slot_upload()
        scalars = [self._lengths, self._cur_tok, self._done,
                   self._remaining, self._counters, self._seeds,
                   self._temp, self._top_k, self._top_p,
                   self._do_sample, self._eos]
        if self.adapter_pool is not None:
            scalars.append(self._aslot)
        self._dstate = tuple(self._rep(jnp.asarray(a))
                             for a in scalars + [self._table_host])
        self._d_lock = self._rep(jnp.asarray(self._page_lock_host()))
        self._eid = str(next(_engine_ids))
        self._metrics = _engine_metrics(self._eid)
        self._metrics["num_slots"].set(self.num_slots)
        self._wbytes_fam = _weight_bytes_family()
        self._set_static_gauges()
        self._shed = _shed_family()
        self._shed_children = {}   # (reason, priority) -> labeled child
        self._shed_counts = {}     # same keys, host-side for stats
        self._ttft_fam = _ttft_family()
        self._ttft_children = {}   # (prompt bucket, tier) -> child
        self._phase_fam = _ttft_phase_family()
        self._phase_children = {}  # (phase, tier) -> labeled child
        # TTFT phase-budget bookkeeping (docs/OBSERVABILITY.md "Phase
        # taxonomy"): per-admission host page-in accumulator (the
        # prefix-cache pagein hook fires inside _map_slot_pages, so
        # per-request attribution needs this bracket), the KV tier the
        # admission landed on, and the prefill chunks fed per slot
        self._pagein_acc = 0.0
        self._kv_tier = ["cold"] * B
        self._chunks_fed = np.zeros(B, np.int32)
        self._tenant_fams = _tenant_families()
        self._tenant_children = {}   # (family, tenant[, reason]) -> child
        self._tenant_shed_counts = {}  # (tenant, reason) -> n
        self._tenants_seen = set()
        self._adapter_page_ins_seen = 0
        self._adapter_evictions_seen = 0
        self._hook_kw_cache = None
        # a collected engine must not leave /healthz stuck degraded
        weakref.finalize(self, _tserver.clear_degraded,
                         f"engine{self._eid}")
        self._evictions_seen = 0
        self._host_evictions_seen = 0
        self._set_pool_gauges()
        # live introspection: /statusz shows this engine's config +
        # occupancy, the flight-recorder watchdog probes its progress
        # (both hold weak refs — a collected engine just drops out),
        # and every request records a lifecycle timeline into
        # telemetry.request_log. dispatch_hook is a test/extension
        # seam called at the top of every step().
        self.dispatch_hook = None
        # device-cost accounting (telemetry.cost, docs/OBSERVABILITY.md
        # "Device-cost accounting"): every program this engine builds is
        # wrapped in a CostedFunction keyed engine<eid>/<program>, so
        # compiles are attributed and MFU/roofline gauges go live.
        # mark_warm() flips the steady flag: any compile after that is a
        # retrace storm the flight recorder latches a dump for.
        self._steady = False
        telemetry.register_status_provider(
            f"engine/{self._eid}", self._statusz)
        telemetry.flight.watch(f"engine{self._eid}", self._flight_probe)
        # /readyz: readiness (warmed AND not degraded AND not draining)
        # is per-component state, distinct from /healthz liveness — an
        # intentionally-draining replica is healthy but not ready
        _tserver.register_ready_probe(f"engine{self._eid}",
                                      self._ready_probe)
        weakref.finalize(self, _tserver.unregister_ready_probe,
                         f"engine{self._eid}")
        # HBM ledger: weights + KV page slab + device-resident slot
        # state, with the prefix-cache-held page subset as an
        # informational detail (it lives inside kv_pages)
        _ledger.register(f"engine/{self._eid}", self._hbm_ledger)

    # -- telemetry ---------------------------------------------------------
    @property
    def stats(self):
        """This engine's counters/gauges as a plain dict (a live read of
        the telemetry children — the PR-1 bare-dict keys kept intact)."""
        m = self._metrics
        return {
            "prefills": int(m["prefills"].value),
            "prefill_tokens": int(m["prefill_tokens"].value),
            "prefill_chunks": int(m["prefill_chunks"].value),
            "prefill_pending": int(m["prefill_pending"].value),
            "decode_dispatches": int(m["decode_dispatches"].value),
            "decode_steps": int(m["decode_steps"].value),
            "tokens_emitted": int(m["tokens_emitted"].value),
            "requests_finished": int(m["requests_finished"].value),
            "requests_rejected": int(m["requests_rejected"].value),
            "requests_cancelled": int(m["requests_cancelled"].value),
            "prefix_hits": int(m["prefix_hits"].value),
            "prefix_misses": int(m["prefix_misses"].value),
            "prefix_tokens_saved": int(m["prefix_tokens_saved"].value),
            "prefix_evicted_pages": int(m["prefix_evicted_pages"].value),
            "spec_draft_tokens": int(m["spec_draft_tokens"].value),
            "spec_accepted_tokens": int(m["spec_accepted_tokens"].value),
            "spec_rollbacks": int(m["spec_rollbacks"].value),
            "model_flops": int(m["model_flops"].value),
            "wasted_flops": int(m["wasted_flops"].value),
            "admission_capacity": int(m["admission_capacity"].value),
            "prefix_cache_pages": int(m["prefix_cache_pages"].value),
            "prefix_pages_shared": int(m["prefix_pages_shared"].value),
            "pool_free_pages": int(m["pool_free_pages"].value),
            "queue_depth": int(m["queue_depth"].value),
            "slot_occupancy": int(m["slot_occupancy"].value),
            "dispatch_errors": int(m["dispatch_errors"].value),
            "dispatch_retries": int(m["dispatch_retries"].value),
            "requests_failed": int(m["requests_failed"].value),
            "overload_level": int(m["overload_level"].value),
            "degraded": int(m["degraded"].value),
            "draining": self._draining,
            "shed": sum(self._shed_counts.values()),
            "adapter_page_ins": int(m["adapter_page_ins"].value),
            "adapter_evictions": int(m["adapter_evictions"].value),
            "adapter_resident": int(m["adapter_resident"].value),
            "adapter_pinned": int(m["adapter_pinned"].value),
            "kv_quant_enabled": int(m["kv_quant_enabled"].value),
            "kv_page_bytes": int(m["kv_page_bytes"].value),
            "kv_bytes_per_token": float(
                m["kv_bytes_per_token"].value),
            "tp_shards": int(m["tp_shards"].value),
            "weight_quant_enabled": int(
                m["weight_quant_enabled"].value),
            "weight_bytes_int8": self._weight_bytes["int8"],
            "weight_bytes_float32": self._weight_bytes["float32"],
            "weight_bytes_total": (self._weight_bytes["int8"]
                                   + self._weight_bytes["float32"]),
            "weight_bytes_per_chip": self._weight_bytes_per_chip,
            "kv_spill_pages": int(m["kv_spill_pages"].value),
            "kv_spill_bytes": int(m["kv_spill_bytes"].value),
            "kv_pagein_pages": int(m["kv_pagein_pages"].value),
            "kv_pagein_bytes": int(m["kv_pagein_bytes"].value),
            "kv_host_evictions": int(m["kv_host_evictions"].value),
            "kv_host_pages": int(m["kv_host_pages"].value),
            "kv_host_bytes": int(m["kv_host_bytes"].value),
            "prefix_resident_pages": int(
                m["prefix_resident_pages"].value),
            "prefix_spilled_pages": int(
                m["prefix_spilled_pages"].value),
            "preempts": int(m["preempts"].value),
            "preempt_resumed": int(m["preempt_resumed"].value),
            "preempt_restarted": int(m["preempt_restarted"].value),
        }

    def tenant_stats(self):
        """Per-tenant occupancy + lifetime accounting: the scheduler's
        queued/active/admitted/quota view plus this engine's shed
        taxonomy split by tenant. Keys are stringified tenant ids."""
        out = self.scheduler.tenants_snapshot()
        for (tenant, reason), n in sorted(self._tenant_shed_counts.items()):
            row = out.setdefault(str(tenant), {})
            row.setdefault("shed", {})[reason] = n
        return out

    def _set_static_gauges(self):
        """Configuration gauges — set at construction and re-applied
        after reset_stats (they describe the engine, not traffic)."""
        pb = self.page_pool.page_bytes
        self._metrics["kv_quant_enabled"].set(int(self._quant))
        self._metrics["kv_page_bytes"].set(pb)
        self._metrics["kv_bytes_per_token"].set(pb / self.page_size)
        self._metrics["tp_shards"].set(self._tp)
        self._metrics["weight_quant_enabled"].set(int(self._w8))
        for wd, nb in self._weight_bytes.items():
            self._wbytes_fam.labels(self._eid, wd).set(nb)

    def reset_stats(self):
        """Zero this engine's telemetry children (other engines and the
        rest of the registry are untouched)."""
        for inst in self._metrics.values():
            inst.reset()
        for child in self._shed_children.values():
            child.reset()
        self._metrics["num_slots"].set(self.num_slots)
        self._set_static_gauges()
        self._shed_counts = {}
        for child in self._tenant_children.values():
            child.reset()
        self._tenant_shed_counts = {}
        for child in self._ttft_children.values():
            child.reset()
        self._adapter_page_ins_seen = 0
        self._adapter_evictions_seen = 0
        self._metrics["num_slots"].set(self.num_slots)
        self._set_pool_gauges()

    def _shed_inc(self, reason, priority, tenant=None):
        key = (reason, int(priority))
        child = self._shed_children.get(key)
        if child is None:
            child = self._shed.labels(self._eid, reason, str(priority))
            self._shed_children[key] = child
        child.inc()
        self._shed_counts[key] = self._shed_counts.get(key, 0) + 1
        if tenant is not None:
            self._tenant_child("shed", tenant, reason).inc()
            tk = (tenant, reason)
            self._tenant_shed_counts[tk] = \
                self._tenant_shed_counts.get(tk, 0) + 1

    def _tenant_child(self, family, tenant, reason=None):
        key = (family, tenant) if reason is None \
            else (family, tenant, reason)
        child = self._tenant_children.get(key)
        if child is None:
            fam = self._tenant_fams[family]
            child = fam.labels(self._eid, str(tenant)) if reason is None \
                else fam.labels(self._eid, str(tenant), reason)
            self._tenant_children[key] = child
        self._tenants_seen.add(tenant)
        return child

    def _observe_ttft(self, prompt_len, dt, kv_tier="cold"):
        """The labeled TTFT-vs-prompt-length child (power-of-two
        buckets x KV tier; children created lazily as combinations
        appear in traffic)."""
        b = 1
        while b < prompt_len:
            b <<= 1
        key = (f"le{b}", kv_tier)
        child = self._ttft_children.get(key)
        if child is None:
            child = self._ttft_fam.labels(self._eid, key[0], kv_tier)
            self._ttft_children[key] = child
        child.observe(dt)

    def _phase(self, req, name, dur, **attrs):
        """Record one TTFT phase span: trace event + per-request
        accumulation (`req.phases` — it rides the Request through
        export/adopt, which is what keeps a migrated request's phase
        budget continuous). Disabled with the request log for honest
        A/B overhead runs."""
        if not telemetry.request_log.enabled:
            return
        dur = max(float(dur), 0.0)
        ph = getattr(req, "phases", None)
        if not isinstance(ph, dict):
            ph = req.phases = {}
        ph[name] = ph.get(name, 0.0) + dur
        telemetry.request_log.phase(req.id, self._eid, name, dur,
                                    **attrs)

    def _observe_phase_budget(self, req, kv_tier):
        """Publish the request's accumulated phase budget into the
        phase histogram at first token (one sample per phase)."""
        ph = getattr(req, "phases", None)
        if not isinstance(ph, dict):
            return
        for name, dur in ph.items():
            key = (name, kv_tier)
            child = self._phase_children.get(key)
            if child is None:
                child = self._phase_fam.labels(self._eid, name, kv_tier)
                self._phase_children[key] = child
            child.observe(dur)

    def _set_load_gauges(self):
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        self._metrics["slot_occupancy"].set(self.scheduler.num_active)
        self._metrics["admission_capacity"].set(
            self.admission_capacity_estimate())
        self._set_tenant_gauges()

    def _set_tenant_gauges(self):
        # one pass over the scheduler's queues/actives; zero the gauges
        # of tenants seen earlier but absent now so they don't stick
        sched = self.scheduler
        if not sched.tenant_quotas and not self._tenants_seen:
            return
        queued, active = {}, {}
        for q in sched._queues:
            for req in q:
                if req.tenant is not None:
                    queued[req.tenant] = queued.get(req.tenant, 0) + 1
        for req in sched._active.values():
            if req.tenant is not None:
                active[req.tenant] = active.get(req.tenant, 0) + 1
        for t in (set(queued) | set(active) | set(sched.tenant_quotas)
                  | self._tenants_seen):
            if t is None:
                continue
            self._tenant_child("queued", t).set(queued.get(t, 0))
            self._tenant_child("active", t).set(active.get(t, 0))

    def admission_capacity_estimate(self):
        """Max concurrent requests the current page budget supports:
        the slots already decoding plus how many more worst-case
        (full-length, zero-sharing) requests the pool could map —
        idle prefix-cache pages count as reclaimable. Derived from the
        same accounting the HBM ledger reports, published as
        serving_admission_capacity (never above num_slots)."""
        free = self.page_pool.num_free
        if self.prefix_cache is not None:
            idle = int((self.prefix_cache.member_mask()
                        & (self.page_pool.refcounts() == 0)).sum())
            free += idle
        return min(self.scheduler.num_active + free // self._pages_per_slot,
                   self.num_slots)

    def _set_pool_gauges(self):
        m = self._metrics
        m["pool_free_pages"].set(self.page_pool.num_free)
        m["prefix_pages_shared"].set(
            int(self.page_pool.shared_mask().sum()))
        pc = self.prefix_cache
        if pc is not None:
            m["prefix_cache_pages"].set(pc.num_pages)
            m["prefix_resident_pages"].set(pc.num_resident)
            m["prefix_spilled_pages"].set(pc.num_spilled)
            delta = pc.evicted_pages - self._evictions_seen
            if delta:
                m["prefix_evicted_pages"].inc(delta)
                self._evictions_seen = pc.evicted_pages
        hp = self.host_pool
        if hp is not None:
            m["kv_host_pages"].set(hp.num_entries)
            m["kv_host_bytes"].set(hp.bytes_used)
            delta = hp.evictions - self._host_evictions_seen
            if delta:
                m["kv_host_evictions"].inc(delta)
                self._host_evictions_seen = hp.evictions
        pool = self.adapter_pool
        if pool is not None:
            m["adapter_resident"].set(pool.num_resident)
            m["adapter_pinned"].set(pool.num_pinned)
            m["adapter_slab_bytes"].set(pool.slab_bytes())
            delta = pool.page_ins - self._adapter_page_ins_seen
            if delta:
                m["adapter_page_ins"].inc(delta)
                self._adapter_page_ins_seen = pool.page_ins
            delta = pool.evictions - self._adapter_evictions_seen
            if delta:
                m["adapter_evictions"].inc(delta)
                self._adapter_evictions_seen = pool.evictions

    def _statusz(self):
        """The /statusz + flight-recorder view of this engine: static
        config, the scheduler's slot/queue snapshot, and the headline
        rates derived from this engine's counters."""
        s = self.stats
        lookups = s["prefix_hits"] + s["prefix_misses"]
        drafted = s["spec_draft_tokens"]
        return {
            "config": {
                "num_slots": self.num_slots,
                "max_length": self.max_length,
                "page_size": self.page_size,
                "chunk_tokens": self.chunk_tokens,
                "prefill_chunk_budget": self.prefill_chunk_budget,
                "dispatch_width": self._width,
                "attn_impl": self.attn_impl,
                "prefix_cache": self.prefix_cache is not None,
                "speculative": self.speculative,
                "spec_tokens": self.spec_tokens
                if self.speculative else None,
                "max_queue": self.scheduler.max_queue,
                "num_priorities": self.scheduler.num_priorities,
                "max_retries": self.max_retries,
                "retry_backoff_s": self.retry_backoff_s,
                "total_pages": self.page_pool.num_pages,
                "kv_dtype": self.kv_dtype,
                "kv_page_bytes": self.page_pool.page_bytes,
                "weight_dtype": self.weight_dtype,
                "weight_bytes": dict(self._weight_bytes),
                "weight_bytes_per_chip": self._weight_bytes_per_chip,
                "quantized_weights": len(self._w8_plan),
                "hbm_budget_bytes": self._hbm_budget,
                "hbm_budget_includes_weights":
                    self._hbm_includes_weights,
                "host_kv_bytes": self._host_kv_bytes,
                "steady_state": self._steady,
                "adapter_pool": self.adapter_pool is not None,
                "adapter_slots": self.adapter_pool.slots
                if self.adapter_pool is not None else None,
                "adapter_max_rank": self.adapter_pool.max_rank
                if self.adapter_pool is not None else None,
                "tp_shards": self._tp,
            },
            "sharding": None if self._mesh is None else {
                "tp_shards": self._tp,
                "mesh_devices": [str(d)
                                 for d in self._mesh.devices.flat],
                "heads_per_shard":
                    self.model.config.num_heads // self._tp,
                "kv_page_bytes_per_chip":
                    self.page_pool.page_bytes // self._tp,
                "replicated": ["embeddings", "lm_head", "layernorm",
                               "page_table", "page_lock",
                               "slot_scalars", "logits"],
            },
            "admission_capacity": self.admission_capacity_estimate(),
            "kv_tier": None if self.host_pool is None else {
                "host_budget_bytes": self.host_pool.budget_bytes,
                "host_bytes_used": self.host_pool.bytes_used,
                "host_entries": self.host_pool.num_entries,
                "host_evictions": self.host_pool.evictions,
                "resident_pages": self.prefix_cache.num_resident,
                "spilled_pages": self.prefix_cache.num_spilled,
                "spilled_total": self.prefix_cache.spilled_pages,
                "paged_in_total": self.prefix_cache.paged_in_pages,
            },
            "robustness": {
                "degraded": self._degraded,
                "draining": self._draining,
                "warmed": self._steady,
                "overload_level": int(s["overload_level"]),
                "policy": None if self.policy is None
                else self.policy.snapshot(),
                "shed": {f"{r}/p{p}": n
                         for (r, p), n in sorted(self._shed_counts.items())},
                "quarantined": int(s["requests_failed"]),
                "dispatch_errors": int(s["dispatch_errors"]),
                "retry_after_s": self.estimated_queue_wait(),
            },
            "scheduler": self.scheduler.snapshot(),
            "tenants": self.tenant_stats(),
            "adapters": self.adapter_pool.snapshot()
            if self.adapter_pool is not None else None,
            "prefix_hit_rate": s["prefix_hits"] / lookups
            if lookups else None,
            "spec_acceptance": s["spec_accepted_tokens"] / drafted
            if drafted else None,
            "stats": s,
        }

    def _flight_probe(self):
        """Watchdog probe (telemetry.flight): progress is the count of
        host-visible scheduling events; busy while work is pending. A
        busy engine whose progress freezes is a stalled dispatch loop."""
        m = self._metrics
        progress = int(m["prefills"].value
                       + m["decode_dispatches"].value
                       + m["requests_finished"].value
                       + m["requests_cancelled"].value
                       + m["requests_failed"].value
                       + m["dispatch_retries"].value
                       + sum(self._shed_counts.values()))
        return progress, self.scheduler.has_work

    # -- device-cost accounting --------------------------------------------
    def mark_warm(self):
        """Declare warmup over: every program this engine should ever
        need is compiled. Any compile after this point is steady-state
        shape churn — the compile still succeeds, but the event is
        flagged and an armed flight recorder latches a
        `retrace_storm:<program>` dump naming the offending key."""
        self._steady = True

    def _steady_probe(self):
        return self._steady

    def _program(self, name):
        """Program-signature key for telemetry.cost: engine-scoped so
        two engines with different model configs never share (and so
        poison) one cost record."""
        return f"engine{self._eid}/{name}"

    def _wrap_program(self, fn, name, cost_scale=1.0):
        # shards: under SPMD, cost_analysis() reports PER-PARTITION
        # figures — the cost layer re-multiplies registration to
        # whole-model and divides the per-chip MFU/bandwidth gauges
        return _cost.CostedFunction(fn, self._program(name),
                                    steady_fn=self._steady_probe,
                                    cost_scale=cost_scale,
                                    shards=self._tp)

    def _account_flops(self, program, wall, wasted_fraction=0.0):
        """Per-dispatch device-cost bookkeeping: attribute the wall to
        the program (live MFU/bandwidth gauges) and advance this
        engine's goodput counters from the program's registered FLOPs."""
        rec = _cost.note_dispatch(program, wall)
        if rec is None or not rec.flops:
            return
        m = self._metrics
        m["model_flops"].inc(rec.flops)
        if wasted_fraction > 0.0:
            m["wasted_flops"].inc(rec.flops * wasted_fraction)
        tokens = m["tokens_emitted"].value
        if tokens:
            m["flops_per_token"].set(m["model_flops"].value / tokens)

    def _hbm_ledger(self):
        """telemetry.ledger provider: where this engine's HBM goes.
        Weights are shared arrays (the ledger dedupes them across
        engines); the prefix-cache figure is a Detail — those pages
        live inside the kv_pages slab already counted above."""
        kv = [self._kp, self._vp]
        if self._quant:
            kv += [self._ks, self._vs]   # dequant scales live with KV
        # w8: the slab the engine SERVES is int8 codes + dequant scales
        # for the quantized weights (plus the still-fp32 leftovers); the
        # model's original fp32 arrays for those weights are a Detail —
        # retained by the owning net, not part of the serving deployment
        if self._w8:
            weights = [self._w8_codes[i] if i in self._w8_codes
                       else p.data()
                       for i, p in enumerate(self._params)]
            weights += list(self._w8_scale_ops)
        else:
            weights = [p.data() for p in self._params]
        out = {
            "weights": weights,
            "kv_pages": kv,
            "slot_state": list(self._dstate) + [self._d_lock],
        }
        if self._w8:
            shadow = sum(
                int(p.data()._data.size
                    * jnp.dtype(p.data()._data.dtype).itemsize)
                for i, p in enumerate(self._params)
                if i in self._w8_codes)
            out["weights_fp32_shadow"] = _ledger.Detail(shadow)
        pool = self.adapter_pool
        if pool is not None:
            slab = [pool.A, pool.B, pool.scale]
            if pool.quantized:
                slab += [pool.a_scale, pool.b_scale]
            out["adapter_slab"] = slab
        # gluon-initialized params usually carry gradient buffers even
        # when only serving — account them so /memz reconciles
        grads = [g for g in (getattr(p._data, "_grad", None)
                             for p in self._params if p._data is not None)
                 if g is not None]
        if grads:
            out["weight_grads"] = grads
        pc = self.prefix_cache
        if pc is not None:
            out["prefix_cache_pages"] = _ledger.Detail(
                pc.num_pages * self.page_pool.page_bytes)
        if self.host_pool is not None:
            # host-tier bytes are NOT HBM: a Detail row so /memz shows
            # the spill tier next to the device figures it relieves,
            # without polluting the accounted device total
            out["host_kv"] = _ledger.Detail(self.host_pool.bytes_used)
        return out

    # -- admission control -------------------------------------------------
    def _drain_rate(self):
        """Recent finishes per second (None until two finishes land in
        the window) — the denominator of every retry-after estimate."""
        ft = self._finish_times
        if len(ft) < 2:
            return None
        dt = ft[-1] - ft[0]
        if dt <= 0:
            return None
        return (len(ft) - 1) / dt

    def estimated_queue_wait(self):
        """Seconds until the current backlog would drain at the recent
        finish rate — the retry-after estimate rejections carry and the
        deadline-feasibility signal the shedding policy uses. None when
        the engine has no recent drain history."""
        rate = self._drain_rate()
        if rate is None:
            return None
        return self.scheduler.num_queued / rate

    def estimated_drain_wait(self):
        """Seconds until EVERYTHING in flight (queued + active) would
        complete at the recent finish rate — the retry-after estimate a
        draining replica attaches to its rejections (retrying sooner
        than the drain completes cannot succeed)."""
        rate = self._drain_rate()
        if rate is None:
            return None
        return (self.scheduler.num_queued
                + self.scheduler.num_active) / rate

    def _reject(self, request, reason, cause=None):
        """Common rejection tail: count, record the terminal timeline
        with structured context, and raise (the scheduler's
        QueueFullError enriched in place, or a fresh ShedError)."""
        depth = self.scheduler.num_queued
        active = self.scheduler.num_active
        wait = self.estimated_drain_wait() if self._draining \
            else self.estimated_queue_wait()
        if wait is not None:
            self._metrics["retry_after"].set(wait)
        request.status = "shed"
        self._metrics["requests_rejected"].inc()
        self._shed_inc(reason, request.priority, request.tenant)
        telemetry.request_log.terminal(
            request.id, self._eid, "rejected", reason=reason,
            priority=request.priority, prompt_len=request.prompt_len,
            queue_depth=depth, active_slots=active,
            retry_after_s=None if wait is None else round(wait, 4))
        suffix = (f" [queue_depth={depth}, active_slots={active}"
                  + (f", retry_after~{wait:.3f}s" if wait is not None
                     else "") + "]")
        if cause is not None:
            telemetry.flight.note_queue_full(f"engine{self._eid}")
            cause.queue_depth = depth
            cause.active_slots = active
            cause.retry_after_s = wait
            cause.args = (str(cause.args[0]) + suffix,)
            raise cause
        telemetry.flight.note_shed(f"engine{self._eid}")
        raise ShedError(
            f"request {request.id} shed ({reason})" + suffix,
            reason=reason, queue_depth=depth, active_slots=active,
            retry_after_s=wait, priority=request.priority)

    # -- drain / readiness (serving/router.py consumes these) --------------
    @property
    def draining(self):
        return self._draining

    @property
    def drained(self):
        """True once a drain() completed: admission closed AND no
        queued or running work remains (slots and pages all released —
        audit_pages() is clean here by construction)."""
        return self._draining and not self.scheduler.has_work

    @property
    def warmed(self):
        """True after mark_warm(): every program is compiled."""
        return self._steady

    def is_ready(self):
        """Readiness for new traffic: warmed AND not degraded AND not
        draining — the /readyz conjunction. Liveness is separate: a
        not-ready engine still serves its in-flight work."""
        return self._steady and not self._degraded \
            and not self._draining

    def _ready_probe(self):
        return {"warmed": self._steady, "degraded": self._degraded,
                "draining": self._draining}

    @thread_safe
    def drain(self):
        """Begin a rolling-restart drain: new submit() rejects with
        ShedError(reason="draining", retry_after_s=<drain estimate>),
        while queued and running requests keep being served by step()
        until the engine is empty (`drained` flips True, page audit
        clean). Rejoin the fleet with undrain(); readiness also needs
        mark_warm() (a restarted replica recompiles). Idempotent."""
        if self._draining:
            return
        self._draining = True
        telemetry.flight.record("draining", engine=self._eid)

    @thread_safe
    def undrain(self):
        """Reopen admission after a drain (no-op when not draining)."""
        if not self._draining:
            return
        self._draining = False
        telemetry.flight.record("undrained", engine=self._eid)

    # -- public API --------------------------------------------------------
    @loop_only
    def submit(self, request):
        """Queue a Request (validated against this engine's capacity).
        Rejections — over-long prompt, full admission queue, policy
        shed — count into serving_requests_rejected_total (sheds also
        into serving_shed_total{reason,priority}) AND record a terminal
        `rejected` timeline with queue depth / active slots / a
        retry-after estimate, so /requests shows rejected traffic too,
        then raise."""
        if request.prompt_len > self.max_length:
            self._metrics["requests_rejected"].inc()
            telemetry.request_log.terminal(
                request.id, self._eid, "rejected",
                reason="prompt_too_long",
                prompt_len=request.prompt_len)
            raise MXNetError(
                f"prompt of {request.prompt_len} tokens exceeds slot "
                f"capacity {self.max_length}")
        if request.adapter_id not in (None, 0):
            pool = self.adapter_pool
            if pool is None or not pool.has(request.adapter_id):
                self._metrics["requests_rejected"].inc()
                telemetry.request_log.terminal(
                    request.id, self._eid, "rejected",
                    reason="unknown_adapter",
                    adapter_id=str(request.adapter_id))
                raise MXNetError(
                    f"adapter {request.adapter_id!r} is not registered "
                    + ("(engine has no adapter pool)" if pool is None
                       else "with this engine's adapter pool"))
        if self._draining:
            self._reject(request, "draining")
        now = self._clock()
        request.t_submit = now
        request.t_deadline = None if request.deadline_ms is None \
            else now + request.deadline_ms / 1e3
        request.output_tokens = []
        request.token_times = []
        request.dispatch_failures = 0
        request.t_not_before = 0.0
        if self.policy is not None:
            action, reason = self.policy.on_submit(self, request, now)
            if action == "shed":
                self._reject(request, reason)
        try:
            out = self.scheduler.submit(request)
        except QueueFullError as e:
            self._reject(request,
                         "tenant_quota" if isinstance(e, TenantQuotaError)
                         else "queue_full", cause=e)
        request.status = "queued"
        request.phases = {}
        request.t_enqueue = now
        t = getattr(request, "trace", None) or {}
        tr = telemetry.request_log.begin(
            request.id, self._eid, trace_id=t.get("trace_id"),
            prompt_len=request.prompt_len,
            max_new_tokens=request.max_new_tokens,
            priority=request.priority,
            deadline_ms=request.deadline_ms,
            parent_span=t.get("parent_span"))
        if tr is not None and not t:
            # no upstream trace context (direct engine submit): the
            # trace id minted here still rides the Request so a later
            # migration/hedge correlates to ONE trace
            request.trace = {"trace_id": tr.trace_id}
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        return out

    @loop_only
    def cancel(self, request_id):
        """Abort a request by id, queued OR running. A queued request is
        simply dequeued; a running one releases its slot and its page
        leases immediately (tokens already emitted stay on the Request).

        Idempotent: returns the cancelled Request on success, or False
        when the id is unknown to the scheduler — never submitted, or
        already terminal (finished/cancelled/shed/failed). The
        late-cancel leg of the disconnect vs natural-finish race is
        therefore a no-op that records no second terminal timeline
        event. Call from the serving thread — cancellation mutates slot
        state between dispatches."""
        req = self.scheduler.cancel_queued(request_id)
        if req is None:
            slot = self.scheduler.slot_of(request_id)
            if slot is None:
                return False
            req = self._release_slot(slot)
        self._drop_swap(req)
        req.t_finish = self._clock()
        req.status = "cancelled"
        self._metrics["requests_cancelled"].inc()
        telemetry.request_log.end(
            request_id, self._eid, "cancelled",
            tokens=len(req.output_tokens))
        self._stream_close(req)
        self._set_load_gauges()
        self._set_pool_gauges()
        return req

    # -- migration seams (serving/router.py failover + drain) --------------
    @loop_only
    def adopt(self, request, migrated_from=None):
        """Queue a request EXPORTED from another replica, preserving
        its emitted tokens: admission re-prefills prompt+emitted and
        resumes the RNG counter at the next token index (the same
        restart continuation a rolled-back request uses), so a migrated
        output is bit-identical to an unfaulted run on the original
        replica. Unlike submit(), class queue bounds do not apply —
        the fleet already accepted this request — and t_submit /
        t_deadline carry over (router and replicas share one clock
        domain). Raises while draining; rejects oversized sequences."""
        if self._draining:
            self._reject(request, "draining")
        total = request.prompt_len + len(request.output_tokens)
        if total > self.max_length:
            self._metrics["requests_rejected"].inc()
            raise MXNetError(
                f"sequence of {total} tokens (prompt + emitted) exceeds "
                f"slot capacity {self.max_length}")
        now = self._clock()
        if request.t_submit is None:
            request.t_submit = now
        request.priority = min(max(int(request.priority), 0),
                               self.scheduler.num_priorities - 1)
        if request._seq is None:
            request._seq = next(_seq_counter)
        request.dispatch_failures = 0
        request.t_not_before = 0.0
        self.scheduler.requeue(request)
        request.status = "queued"
        request.t_enqueue = now
        if not isinstance(getattr(request, "phases", None), dict):
            request.phases = {}
        # stitch: export_requests packed the origin timeline's trace id
        # and start onto the Request — the continuation opens with the
        # SAME trace id, the ORIGINAL t_begin, and the phase budget
        # accumulated so far, so the migrated request reads as one
        # trace, not two orphans
        t = getattr(request, "trace", None) or {}
        telemetry.request_log.begin(
            request.id, self._eid, trace_id=t.get("trace_id"),
            t_begin=t.get("t_begin"), phases=request.phases,
            prompt_len=request.prompt_len,
            max_new_tokens=request.max_new_tokens,
            priority=request.priority,
            deadline_ms=request.deadline_ms,
            migrated_from=migrated_from,
            resumed_tokens=len(request.output_tokens))
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        return request

    @loop_only
    def export_requests(self):
        """Remove and return EVERY queued and in-flight request
        (original submit order), releasing slots and page leases. The
        emitted tokens stay on each Request, so a survivor replica can
        adopt() them and continue bit-identically. Device syncs are
        best-effort — the caller may be abandoning a wedged replica,
        whose device state no longer matters; host-side lease
        accounting is always rolled back."""
        out = list(self.scheduler.queued_requests())
        for q in self.scheduler._queues:
            q.clear()
        for slot in list(self.scheduler.active_slots):
            req = self.scheduler.request_at(slot)
            try:
                self._release_slot(slot)
            except Exception:       # noqa: BLE001 — wedged replica
                try:
                    self.scheduler.release(slot)
                except Exception:   # noqa: BLE001
                    pass
                self._free_slot_pages(slot)
                try:
                    self._release_adapter(slot)
                except Exception:   # noqa: BLE001
                    pass
            out.append(req)
        out.sort(key=lambda r: r._seq if r._seq is not None else -1)
        for req in out:
            # a swap payload cannot travel to another replica — drop
            # it; the adopter restarts via the replay path instead
            self._drop_swap(req)
            req.status = "exported"
            # pack the stitch context BEFORE ending the timeline: the
            # adopting replica re-opens the trace with the same id and
            # original start (adopt() passes these back to begin())
            tr = telemetry.request_log.live_trace(req.id, self._eid)
            if tr is not None:
                t = dict(getattr(req, "trace", None) or {})
                t.setdefault("trace_id", tr.trace_id)
                t["t_begin"] = tr.t_begin
                req.trace = t
            telemetry.request_log.end(
                req.id, self._eid, "migrated",
                tokens=len(req.output_tokens))
        self._set_load_gauges()
        self._set_pool_gauges()
        return out

    @loop_only
    def export_handoff(self, request_id):
        """Export ONE decoding request WITH its device KV — the
        prefill->decode handoff seam (serving/fleet, docs/SERVING.md
        "Disaggregated prefill/decode"). The slot's used pages (codes
        AND the int8 scale leaves, via the tier gather) and the decode
        cursor scalars land in `req.kv_payload`; the slot and every
        lease release; the timeline ends "migrated" with the stitch
        context packed like export_requests. An engine that adopts the
        payload (`_adopt_payload`) scatters the pages back verbatim and
        continues decoding bit-identically with no re-prefill.

        Returns None when the request is not actively decoding here:
        already terminal, never admitted, or still mid-prefill (its
        un-fed chunk queue is host state the payload format does not
        carry — the caller retries after the final chunk lands)."""
        slot = None
        for s in self.scheduler.active_slots:
            if self.scheduler.request_at(s).id == request_id:
                slot = s
                break
        if slot is None:
            return None
        req = self.scheduler.request_at(slot)
        if self._pending[slot] is not None or not req.output_tokens:
            return None         # mid-prefill: nothing decodable yet
        length = int(self._lengths[slot])
        n_used = min(self._pages_per_slot,
                     -(-length // self.page_size))
        row = [int(p) for p in self._table_host[slot][:n_used]]
        req.kv_payload = {
            "length": length,
            "cur_tok": int(self._cur_tok[slot]),
            "remaining": int(self._remaining[slot]),
            "counters": int(self._counters[slot]),
            "pages": self._tier_gather(row),
            # wall-clock stamp (telemetry's re-anchored perf_counter):
            # the ONLY clock an adopting PROCESS shares with us — the
            # adopter's "handoff" phase measures from here
            "t_export": telemetry.request_trace.now(),
        }
        self._drop_swap(req)
        self._release_slot(slot)
        req.status = "exported"
        tr = telemetry.request_log.live_trace(req.id, self._eid)
        if tr is not None:
            t = dict(getattr(req, "trace", None) or {})
            t.setdefault("trace_id", tr.trace_id)
            t["t_begin"] = tr.t_begin
            req.trace = t
        telemetry.request_log.end(
            req.id, self._eid, "migrated", reason="handoff",
            tokens=len(req.output_tokens))
        self._set_load_gauges()
        self._set_pool_gauges()
        return req

    @property
    def has_work(self):
        return self.scheduler.has_work

    @loop_only
    def step(self):
        """One SUPERVISED scheduling round: shed queued work past its
        deadline, cancel running work past its deadline, admit free
        slots (queue their prompt chunks), run ONE unified dispatch
        (prefill chunks + decode + verify in the same fixed-shape
        program), free finished slots.

        Dispatch exceptions do NOT propagate. The supervisor catches
        them, runs the page-pool invariant audit, latches a
        flight-recorder dump, rolls the implicated slots back (leases
        released, device state parked), re-queues the requests with
        backoff — and quarantines a request whose dispatches failed
        `max_retries` times (terminal reason="error"). Rolled-back
        requests restart by re-prefilling prompt+emitted with their RNG
        counter resumed, so recovered outputs are bit-identical to an
        uninterrupted run.

        Returns every request that reached a TERMINAL state this round:
        finished, deadline-shed/-cancelled, or quarantined."""
        now = self._clock()
        self._fire_hook("step")
        finished = []
        for req in self.scheduler.pop_expired(now):
            finished.append(self._shed_expired(req))
        for slot in list(self.scheduler.active_slots):
            req = self.scheduler.request_at(slot)
            if req.t_deadline is not None and now >= req.t_deadline:
                finished.append(self._deadline_cancel(slot))
        for slot, req in self.scheduler.admit(now):
            try:
                fin = self._admit(slot, req)
            except Exception as e:          # noqa: BLE001 — supervisor
                q = self._on_admit_fault(slot, req, e)
                if q is not None:
                    finished.append(q)
                continue
            if fin is not None:
                finished.append(fin)
        if self.policy is not None:
            # Assess AFTER admission: the overload level must reflect the
            # backlog this tick's dispatch actually leaves queued, not the
            # pre-admission spike that free slots are about to absorb.
            self.policy.on_step(self, now)
            if self.host_pool is not None \
                    and hasattr(self.policy, "preempt_victim"):
                # whole-request swap: with every slot busy and strictly
                # more-urgent work queued, swap the least-urgent running
                # request out through the host tier — its slot admits
                # the urgent request next tick, and it resumes
                # bit-identically later (page-in or replay)
                victim = self.policy.preempt_victim(self)
                if victim is not None:
                    self._preempt_slot(victim)
        self._set_load_gauges()
        if self.scheduler.num_active:
            try:
                finished.extend(self._dispatch())
            except Exception as e:          # noqa: BLE001 — supervisor
                finished.extend(self._on_decode_fault(e))
            self._set_load_gauges()
        return finished

    @loop_only
    def serve(self, requests=()):
        """Submit `requests`, run until the queue and all slots drain,
        and return every TERMINAL request (submission order) —
        finished requests plus any shed, deadline-cancelled, or
        quarantined along the way (check `.status`). Rejected
        submissions raise out of submit() and are not returned. Drain
        wall time (last submit -> empty) lands in
        serving_drain_seconds."""
        done = []
        for r in requests:
            try:
                self.submit(r)
            except (QueueFullError, ShedError):
                done.append(r)      # terminal: status == "shed"
        t_drain0 = self._clock()
        with span("serving.drain", engine=self._eid):
            while self.has_work:
                done.extend(self.step())
        self._metrics["drain_seconds"].observe(
            self._clock() - t_drain0)
        done.sort(key=lambda r: r.t_submit)
        return done

    def generate(self, prompts, max_new_tokens, **request_kw):
        """Convenience: serve a list of prompts with shared settings and
        return their generated token lists in order."""
        reqs = [Request(p, max_new_tokens, **request_kw) for p in prompts]
        by_id = {r.id: r for r in reqs}
        self.serve(reqs)
        return [by_id[r.id].output_tokens for r in reqs]

    # -- dispatch hook ------------------------------------------------------
    def _hook_takes_phase(self, hook):
        """Legacy dispatch hooks take (engine) and fire once per step;
        phase-aware hooks accept phase=/requests= keywords (or **kw)
        and fire at every prefill/decode boundary too — the seam the
        fault-injection harness (serving/faults.py) installs into.
        Detected once per hook identity from its signature."""
        cached = self._hook_kw_cache
        if cached is not None and cached[0] is hook:
            return cached[1]
        try:
            params = inspect.signature(hook).parameters
            takes = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                or name in ("phase", "requests")
                for name, p in params.items())
        except (TypeError, ValueError):
            takes = False
        self._hook_kw_cache = (hook, takes)
        return takes

    def _fire_hook(self, phase, requests=()):
        hook = self.dispatch_hook
        if hook is None:
            return
        if self._hook_takes_phase(hook):
            hook(self, phase=phase, requests=tuple(requests))
        elif phase == "step":
            hook(self)

    # -- graceful degradation ----------------------------------------------
    def _set_degraded(self, on, reason="overload"):
        """Latch / clear graceful degradation. While degraded the
        engine suspends speculative decoding (wasted verify FLOPs are
        pure loss when demand exceeds capacity — the plain decode
        program serves until recovery), serving_degraded flips, and
        /healthz reports the engine degraded."""
        on = bool(on)
        if on == self._degraded:
            return
        self._degraded = on
        self._metrics["degraded"].set(int(on))
        name = f"engine{self._eid}"
        if on:
            _tserver.set_degraded(name, reason)
            telemetry.flight.record("degraded", engine=self._eid,
                                    reason=reason)
        else:
            _tserver.clear_degraded(name)
            telemetry.flight.record("recovered", engine=self._eid)

    # -- deadline enforcement ----------------------------------------------
    def _shed_expired(self, req):
        """A queued request whose deadline passed before admission:
        terminal `rejected(deadline)` — no tokens were produced, no
        slot or page was ever touched."""
        self._drop_swap(req)
        req.status = "shed"
        req.t_finish = self._clock()
        self._shed_inc("deadline_queued", req.priority, req.tenant)
        telemetry.request_log.end(
            req.id, self._eid, "rejected", reason="deadline",
            queued=True, tokens=0)
        self._stream_close(req)
        return req

    def _deadline_cancel(self, slot):
        """A running request past its deadline, cancelled at the
        dispatch boundary: slot and page leases released; the tokens
        already emitted stay on the Request; terminal
        `finished(deadline)`."""
        req = self._release_slot(slot)
        req.status = "deadline"
        self._shed_inc("deadline_running", req.priority, req.tenant)
        telemetry.request_log.end(
            req.id, self._eid, "finished", reason="deadline",
            tokens=len(req.output_tokens))
        self._stream_close(req)
        self._set_pool_gauges()
        return req

    # -- fault supervision --------------------------------------------------
    @thread_safe
    def audit_pages(self, raise_on_error=False):
        """Page-pool invariant audit with this engine's full lease map:
        every mapped slot's table row, any extra lease rows registered
        in `audit_extra_leases` (the fault-injection harness registers
        pages it holds), and the prefix cache's member pages. With the
        host tier on, the CROSS-TIER check rides along: the tier's
        node keys must match the tree's spilled keypaths exactly, its
        swap keys must belong to queued preempted requests, and the
        host pool's own byte accounting must balance — no page may
        leak across tiers in either direction. Returns the violation
        list ([] = clean)."""
        leases = [self._table_host[s] for s in range(self.num_slots)
                  if self._mapped[s]]
        leases.extend(self.audit_extra_leases)
        members = ()
        if self.prefix_cache is not None:
            members = np.nonzero(self.prefix_cache.member_mask())[0]
        scales = None
        if self._quant:
            # per-page scale summary for the pool's lease-consistency
            # check: the max magnitude over layers/heads — NaN/inf
            # propagates and gets flagged as corrupt quant state
            scales = np.maximum(
                np.abs(np.asarray(self._ks)).max(axis=(0, 2)),
                np.abs(np.asarray(self._vs)).max(axis=(0, 2)))
        host_keys = spilled_keys = None
        extra = []
        if self.host_pool is not None:
            spilled_keys = set(self.prefix_cache.spilled_keypaths())
            # swap payloads are legitimate host entries only while a
            # queued preempted request references them (the stale
            # inverse — a swap record whose payload the host LRU
            # dropped — is fine: resume detects it and restarts)
            swaps = {("req", r.id)
                     for r in self.scheduler.queued_requests()
                     if getattr(r, "swap", None) is not None
                     and r.swap.get("key") is not None}
            host_keys = set()
            for key in self.host_pool.keys():
                kind = key[0] if isinstance(key, tuple) and key else None
                if kind == "node":
                    host_keys.add(key[1])
                elif kind == "req":
                    if key not in swaps:
                        extra.append(
                            f"host tier holds swap payload {key!r} "
                            "with no queued preempted request "
                            "(leaked)")
                else:
                    extra.append(
                        f"host tier holds unknown key {key!r}")
            extra.extend(self.host_pool.audit())
        out = self.page_pool.audit(leases=leases, members=members,
                                   scales=scales, host_keys=host_keys,
                                   spilled_keys=spilled_keys)
        out.extend(extra)
        if out and raise_on_error:
            raise MXNetError("page pool audit failed: "
                             + "; ".join(out))
        return out

    @thread_safe
    def audit_adapters(self, raise_on_error=False):
        """Adapter-pool invariant audit with this engine's slot
        assignments: every active slot's pinned adapter must be
        resident with a pin count that matches the assignment count
        exactly (a leaked pin would wedge the slab). Returns the
        violation list ([] = clean; also [] without a pool)."""
        if self.adapter_pool is None:
            return []
        assignments = [aid for aid in self._adapter_of if aid is not None]
        return self.adapter_pool.audit(assignments=assignments,
                                       raise_on_error=raise_on_error)

    def _audit_and_latch(self, phase, exc):
        """Post-fault integrity check: run the page-pool AND
        adapter-pool audits while the implicated slots still hold their
        leases/pins (so the maps are complete) and latch a
        flight-recorder dump naming the fault. Returns the violation
        list (normally empty — the fault was caught BEFORE any
        accounting was rolled back)."""
        violations = self.audit_pages() + self.audit_adapters()
        detail = f"{phase}: {type(exc).__name__}: {exc}"
        if violations:
            detail += " | audit: " + "; ".join(violations)
        telemetry.flight.record("dispatch_error", engine=self._eid,
                                phase=phase, error=str(exc)[:200],
                                audit_violations=len(violations))
        telemetry.flight.trigger(
            f"dispatch_error:engine{self._eid}", detail)
        return violations

    def _quarantine(self, req, error):
        """Terminal failure: this request's dispatches failed
        `max_retries` times — it is poison as far as the engine can
        tell. Terminal `failed(error)`; the engine keeps serving
        everyone else."""
        self._drop_swap(req)
        req.status = "failed"
        req.t_finish = self._clock()
        self._metrics["requests_failed"].inc()
        telemetry.request_log.end(
            req.id, self._eid, "failed", reason="error",
            failures=req.dispatch_failures, error=str(error)[:200],
            tokens=len(req.output_tokens))
        telemetry.flight.record("quarantined", engine=self._eid,
                                request=req.id,
                                failures=req.dispatch_failures)
        self._stream_close(req)
        return req

    def _requeue(self, req, now, blamed, error=""):
        """Roll one request back to the queue after a caught fault.
        A `blamed` request carries the failure: exponential backoff,
        probation (the scheduler re-tries it alone), quarantine at
        max_retries. Innocents re-queue with one flat backoff tick and
        no blame — their emitted tokens ride along and the restart
        continuation keeps their output bit-identical. Returns the
        quarantined Request when the retry budget is spent, else
        None."""
        if blamed:
            req.dispatch_failures += 1
            if req.dispatch_failures >= self.max_retries:
                return self._quarantine(req, error)
            backoff = self.retry_backoff_s * (
                2 ** (req.dispatch_failures - 1))
        else:
            backoff = self.retry_backoff_s
        req.t_not_before = now + backoff
        req.t_enqueue = now     # queue_wait re-counts from HERE, not
        self._metrics["dispatch_retries"].inc()   # from t_submit
        self.scheduler.requeue(req)
        req.status = "queued"
        telemetry.request_log.event(
            req.id, self._eid, "requeued", blamed=blamed,
            failures=req.dispatch_failures, backoff_s=round(backoff, 4))
        return None

    def _on_admit_fault(self, slot, req, exc):
        """Supervise one failed admission: roll the slot fully back
        (scheduler, page leases, parked device state) and re-queue the
        request. Pool exhaustion is BACKPRESSURE — pages will drain, so
        nobody is blamed and no dump is latched; anything else counts
        against the request's retry budget. Returns the quarantined
        Request, or None."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        backpressure = isinstance(exc, (PagePoolExhausted,
                                        AdapterPoolExhausted))
        self.scheduler.release(slot)
        self._free_slot_pages(slot)
        self._release_adapter(slot)
        self._pending[slot] = None
        self._replay[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        self._lengths[slot] = self.max_length
        self._sync_slot(slot)
        if not backpressure:
            self._audit_and_latch("prefill", exc)
        self._set_pool_gauges()
        return self._requeue(req, now, blamed=not backpressure,
                             error=str(exc))

    def _on_decode_fault(self, exc):
        """Supervise a failed decode dispatch: audit while the batch's
        leases are still mapped, then roll every active slot back.
        Blame assignment: when the batch held probationers (requests
        with prior failures) only THEY are blamed — the scheduler
        admits at most one probationer at a time, so repeat faults
        converge on the poison request; a first fault (no history
        anywhere) blames the whole batch, and a later clean dispatch
        resets the innocents' counters. Returns the requests
        quarantined by this fault."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        self._audit_and_latch("decode", exc)
        active = [(slot, self.scheduler.request_at(slot))
                  for slot in self.scheduler.active_slots]
        probationers = {id(r) for _, r in active
                        if r.dispatch_failures > 0}
        blame_all = not probationers
        quarantined = []
        # reversed + appendleft in requeue() restores admission order
        for slot, req in reversed(active):
            self._release_slot(slot)
            q = self._requeue(
                req, now,
                blamed=blame_all or id(req) in probationers,
                error=str(exc))
            if q is not None:
                quarantined.append(q)
        self._set_pool_gauges()
        return quarantined

    def _scrub_slot_pages(self, slot):
        """Zero the KV of the slot's EXCLUSIVE, non-tree pages (the
        only pages a poisoned write can live in) before their leases
        are released — a recycled page must not carry NaN residue into
        the next owner's attention window, whatever the kernel's
        masking does with out-of-range positions."""
        if not self._mapped[slot]:
            return
        ref = self.page_pool.refcounts()
        member = self.prefix_cache.member_mask() \
            if self.prefix_cache is not None else None
        pages = [int(p) for p in self._table_host[slot]
                 if ref[int(p)] == 1
                 and (member is None or not member[int(p)])]
        if not pages:
            return
        idx = jnp.asarray(pages, jnp.int32)
        zero = jnp.zeros((), self._kp.dtype)
        self._kp = self._kp.at[:, idx].set(zero)
        self._vp = self._vp.at[:, idx].set(zero)
        if self._quant:
            # a poisoned slot may have bumped these pages' scales with
            # NaN/inf absmaxes — scrub them with the codes
            zs = jnp.zeros((), jnp.float32)
            self._ks = self._ks.at[:, idx].set(zs)
            self._vs = self._vs.at[:, idx].set(zs)

    def _on_bad_slots(self, bad, exc_msg):
        """Slots whose dispatch produced non-finite logits (the
        in-program finite guard): this dispatch's tokens for them are
        already discarded by the caller; scrub their exclusive pages,
        roll them back blamed, and latch a dump. Co-batched finite
        slots keep their tokens — their state never mixed with the
        poison. Returns the requests quarantined."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        self._audit_and_latch("decode_nonfinite",
                              MXNetError(exc_msg))
        quarantined = []
        for slot in reversed(bad):
            req = self.scheduler.request_at(slot)
            telemetry.request_log.event(
                req.id, self._eid, "decode_discarded", slot=slot,
                reason="nonfinite_logits")
            self._scrub_slot_pages(slot)
            self._release_slot(slot)
            q = self._requeue(req, now, blamed=True, error=exc_msg)
            if q is not None:
                quarantined.append(q)
        self._set_pool_gauges()
        return quarantined

    # -- device-resident slot state ----------------------------------------
    def _build_slot_upload(self):
        """One jitted scatter that refreshes EVERY device-resident
        per-slot array for one slot in a single dispatch."""
        def upload(state, slot, vals, row):
            *scalars, table = state
            out = tuple(a.at[slot].set(v) for a, v in zip(scalars, vals))
            return out + (table.at[slot].set(row),)
        return jax.jit(upload, donate_argnums=(0,))

    def _sync_slot(self, slot):
        """Upload one slot's host-side scalar state (plus its page-table
        row and the pool's page_lock mask, which change in the same
        events) to the device-resident copies. Called on admission,
        finish and cancel — never per decode dispatch."""
        vals = (self._lengths[slot], self._cur_tok[slot],
                self._done[slot], self._remaining[slot],
                self._counters[slot], self._seeds[slot],
                self._temp[slot], self._top_k[slot], self._top_p[slot],
                self._do_sample[slot], self._eos[slot])
        if self.adapter_pool is not None:
            vals = vals + (self._aslot[slot],)
        self._dstate = self._upload_fn(self._dstate, np.int32(slot),
                                       vals, self._table_host[slot])
        self._d_lock = self._rep(jnp.asarray(self._page_lock_host()))

    def _adapter_args(self, aslot):
        """The extra dispatch operands when the adapter pool is on: the
        slab-slot index array plus the slab itself (read-only — never
        donated, so page-ins and dispatches interleave freely). () when
        the pool is off, keeping the dispatch signature — and the trace
        — byte-identical to a pre-adapter engine."""
        pool = self.adapter_pool
        if pool is None:
            return ()
        if isinstance(aslot, tuple):    # the _dstate tail
            aslot = aslot[0]
        args = (aslot, pool.A, pool.B, pool.scale)
        if pool.quantized:
            args = args + (pool.a_scale, pool.b_scale)
        if self._mesh is not None:
            args = (aslot,) + self._placed_slab(args[1:])
        return args

    # -- pages -------------------------------------------------------------
    def _page_lock_host(self):
        """(total_pages,) bool for the decode program: True = this page
        must not be written (shared, cached, or free). Decode writes are
        only legal in pages the writing slot holds EXCLUSIVELY."""
        lock = self.page_pool.refcounts() != 1
        if self.prefix_cache is not None:
            lock |= self.prefix_cache.member_mask()
        return lock

    def _map_slot_pages(self, slot, tokens, match=True):
        """Page-table surgery for an admission (`tokens` = the ids the
        slot must hold: the prompt, plus already-emitted tokens when a
        rolled-back request restarts): longest-prefix match, CoW split
        when the whole sequence is cached, exclusive allocation for the
        rest. Returns the prefix offset (tokens NOT recomputed; prefill
        starts there). On an allocation failure every lease taken by
        the match is released before the exception propagates — a
        faulted admission must not leak refcounts. match=False skips
        the prefix lookup (quantized restarts must recompute every
        position to replay the recorded write schedule)."""
        S, P = self.page_size, self._pages_per_slot
        Tp = int(tokens.size)
        pc = self.prefix_cache
        matched = pc.match(tokens) if (pc is not None and match) else []
        leased = list(matched)         # every lease match() took
        cow_src = None
        if matched and len(matched) * S >= Tp:
            # Fully cached sequence (page-aligned): the last token must
            # still run through the model for its logits, and that
            # rewrites the KV at position Tp-1 — INSIDE the last cached
            # page. Copy-on-write: re-home that page to an exclusive
            # copy; the other matched pages stay shared.
            cow_src = matched.pop()
        n_shared = len(matched)
        need = P - n_shared
        try:
            if pc is not None and self.page_pool.num_free < need:
                pc.reclaim(need)       # LRU-evict idle cached prefixes
            fresh = self.page_pool.alloc(need)
        except Exception:
            if pc is not None and leased:
                pc.release(leased)
            raise
        if self._quant and fresh:
            # reset recycled pages' dequant scales BEFORE any CoW copy
            # lands (the copy then stamps the source page's scale over
            # the zero). Fixed-length padded index: one compile, ever.
            idx = np.full(P, self.page_pool.num_pages, np.int32)
            idx[:len(fresh)] = fresh
            self._ks, self._vs = self._zero_scales_fn(
                self._ks, self._vs, jnp.asarray(idx))
        if cow_src is not None:
            dst = fresh[0]             # lands at row index n_shared
            src = jnp.asarray(cow_src, jnp.int32)
            dsti = jnp.asarray(dst, jnp.int32)
            if self._quant:
                self._kp, self._vp, self._ks, self._vs = \
                    self._copy_page_fn(self._kp, self._vp, self._ks,
                                       self._vs, src, dsti)
            else:
                self._kp, self._vp = self._copy_page_fn(
                    self._kp, self._vp, src, dsti)
            pc.release([cow_src])      # drop our lease on the source
            offset = Tp - 1
        else:
            offset = n_shared * S
        self._table_host[slot] = np.asarray(matched + fresh, np.int32)
        self._mapped[slot] = True
        return offset

    def _free_slot_pages(self, slot):
        if not self._mapped[slot]:
            return
        row = [int(p) for p in self._table_host[slot]]
        if self.prefix_cache is not None:
            self.prefix_cache.release(row)
        else:
            self.page_pool.free(self.page_pool.decref(row))
        self._mapped[slot] = False

    # -- host KV tier (docs/SERVING.md "Tiered KV cache") ------------------
    def _tier_gather(self, pages):
        """Device -> host payload read: the fixed-width jitted page
        gather (index padded with page 0, one compiled program however
        many pages move) plus one device_get. Returns one payload dict
        per page — int8 codes AND the per-page scale leaves travel
        together, so a later page-in restores the page verbatim and
        every future read of it is bit-identical."""
        P = self._pages_per_slot
        out = []
        for i in range(0, len(pages), P):
            blk = [int(p) for p in pages[i:i + P]]
            idx = np.zeros(P, np.int32)
            idx[:len(blk)] = blk
            if self._quant:
                k, v, ks, vs = self._tier_gather_fn(
                    self._kp, self._vp, self._ks, self._vs,
                    jnp.asarray(idx))
                k, v, ks, vs = jax.device_get((k, v, ks, vs))
            else:
                k, v = self._tier_gather_fn(self._kp, self._vp,
                                            jnp.asarray(idx))
                k, v = jax.device_get((k, v))
                ks = vs = None
            for j in range(len(blk)):
                # copy out of the gathered block: a view would pin the
                # whole (L, P, ...) buffer in host RAM per page
                pl = {"k": np.ascontiguousarray(k[:, j]),
                      "v": np.ascontiguousarray(v[:, j])}
                if ks is not None:
                    pl["ks"] = np.ascontiguousarray(ks[:, j])
                    pl["vs"] = np.ascontiguousarray(vs[:, j])
                out.append(pl)
        return out

    def _tier_scatter(self, items):
        """Host -> device page-in write for `items` = [(page_id,
        payload)]: assemble the fixed-width value block, upload it, and
        run the donated jitted scatter (pad rows target an out-of-range
        page id and drop). Scale leaves are written with the codes, so
        a paged-in int8 page needs no re-quantization — and no
        _zero_scales pass — to read back exactly."""
        P = self._pages_per_slot
        L, _, S, H, Dh = self._kp.shape
        for i in range(0, len(items), P):
            blk = items[i:i + P]
            idx = np.full(P, self.page_pool.num_pages, np.int32)
            kval = np.zeros((L, P, S, H, Dh), self._kp.dtype)
            vval = np.zeros_like(kval)
            ksv = vsv = None
            if self._quant:
                ksv = np.zeros((L, P, H), np.float32)
                vsv = np.zeros((L, P, H), np.float32)
            for j, (page, pl) in enumerate(blk):
                idx[j] = int(page)
                kval[:, j] = pl["k"]
                vval[:, j] = pl["v"]
                if self._quant:
                    ksv[:, j] = pl["ks"]
                    vsv[:, j] = pl["vs"]
            if self._quant:
                (self._kp, self._vp, self._ks,
                 self._vs) = self._tier_scatter_fn(
                    self._kp, self._vp, self._ks, self._vs,
                    jnp.asarray(idx),
                    self._rep(jnp.asarray(kval)),
                    self._rep(jnp.asarray(vval)),
                    self._rep(jnp.asarray(ksv)),
                    self._rep(jnp.asarray(vsv)))
            else:
                self._kp, self._vp = self._tier_scatter_fn(
                    self._kp, self._vp, jnp.asarray(idx),
                    self._rep(jnp.asarray(kval)),
                    self._rep(jnp.asarray(vval)))

    def _spill_node(self, keypath, page):
        """PrefixCache evict_hook: offer one evicted node's payload to
        the host tier (gather runs BEFORE the cache frees the device
        page). False — payload not taken, host budget unmeetable —
        makes the cache fall back to plain discard."""
        t0 = self._clock()
        key = ("node", keypath)
        payload = self._tier_gather([int(page)])[0]
        if not self.host_pool.put(key, payload):
            return False
        m = self._metrics
        m["kv_spill_pages"].inc()
        m["kv_spill_bytes"].inc(self.host_pool.entry_bytes(key))
        m["kv_spill_seconds"].observe(self._clock() - t0)
        return True

    def _pagein_nodes(self, items):
        """PrefixCache pagein_hook: restore `items` = [(keypath,
        fresh_page)] from the host tier in one batched scatter. Each
        payload is checked out (pinned) for the duration and released
        with drop=True only once the scatter landed — on any failure
        the entries survive for the next attempt."""
        t0 = self._clock()
        taken, ok, nbytes = [], False, 0
        try:
            payloads = []
            for kp, _ in items:
                key = ("node", kp)
                payloads.append(self.host_pool.checkout(key))
                taken.append(key)
                nbytes += self.host_pool.entry_bytes(key)
            self._tier_scatter(
                [(pg, pl) for (_, pg), pl in zip(items, payloads)])
            ok = True
        finally:
            for key in taken:
                self.host_pool.release(key, drop=ok)
        dt = self._clock() - t0
        m = self._metrics
        m["kv_pagein_pages"].inc(len(items))
        m["kv_pagein_bytes"].inc(nbytes)
        m["kv_pagein_seconds"].observe(dt)
        # per-request attribution: _admit zeroes this bracket before
        # the page map, so whatever the match paged in lands in the
        # admitting request's host_pagein phase
        self._pagein_acc += dt

    def _host_evict(self, key):
        """HostPagePool evict_cb: the tier wants to LRU-drop `key` to
        admit a newer spill. Node payloads go through the prefix
        cache's drop_spilled (vetoed while the node still anchors a
        spilled subtree); swap payloads are always droppable — the
        preempted request's resume detects the loss and falls back to
        the replay/restart path, which is bit-identical anyway."""
        kind, val = key
        if kind == "node":
            return self.prefix_cache.drop_spilled(val)
        return True

    def _drop_swap(self, req):
        """Discard a preempted request's swap record and host payload
        (the request went terminal, migrated, or its record went
        stale). If it ever runs again it restarts via the replay
        path. No-op for requests that were never preempted."""
        swap = getattr(req, "swap", None)
        if swap is None:
            return
        req.swap = None
        key = swap.get("key")
        if key is not None and self.host_pool is not None \
                and key in self.host_pool:
            self.host_pool.discard(key)

    def _preempt_slot(self, slot):
        """Whole-request swap under overload: gather the victim's
        EXCLUSIVE pages (the shared prefix stays in the radix tree) to
        one host-tier payload, release the slot and every page lease,
        and requeue the request unblamed at the front of its class
        with a swap record naming its prefix nodes and slot scalars.
        If the host tier cannot take the payload the request still
        yields its slot, but will restart via the replay path instead
        of resuming. Either way the continuation is bit-identical —
        swapping just skips the re-prefill compute."""
        req = self.scheduler.request_at(slot)
        S, P = self.page_size, self._pages_per_slot
        pc = self.prefix_cache
        length = int(self._lengths[slot])
        n_used = min(P, -(-length // S))
        row = [int(p) for p in self._table_host[slot][:n_used]]
        member = pc.member_mask()
        n_shared = 0
        for p in row:
            if not member[p]:
                break
            n_shared += 1
        excl = row[n_shared:]
        m = self._metrics
        m["preempts"].inc()
        key = ("req", req.id) if excl else None
        swapped = True
        if excl:
            t0 = self._clock()
            pls = self._tier_gather(excl)
            payload = {name: np.stack([pl[name] for pl in pls])
                       for name in pls[0]}
            swapped = self.host_pool.put(key, payload)
            if swapped:
                m["kv_spill_pages"].inc(len(excl))
                m["kv_spill_bytes"].inc(
                    self.host_pool.entry_bytes(key))
                m["kv_spill_seconds"].observe(self._clock() - t0)
        nodes = [pc._by_page.get(p) for p in row[:n_shared]]
        if swapped and all(n is not None for n in nodes):
            req.swap = {
                "key": key,
                "nodes": nodes,
                "n_excl": len(excl),
                "length": length,
                "cur_tok": int(self._cur_tok[slot]),
                "remaining": int(self._remaining[slot]),
                "counters": int(self._counters[slot]),
            }
        else:
            if swapped and key is not None:
                self.host_pool.discard(key)
            m["preempt_restarted"].inc()
        self._release_slot(slot)
        req.t_enqueue = self._clock()
        self.scheduler.requeue(req)
        req.status = "queued"
        telemetry.request_log.event(
            req.id, self._eid, "preempted", slot=slot,
            swapped=req.swap is not None,
            tokens=len(req.output_tokens))
        self._set_pool_gauges()

    def _try_resume(self, slot, req):
        """Splice a swapped request straight back into decode: re-lease
        its shared prefix nodes (paging spilled ones back in), restore
        its exclusive pages from the swap payload into fresh device
        pages, and rebuild the slot scalars from the swap record — no
        prefill, no replay. Returns False when the record went stale
        (payload LRU-dropped, a prefix node discarded); the caller
        falls back to the plain restart. PagePoolExhausted mid-resume
        rolls every lease taken here back and propagates — the
        supervisor requeues unblamed with the swap kept."""
        swap = req.swap
        pc = self.prefix_cache
        key = swap["key"]
        nodes = swap["nodes"]
        if (key is not None and key not in self.host_pool) \
                or any(n.dead for n in nodes):
            return False
        P = self._pages_per_slot
        n_shared = len(nodes)
        n_excl = int(swap["n_excl"])
        t0 = self._clock()
        m = self._metrics
        taken, ok, payload, nbytes = [], False, None, 0
        try:
            if key is not None:
                # pin the payload FIRST: the reclaim below may spill
                # into the host tier and LRU-pressure it out otherwise
                payload = self.host_pool.checkout(key)
                nbytes = self.host_pool.entry_bytes(key)
            resident = [n for n in nodes if not n.spilled]
            spilled = [n for n in nodes if n.spilled]
            self.page_pool.adopt([n.page for n in resident])
            taken.extend(n.page for n in resident)
            if spilled:
                pin = pc._pagein(
                    [(pc._keypath(n), n) for n in spilled],
                    next(pc._clock))
                taken.extend(pin)
                if len(pin) < len(spilled):
                    raise PagePoolExhausted(
                        f"page-in of {len(spilled)} spilled prefix "
                        f"pages restored {len(pin)} — resume of "
                        f"request {req.id} waits for pages to drain")
            need = P - n_shared
            if self.page_pool.num_free < need:
                pc.reclaim(need)
            fresh = self.page_pool.alloc(need)
            taken.extend(fresh)
            if self._quant and fresh:
                # recycled pages beyond the payload rows still need
                # zeroed scales before decode's monotone max-update
                idx = np.full(P, self.page_pool.num_pages, np.int32)
                idx[:len(fresh)] = fresh
                self._ks, self._vs = self._zero_scales_fn(
                    self._ks, self._vs, jnp.asarray(idx))
            if n_excl:
                items = []
                for j in range(n_excl):
                    pl = {"k": payload["k"][j], "v": payload["v"][j]}
                    if self._quant:
                        pl["ks"] = payload["ks"][j]
                        pl["vs"] = payload["vs"][j]
                    items.append((fresh[j], pl))
                self._tier_scatter(items)
            ok = True
        except BaseException:
            if taken:
                pc.release(taken)
            raise
        finally:
            if payload is not None:
                self.host_pool.release(key, drop=ok)
        if n_excl:
            m["kv_pagein_pages"].inc(n_excl)
            m["kv_pagein_bytes"].inc(nbytes)
            m["kv_pagein_seconds"].observe(self._clock() - t0)
        self._table_host[slot] = np.asarray(
            [n.page for n in nodes] + fresh, np.int32)
        self._mapped[slot] = True
        self._pending[slot] = None
        self._replay[slot] = None
        self._base[slot] = len(req.output_tokens)
        self._lengths[slot] = swap["length"]
        self._cur_tok[slot] = swap["cur_tok"]
        self._remaining[slot] = swap["remaining"]
        self._counters[slot] = swap["counters"]
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = False
        if self.speculative:
            self._hist[slot] = [int(t) for t in req.prompt] \
                + [int(t) for t in req.output_tokens]
        req.swap = None
        req.status = "running"
        self._sync_slot(slot)
        m["preempt_resumed"].inc()
        telemetry.request_log.event(
            req.id, self._eid, "resumed_swap", slot=slot,
            tokens=len(req.output_tokens))
        self._set_pool_gauges()
        return True

    def _adopt_payload(self, slot, req):
        """Splice a handed-off request straight into decode from its
        shipped KV payload (export_handoff on the exporting engine,
        possibly in another PROCESS): a full row of fresh exclusive
        pages, one batched scatter of the shipped page slabs — int8
        codes and their scale leaves land verbatim, so no
        re-quantization and no replay — and the decode cursor restored
        from the payload scalars. The continuation is bit-identical to
        the exporter having kept decoding. Returns False when the
        payload cannot land here (geometry/dtype mismatch, page-pool
        pressure): the caller falls back to the replay restart, which
        reaches the same tokens by recomputing."""
        kvp = req.kv_payload
        pages = kvp.get("pages") or []
        length = int(kvp.get("length", -1))
        P, S = self._pages_per_slot, self.page_size
        if (not pages or length < 1 or length > self.max_length
                or len(pages) != min(P, -(-length // S))):
            return False
        L, _, S_, H, Dh = self._kp.shape
        k0 = np.asarray(pages[0].get("k"))
        if k0.shape != (L, S_, H, Dh) \
                or k0.dtype != np.dtype(self._kp.dtype) \
                or self._quant != ("ks" in pages[0]):
            return False
        pc = self.prefix_cache
        try:
            try:
                if pc is not None and self.page_pool.num_free < P:
                    pc.reclaim(P)
                fresh = self.page_pool.alloc(P)
            except Exception:   # noqa: BLE001 — pool pressure: replay
                return False
            if self._quant:
                # recycled pages must start from scale 0 before the
                # shipped scales stamp over the payload rows (the tail
                # rows stay zeroed for decode's monotone max-update)
                idx = np.full(P, self.page_pool.num_pages, np.int32)
                idx[:len(fresh)] = fresh
                self._ks, self._vs = self._zero_scales_fn(
                    self._ks, self._vs, jnp.asarray(idx))
            self._tier_scatter(list(zip(fresh[:len(pages)], pages)))
        except Exception:
            # the slot table does not reference `fresh` yet, so the
            # lease goes straight back to the pool
            self.page_pool.free(fresh)
            raise
        self._table_host[slot] = np.asarray(fresh, np.int32)
        self._mapped[slot] = True
        self._pending[slot] = None
        self._replay[slot] = None
        self._base[slot] = len(req.output_tokens)
        self._lengths[slot] = length
        self._cur_tok[slot] = int(kvp["cur_tok"])
        self._remaining[slot] = int(kvp["remaining"])
        self._counters[slot] = int(kvp["counters"])
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = False
        self._kv_tier[slot] = "cold"
        if self.speculative:
            self._hist[slot] = [int(t) for t in req.prompt] \
                + [int(t) for t in req.output_tokens]
        req.kv_payload = None
        req.status = "running"
        self._sync_slot(slot)
        # the handoff TTFT phase: export stamp -> payload scattered,
        # on the shared wall clock. The exporter already closed the
        # five in-process phases at the first token; this engine owns
        # only the hop, and publishes it into the phase histogram
        # directly (the first-token budget publication ran over there).
        t_exp = kvp.get("t_export")
        if t_exp is not None and telemetry.request_log.enabled:
            dur = max(0.0, telemetry.request_trace.now() - float(t_exp))
            self._phase(req, "handoff", dur)
            key = ("handoff", "cold")
            child = self._phase_children.get(key)
            if child is None:
                child = self._phase_fam.labels(self._eid, *key)
                self._phase_children[key] = child
            child.observe(dur)
        telemetry.request_log.event(
            req.id, self._eid, "adopted_payload", slot=slot,
            pages=len(pages), tokens=len(req.output_tokens))
        self._set_pool_gauges()
        return True

    # -- admission ---------------------------------------------------------
    @supervised("adapter/page leases taken here are rolled back by "
                "_on_admit_fault (slot state parked, leases released, "
                "pool audited) when any later admission step raises")
    def _admit(self, slot, req):
        """Map pages and park the prompt as this slot's chunk queue —
        NO forward runs here. The unified dispatch streams the queue
        chunk_tokens at a time next to everyone else's decode work and
        samples the first token when the final chunk lands.

        Restart continuation: a request rolled back after a caught
        fault already emitted `base` tokens — re-feed the prompt PLUS
        those tokens and resume the RNG stream at token index `base`,
        making the recovered output bit-identical to an uninterrupted
        run (streams are keyed (seed, token_index))."""
        base = len(req.output_tokens)
        tokens = req.prompt if not base else np.concatenate(
            [req.prompt, np.asarray(req.output_tokens, np.int32)])
        Tp = int(tokens.size)
        telemetry.request_log.event(req.id, self._eid, "admitted",
                                    slot=slot)
        if base:
            telemetry.request_log.event(
                req.id, self._eid, "resumed", tokens=base)
        self._fire_hook("prefill", (req,))
        if self.adapter_pool is not None:
            # pin BEFORE the page map: either acquire can raise
            # (AdapterPoolExhausted is backpressure, like
            # PagePoolExhausted) and _on_admit_fault rolls back
            # whatever was taken
            aslot = self.adapter_pool.acquire(req.adapter_id)
            self._adapter_of[slot] = req.adapter_id \
                if req.adapter_id not in (None, 0) else None
            self._aslot[slot] = aslot
        if req.kv_payload is not None:
            # cross-process handoff (serving/fleet): scatter the
            # shipped KV pages and splice straight into decode — no
            # re-prefill. A payload that cannot land here falls
            # through to the replay restart below, which reaches the
            # same tokens by recomputing (`kv_history` rode the wire).
            if self._adopt_payload(slot, req):
                return None
            req.kv_payload = None
            telemetry.request_log.event(req.id, self._eid,
                                        "handoff_fallback")
        if req.swap is not None:
            # preempted request: splice straight back into decode from
            # its swapped KV — no prefill. A stale swap (payload
            # LRU-dropped from the host tier, prefix nodes discarded)
            # falls through to the plain restart below, which replays
            # to the same output; PagePoolExhausted mid-resume
            # propagates as backpressure with the swap kept for a
            # later retry.
            if self._try_resume(slot, req):
                return None
            self._drop_swap(req)
            self._metrics["preempt_restarted"].inc()
            telemetry.request_log.event(req.id, self._eid,
                                        "swap_stale")
        # a prefix-cache hit seeds the chunk cursor past the shared
        # pages: length starts at the cached offset and the queue holds
        # only the uncached tail (>= 1 token — a fully cached prompt is
        # re-homed by the CoW split to recompute its last position)
        t_map0 = self._clock()
        self._pagein_acc = 0.0
        offset = self._map_slot_pages(slot, tokens,
                                      match=not (self._quant and base))
        t_map1 = self._clock()
        pagein_s = self._pagein_acc
        req.status = "prefilling"
        if req.tenant is not None:
            self._tenant_child("admitted", req.tenant).inc()
        m = self._metrics
        pc = self.prefix_cache
        if pc is not None:
            telemetry.request_log.event(
                req.id, self._eid, "prefix_match", cached_tokens=offset)
            if offset:
                m["prefix_hits"].inc()
                m["prefix_tokens_saved"].inc(offset)
            else:
                m["prefix_misses"].inc()
        # KV tier of THIS admission: a page-in during the match means
        # the prefix came back from the host tier; a hit without one
        # was device-resident; no cached prefix is a cold start
        self._kv_tier[slot] = "spilled" if pagein_s > 0.0 \
            else ("resident" if offset else "cold")
        self._chunks_fed[slot] = 0
        if not base:
            # latency SLO metrics describe the FIRST admission only —
            # a restart's wait is retry bookkeeping, not user TTFT
            m["admission_wait"].observe(self._clock() - req.t_submit)
            # TTFT phase budget: queue_wait ends where the page map
            # begins; the map splits into prefix_match (radix walk +
            # CoW/alloc) and host_pagein (tier transfers the match
            # triggered). The remaining TTFT share lands at the first
            # token (_dispatch): prefill_chunks up to the final
            # chunk's dispatch, first_decode for that dispatch itself.
            t_enq = getattr(req, "t_enqueue", None)
            self._phase(req, "queue_wait",
                        t_map0 - (t_enq if t_enq is not None
                                  else req.t_submit))
            self._phase(req, "prefix_match",
                        (t_map1 - t_map0) - pagein_s,
                        cached_tokens=int(offset))
            if pagein_s > 0.0:
                self._phase(req, "host_pagein", pagein_s)
            req.t_mark = t_map1
        # budget: every decode step writes one KV; the last sampled
        # token is never written, so a sequence of Tp supports up to
        # max_length - Tp + 1 further generated tokens; `base` already
        # spent that much of max_new_tokens. The dispatch decrements
        # remaining when the first token is emitted.
        cap = min(req.max_new_tokens - base, self.max_length - Tp + 1)
        self._pending[slot] = np.asarray(tokens[offset:], np.int32)
        if self._quant:
            if base:
                # Replay the recorded write schedule: prefix tokens the
                # first admission attached (best-effort re-chunked on
                # the natural grid — those positions were never computed
                # here), then the recorded prefill chunks, then every
                # emitted token as its own 1-token chunk, exactly how
                # decode wrote it. The trim below keeps the plan honest
                # if a replica with a different chunk_tokens adopted us.
                plan, head = [], int(req.kv_attach)
                while head > 0:
                    plan.append(min(head, self.chunk_tokens))
                    head -= plan[-1]
                plan += [int(c) for c in req.kv_history]
                tot, trimmed = 0, []
                for c in plan:
                    c = min(c, Tp - tot)
                    if c <= 0:
                        break
                    trimmed.append(c)
                    tot += c
                trimmed += [1] * (Tp - tot)
                req.kv_attach = 0
                req.kv_history = list(trimmed)
                self._replay[slot] = deque(trimmed)
            else:
                # fresh admission: nothing emitted yet, so the schedule
                # is free — reset the recording (a pre-first-token
                # rollback may have recorded chunks it then discarded)
                req.kv_history = []
                req.kv_attach = int(offset)
                self._replay[slot] = None
        self._base[slot] = base
        self._lengths[slot] = offset
        self._cur_tok[slot] = 0
        self._remaining[slot] = cap
        self._counters[slot] = base
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = False
        if self.speculative:
            self._hist[slot] = None     # drafting starts after prefill
        self._sync_slot(slot)
        m["prefill_pending"].set(self._pending_tokens())
        if pc is not None or self.adapter_pool is not None:
            self._set_pool_gauges()
        return None

    def _pending_tokens(self):
        return sum(int(p.size) for p in self._pending if p is not None)

    # -- tensor parallelism ------------------------------------------------
    def _kv_pspec(self):
        """KV pool layout under tp: (L, pages, page, H, Dh) with the
        HEAD axis split over the mesh. Page structure is replicated, so
        the page table, the lock mask, and every host-side lease
        decision are shard-count-independent — prefix sharing, CoW and
        migration never see the mesh."""
        return PartitionSpec(None, None, None, AXIS_TP, None)

    def _scale_pspec(self):
        # int8 dequant scales are per-(layer, page, head): they shard
        # head-wise alongside the codes they decode
        return PartitionSpec(None, None, AXIS_TP)

    def _rep(self, arr):
        """Replicate a freshly-built array onto the tp mesh (identity
        at tp=1). Every dispatch operand must keep a STABLE layout
        across calls — an operand flipping between single-device and
        mesh-replicated would be a new jit cache entry, i.e. a
        steady-state recompile."""
        if self._mesh is None:
            return arr
        return jax.device_put(
            arr, named_sharding(PartitionSpec(), mesh=self._mesh))

    def _placed_params(self):
        """The dispatch's weight operands, placed onto the tp mesh ONCE
        per array (cached by identity, the source pinned so ids can't
        be recycled): qkv/fc1 column-sharded, proj/fc2 row-sharded,
        embeddings and norms replicated. set_data swaps the underlying
        array and therefore re-places. With weight_dtype="int8" the
        quantized positions carry the int8 CODE arrays instead of the
        fp32 weights — same positions, same specs, stable identities
        (quantized once at construction), so the jit cache and the
        placement cache behave exactly as in the fp path."""
        if self._w8:
            datas = tuple(
                self._w8_codes[i] if i in self._w8_codes
                else p.data()._data
                for i, p in enumerate(self._params))
        else:
            datas = tuple(p.data()._data for p in self._params)
        if self._mesh is None:
            return datas
        placed = []
        for d, spec in zip(datas, self._param_specs):
            hit = self._placed.get(id(d))
            if hit is None:
                hit = (d, jax.device_put(
                    d, named_sharding(spec, mesh=self._mesh)))
                self._placed[id(d)] = hit
            placed.append(hit[1])
        return tuple(placed)

    def _placed_slab(self, arrs):
        """Mesh placement for the adapter slab leaves (A sharded on its
        input/U axis, B on its output axis — the SAME head-aligned
        split as the base weights, so the per-shard LoRA delta lands in
        the projection's psum; scales replicated). Cached by identity
        and replaced wholesale when a page-in swaps the slab."""
        key = tuple(map(id, arrs))
        cache = self._slab_cache
        if cache is not None and cache[0] == key:
            return cache[2]
        specs = [PartitionSpec(None, None, None, AXIS_TP, None),
                 PartitionSpec(None, None, None, None, AXIS_TP)]
        specs += [PartitionSpec()] * (len(arrs) - 2)
        placed = tuple(
            jax.device_put(a, named_sharding(s, mesh=self._mesh))
            for a, s in zip(arrs, specs))
        self._slab_cache = (key, arrs, placed)
        return placed

    # -- unified dispatch --------------------------------------------------
    def _unified_fn(self):
        """The unified program for this dispatch: greedy-only (no
        sort/RNG in-program) when no active slot samples, the general
        mixed-sampling flavor otherwise. Both are cached forever — two
        compiles per engine lifetime, never per admission, never per
        prompt length."""
        greedy_only = not bool(
            self._do_sample[self.scheduler.active_slots].any())
        fn = self._programs.get(greedy_only)
        if fn is None:
            variant = "greedy" if greedy_only else "sampled"
            name = (f"unified/W{self._width}/S{self.spec_tokens}"
                    f"/{variant}" if self.speculative
                    else f"unified/W{self._width}/{variant}")
            if self._tp > 1:
                name += f"/tp{self._tp}"
            if self._w8:
                name += "/w8"
            fn = self._wrap_program(self._build_unified(greedy_only),
                                    name)
            self._programs[greedy_only] = fn
        return fn

    def _build_unified(self, greedy_only=False):
        """ONE fixed-shape program for every kind of work a slot can
        carry in a dispatch (ISSUE 11 / ROADMAP §2): per-slot q_counts
        route each of the B rows down the span kernel as a prefill
        chunk (chunk_len), a decode step (1), a speculative verify
        (1 + drafts), or idle (0). Dead query rows write no KV and emit
        exact zeros, so activity is runtime DATA — the program's shape
        never changes after its first compile."""
        model, params = self.model, self._params
        W, impl = self._width, self.attn_impl
        spec = self.speculative
        S = self.spec_tokens
        quant = self._quant
        tp = self._tp
        # w8: positions whose param_arrays entry is an int8 code array;
        # the per-out-tile dequant scales arrive as the operands right
        # after the KV scale pools and are bound to the traced code
        # arrays by identity (ops.nn registry) for the duration of the
        # trace — the same trace-time ctx discipline as the adapter/tp
        # contexts above, because apply_op strips NDArray attributes
        # before FullyConnected runs
        w8_idx = tuple(q.index for q in self._w8_plan)

        def unified(param_arrays, kp, vp, table, lock, lengths, cur_tok,
                    done, remaining, counters, seeds, temp, top_k,
                    top_p, do_sample, eos, toks_in, chunk_len, is_final,
                    decode_mask, *rest):
            if spec:
                drafts, n_draft, *rest = rest
            if quant:
                ks, vs, *rest = rest
            wscales = ()
            if w8_idx:
                wscales = tuple(rest[:len(w8_idx)])
                rest = rest[len(w8_idx):]
            adapter = tuple(rest)
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            prev_ctx = None
            if adapter:
                aslot, a_A, a_B, a_scale, *a_qs = adapter
                prev_ctx = _set_adapter_ctx(
                    (a_A, a_B, a_scale, aslot) + tuple(a_qs))
            # tp > 1: this body traces INSIDE the shard_map, so the
            # model sees per-shard weight slices; the tp context makes
            # the attention head split and the proj/fc2 psum explicit
            prev_tp = _set_tp_ctx((AXIS_TP, tp)) if tp > 1 else None
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                for i, s in zip(w8_idx, wscales):
                    register_w8_weight(param_arrays[i], s)
                active = decode_mask & (~done) & (remaining > 0)
                prefilling = chunk_len > 0
                finishing = prefilling & is_final
                if spec:
                    nd = jnp.where(active, n_draft, 0)
                    qn = jnp.where(prefilling, chunk_len,
                                   jnp.where(active, 1 + nd, 0))
                else:
                    qn = jnp.where(prefilling, chunk_len,
                                   jnp.where(active, 1, 0))
                if quant:
                    cache = PagedKVCache(kp, vp, table, lengths,
                                         page_lock=lock, spans=qn,
                                         k_scale=ks, v_scale=vs,
                                         attn_impl=impl)
                else:
                    cache = PagedKVCache(kp, vp, table, lengths,
                                         page_lock=lock, spans=qn,
                                         attn_impl=impl)
                logits, cache = model.forward(NDArray(toks_in), cache)
                lg = logits._data
                pos = jnp.arange(W)[None, :]
                live = pos < qn[:, None]
                # in-program finite guard over LIVE positions only: a
                # slot whose logits went non-finite (corrupted KV,
                # numeric blowup) is flagged; the host discards its
                # tokens from this dispatch and re-prefills the request
                ok = jnp.isfinite(
                    jnp.where(live[:, :, None], lg, 0.0)
                ).all(axis=(1, 2)) | ~(active | prefilling)
                # the token every non-verify row samples: a decode row
                # reads position 0, a finishing prefill reads its last
                # live position — the distribution of the token after
                # the full prompt
                sel = jnp.take_along_axis(
                    lg, jnp.maximum(chunk_len - 1, 0)[:, None, None],
                    axis=1)[:, 0]
                if greedy_only:
                    nxt = jnp.argmax(sel, axis=-1).astype(jnp.int32)
                else:
                    keys = slot_keys(seeds, counters)
                    nxt = sample_tokens(sel, keys, do_sample, temp,
                                        top_k, top_p)
                if spec:
                    emitted, n_acc = verify_tokens(
                        lg[:, :S], drafts, nd, seeds, counters,
                        do_sample, temp, top_k, top_p,
                        greedy_only=greedy_only)
                    vpos = jnp.arange(S)[None, :]
                    # emit the accepted drafts + one verifier token,
                    # capped by the remaining budget, truncated at the
                    # first eos; only the emitted count advances
                    # `lengths` — rejected drafts' KV stays behind the
                    # length (invisible) and is overwritten in place
                    n_em = jnp.minimum(n_acc + 1, remaining)
                    hit = ((emitted == eos[:, None])
                           & (eos >= 0)[:, None]
                           & (vpos < n_em[:, None]))
                    any_hit = hit.any(axis=1)
                    n_em = jnp.where(
                        any_hit,
                        jnp.minimum(n_em, jnp.argmax(hit, 1) + 1),
                        n_em)
                    n_em = jnp.where(active, n_em, 0)
                    # a finishing prefill emits exactly its first token
                    n_em = jnp.where(finishing, 1, n_em)
                    toks = jnp.where(vpos < n_em[:, None], emitted, -1)
                    toks = jnp.where(
                        finishing[:, None],
                        jnp.where(vpos == 0, nxt[:, None], -1), toks)
                    last = jnp.take_along_axis(
                        emitted, jnp.maximum(n_em - 1, 0)[:, None],
                        axis=1)[:, 0]
                    last = jnp.where(finishing, nxt, last)
                    stop = jnp.where(finishing,
                                     (nxt == eos) & (eos >= 0), any_hit)
                    n_acc_em = jnp.minimum(n_acc, n_em)
                else:
                    n_em = jnp.where(active | finishing, 1, 0)
                    toks = jnp.where((active | finishing)[:, None],
                                     nxt[:, None], -1)
                    last = nxt
                    stop = (nxt == eos) & (eos >= 0)
                    n_acc_em = jnp.zeros_like(n_em)
                emit = active | finishing
                # a prefill chunk advances by the tokens it FED (the
                # first sampled token is never written — the next
                # decode writes it); a verify row by the tokens emitted
                adv = jnp.where(prefilling, chunk_len,
                                jnp.where(active, n_em, 0))
                new_len = lengths + adv
                new_rem = remaining - jnp.where(emit, n_em, 0)
                new_done = done | (emit & (stop | (new_rem <= 0)))
                new_cur = jnp.where(emit, last, cur_tok)
                new_cnt = counters + jnp.where(emit, n_em, 0)
            finally:
                for i in w8_idx:
                    deregister_w8_weight(param_arrays[i])
                if adapter:
                    _set_adapter_ctx(prev_ctx)
                if tp > 1:
                    _set_tp_ctx(prev_tp)
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            out = (cache.k_pages, cache.v_pages, new_len, new_cur,
                   new_done, new_rem, new_cnt, ok, toks, n_em,
                   n_acc_em)
            if quant:
                out = out + (cache.k_scale, cache.v_scale)
            return out

        # the scale pools are state like kp/vp: donated through every
        # dispatch (positions 20/21, or 22/23 after the spec operands)
        donate = (1, 2)
        if quant:
            donate += (22, 23) if spec else (20, 21)
        if tp == 1:
            return jax.jit(unified, donate_argnums=donate)
        # tp > 1: the SAME body runs shard_map'ed over the {tp: N}
        # mesh. KV pools and int8 scales enter/leave split on the head
        # axis; weights enter per the serving tp rules; everything the
        # host schedules with (tables, locks, slot scalars, token
        # grids, drafts) is replicated, and every scalar OUTPUT is
        # replicated too — each shard computes the identical
        # post-psum sampler, so the result is well-defined without a
        # replication check (check_rep off: psum breaks jax's
        # conservative replication inference).
        kv, rep = self._kv_pspec(), PartitionSpec()
        # positions 3..19: table, lock, the 11 slot scalars, toks_in,
        # chunk_len, is_final, decode_mask — all replicated
        in_specs = [tuple(self._param_specs), kv, kv] + [rep] * 17
        if spec:
            in_specs += [rep, rep]            # drafts, n_draft
        if quant:
            in_specs += [self._scale_pspec()] * 2
        # w8 dequant scales: column-parallel scales shard with the out
        # dim they describe, row-parallel scales are replicated (see
        # serving/weight_quant.py for why row scales are shard-
        # invariant); read-only, so never donated
        in_specs += [q.scale_spec for q in self._w8_plan]
        if self.adapter_pool is not None:
            in_specs += [rep,                  # aslot
                         PartitionSpec(None, None, None, AXIS_TP,
                                       None),  # A (input/U axis)
                         PartitionSpec(None, None, None, None,
                                       AXIS_TP),  # B (output axis)
                         rep]                  # scale
            if self.adapter_pool.quantized:
                in_specs += [rep, rep]         # a_scale, b_scale
        out_specs = [kv, kv] + [rep] * 9
        if quant:
            out_specs += [self._scale_pspec()] * 2
        fn = shard_map_compat(unified, mesh=self._mesh,
                              in_specs=tuple(in_specs),
                              out_specs=tuple(out_specs),
                              check_rep=False)
        return jax.jit(fn, donate_argnums=donate)

    def _dispatch(self):
        """ONE unified dispatch: assemble the per-slot work rows
        (prefill chunk / decode / verify / idle) on the host, run the
        fixed-shape program, then fan the results back out — emitted
        tokens, chunk-cursor advances, first tokens of prompts whose
        final chunk landed, and finish/rollback bookkeeping."""
        spec = self.speculative
        spec_on = spec and not self._degraded
        B, W = self.num_slots, self._width
        S = self.spec_tokens if spec else 1
        toks_in = np.zeros((B, W), np.int32)
        chunk_len = np.zeros(B, np.int32)
        is_final = np.zeros(B, bool)
        decode_mask = np.zeros(B, bool)
        drafts = np.zeros((B, S - 1), np.int32) if spec else None
        n_draft = np.zeros(B, np.int32)
        budget = self.prefill_chunk_budget
        active_slots = list(self.scheduler.active_slots)
        self._fire_hook("decode", [self.scheduler.request_at(s)
                                   for s in active_slots])
        # prefill-budget fairness: visit slots round-robin from a
        # rotating cursor, so concurrent long prompts take turns when
        # the budget can't cover everyone each dispatch
        for slot in sorted(active_slots,
                           key=lambda s: (s - self._chunk_rr) % B):
            pend = self._pending[slot]
            if pend is not None and pend.size:
                rq = self._replay[slot]
                if rq:
                    # quantized restart: feed the recorded chunk size
                    # exactly — splitting it would re-quantize deep
                    # layers under different scale views and break
                    # continuation bit-identity. A chunk the current
                    # dispatch budget can't cover waits for a fresh
                    # budget; only one that can NEVER fit is split.
                    want = min(int(rq[0]), self.chunk_tokens)
                    if want > budget and want <= self.prefill_chunk_budget:
                        continue
                    n = min(want, budget)
                    if n <= 0:
                        continue
                    if n >= int(rq[0]):
                        rq.popleft()
                    else:
                        rq[0] = int(rq[0]) - n
                else:
                    n = min(int(pend.size), self.chunk_tokens, budget)
                    if n <= 0:
                        continue    # budget spent: the chunk waits
                    if self._quant:
                        self.scheduler.request_at(slot) \
                            .kv_history.append(n)
                budget -= n
                toks_in[slot, :n] = pend[:n]
                chunk_len[slot] = n
                is_final[slot] = n == pend.size
            elif not self._done[slot] and self._remaining[slot] > 0:
                decode_mask[slot] = True
                toks_in[slot, 0] = self._cur_tok[slot]
                if spec_on and self._hist[slot] is not None:
                    d = self._proposer.propose(self._hist[slot])
                    n_draft[slot] = d.size
                    drafts[slot, :d.size] = d
                    toks_in[slot, 1:1 + d.size] = d
        self._chunk_rr = (self._chunk_rr + 1) % B
        fn = self._unified_fn()
        param_datas = self._placed_params()
        st = self._dstate
        (lengths, cur_tok, done, remaining, counters, seeds, temp,
         top_k, top_p, do_sample, eos) = st[:11]
        tail, table = st[11:-1], st[-1]   # (aslot,) with the pool on
        extra = (jnp.asarray(drafts), jnp.asarray(n_draft)) \
            if spec else ()
        if self._quant:
            extra = extra + (self._ks, self._vs)
        if self._w8:
            extra = extra + self._w8_scale_ops
        t0 = self._clock()
        with span("serving.dispatch", engine=self._eid,
                  active=len(active_slots),
                  prefill_tokens=int(chunk_len.sum()),
                  drafted=int(n_draft.sum())):
            out = fn(
                param_datas, self._kp, self._vp, table, self._d_lock,
                lengths, cur_tok, done, remaining, counters, seeds,
                temp, top_k, top_p, do_sample, eos,
                jnp.asarray(toks_in), jnp.asarray(chunk_len),
                jnp.asarray(is_final), jnp.asarray(decode_mask),
                *extra, *self._adapter_args(tail))
            if self._quant:
                (self._kp, self._vp, lengths, cur_tok, done, remaining,
                 counters, okc, toks, n_em, n_acc,
                 self._ks, self._vs) = out
            else:
                (self._kp, self._vp, lengths, cur_tok, done, remaining,
                 counters, okc, toks, n_em, n_acc) = out
            self._dstate = (lengths, cur_tok, done, remaining, counters,
                            seeds, temp, top_k, top_p, do_sample,
                            eos) + tail + (table,)
            # ONE host sync per dispatch: everything small fetches
            # together (the pools stay on device, donated through)
            (self._lengths, self._cur_tok, self._done, self._remaining,
             self._counters) = (
                np.array(lengths), np.array(cur_tok), np.array(done),
                np.array(remaining), np.array(counters))
            toks, n_em, n_acc, ok = (np.asarray(toks), np.asarray(n_em),
                                     np.asarray(n_acc),
                                     np.asarray(okc))
        now = self._clock()
        dt = now - t0
        m = self._metrics
        m["decode_dispatches"].inc()
        m["decode_steps"].inc()
        m["decode_seconds"].observe(dt)
        n_chunks = int((chunk_len > 0).sum())
        if n_chunks:
            m["prefill_chunks"].inc(n_chunks)
            m["prefill_tokens"].inc(int(chunk_len.sum()))
            m["prefill_seconds"].observe(dt)
        rl = telemetry.request_log
        finished = []
        bad = []
        overflowed = []
        n_emitted = 0
        accepted = 0
        for slot in active_slots:
            req = self.scheduler.request_at(slot)
            if not ok[slot]:
                # non-finite logits: every token this dispatch produced
                # for the slot is garbage — discard it all, roll the
                # request back (handled below, after accounting)
                bad.append(slot)
                continue
            cl = int(chunk_len[slot])
            if cl:
                self._pending[slot] = self._pending[slot][cl:]
                self._chunks_fed[slot] += 1
                if rl.enabled:
                    rl.event(req.id, self._eid, "prefill_chunk",
                             dur=dt, tokens=cl,
                             final=bool(is_final[slot]))
                if not is_final[slot]:
                    req.dispatch_failures = 0
                    req.t_not_before = 0.0
                    continue
                # final chunk: the request's first token landed in the
                # same dispatch — the slot decodes from the next tick
                self._pending[slot] = None
                self._replay[slot] = None
                first = int(toks[slot, 0])
                req.output_tokens.append(first)
                req.token_times.append(now)
                streamed = self._stream_emit(req, [first])
                req.dispatch_failures = 0
                req.t_not_before = 0.0
                req.status = "running"
                rl.event(req.id, self._eid, "prefill", dur=dt,
                         first_token=first)
                m["prefills"].inc()
                n_emitted += 1
                if not self._base[slot]:
                    req.t_admit = now
                    ttft = now - req.t_submit
                    tier = self._kv_tier[slot]
                    m["ttft"].observe(ttft)
                    self._observe_ttft(req.prompt_len, ttft, tier)
                    # close the TTFT phase budget: everything between
                    # the admit mark and this dispatch's start is
                    # prefill_chunks (earlier chunk dispatches + the
                    # waits between them); the dispatch that sampled
                    # the first token is first_decode. With the marks
                    # on ONE clock the five phases sum to TTFT exactly
                    # (minus re-queue gaps on restart/migration paths).
                    t_mark = getattr(req, "t_mark", None)
                    if rl.enabled and t_mark is not None:
                        self._phase(req, "prefill_chunks", t0 - t_mark,
                                    chunks=int(self._chunks_fed[slot]))
                        self._phase(req, "first_decode", dt)
                        rl.event(req.id, self._eid, "first_token",
                                 ttft=ttft, kv_tier=tier)
                    self._observe_phase_budget(req, tier)
                    telemetry.slo.observe_ttft(
                        ttft, priority=req.priority, tenant=req.tenant)
                pc = self.prefix_cache
                if pc is not None:
                    # adopt the PROMPT's full pages into the radix
                    # tree: the next request sharing this prefix
                    # attaches instead of recomputing. Membership
                    # changes the page_lock mask — refresh the device
                    # copy before the next dispatch.
                    n_full = req.prompt_len // self.page_size
                    if n_full:
                        pc.insert(
                            req.prompt,
                            [int(p)
                             for p in self._table_host[slot][:n_full]])
                        self._d_lock = self._rep(jnp.asarray(
                            self._page_lock_host()))
                    self._set_pool_gauges()
                if spec:
                    self._hist[slot] = [int(t) for t in req.prompt] \
                        + [int(t) for t in req.output_tokens]
                if not streamed:
                    overflowed.append(slot)
                elif self._done[slot] or self._remaining[slot] <= 0:
                    finished.append(self._finish(slot))
                continue
            if not decode_mask[slot]:
                continue            # chunk queued but out of budget
            n = int(n_em[slot])
            emitted = [int(t) for t in toks[slot, :n]]
            req.output_tokens.extend(emitted)
            req.token_times.extend([now] * n)
            streamed = self._stream_emit(req, emitted) if n else True
            # a clean dispatch clears the request's failure history —
            # probation is for consecutive faults, not per-lifetime
            req.dispatch_failures = 0
            req.t_not_before = 0.0
            if spec and self._hist[slot] is not None:
                self._hist[slot].extend(emitted)
            if rl.enabled:
                if spec:
                    rl.event(req.id, self._eid, "verify", dur=dt,
                             drafted=int(n_draft[slot]),
                             accepted=int(n_acc[slot]), tokens=n)
                else:
                    rl.event(req.id, self._eid, "decode", dur=dt,
                             tokens=n)
            n_emitted += n
            accepted += int(n_acc[slot])
            # dispatch resolution: a slot that got n of this dispatch's
            # tokens saw dt/n per token — the ACTUAL emitted count
            if n:
                m["token_latency"].observe(dt / n, n)
            if not streamed:
                overflowed.append(slot)
            elif self._done[slot] or self._remaining[slot] <= 0:
                finished.append(self._finish(slot))
        for slot in overflowed:
            finished.append(self._overflow_cancel(slot))
        m["tokens_emitted"].inc(n_emitted)
        m["prefill_pending"].set(self._pending_tokens())
        if spec:
            drafted = int(n_draft.sum())
            m["spec_draft_tokens"].inc(drafted)
            m["spec_accepted_tokens"].inc(accepted)
            m["spec_rollbacks"].inc(drafted - accepted)
            # goodput: the unified program computes B x W query
            # positions a dispatch; the drafted-but-rejected share is
            # speculation waste (idle padding is a separate,
            # structural cost the MFU gauges already show)
            waste = (drafted - accepted) / (B * W)
        else:
            waste = 0.0
        self._account_flops(fn.program, dt, wasted_fraction=waste)
        if bad:
            finished.extend(self._on_bad_slots(
                bad, "non-finite logits in unified dispatch"))
        return finished

    # -- per-request token streaming (serving/frontend.py subscribes) ------
    def _stream_emit(self, req, tokens):
        """Feed freshly emitted tokens to the request's subscriber
        stream, if any (duck-typed: anything with emit(list) -> bool).
        Returns False when the stream's bounded buffer could not absorb
        them — the slow-client overflow signal. A raising subscriber is
        treated the same way; it must never take the engine down."""
        st = req.stream
        if st is None:
            return True
        try:
            return bool(st.emit(tokens))
        except Exception:           # noqa: BLE001 — subscriber fault
            return False

    def _stream_close(self, req):
        """Close the request's subscriber stream (if any) with its
        terminal status, waking any reader blocked on it. Best-effort
        and exception-proof for the same reason as _stream_emit."""
        st = req.stream
        if st is None:
            return
        try:
            st.close(req.status)
        except Exception:           # noqa: BLE001 — subscriber fault
            pass

    def _overflow_cancel(self, slot):
        """Slow-client policy: the request's subscriber stream could
        not absorb this dispatch's tokens (bounded buffer full).
        Rather than queue tokens unboundedly on the host, cancel the
        request — slot, page, and adapter leases released, terminal
        `cancelled(stream_overflow)`. The stream closes with its
        overflow flag set, so the front-end sends the client a
        structured overflow error event instead of silently dropping
        tokens."""
        req = self._release_slot(slot)
        req.status = "cancelled"
        self._metrics["requests_cancelled"].inc()
        telemetry.request_log.end(
            req.id, self._eid, "cancelled", reason="stream_overflow",
            tokens=len(req.output_tokens))
        telemetry.flight.record("stream_overflow", engine=self._eid,
                                request=req.id)
        self._stream_close(req)
        self._set_pool_gauges()
        return req

    def _release_slot(self, slot):
        """Free a slot mid-flight or at completion: scheduler slot back
        to the pool, page leases released, in-program writes parked OOB
        (length = max_length) so the recycled pages can't be touched."""
        req = self.scheduler.release(slot)
        req.t_finish = self._clock()
        self._pending[slot] = None
        self._replay[slot] = None
        self._done[slot] = True
        self._remaining[slot] = 0
        self._lengths[slot] = self.max_length
        self._free_slot_pages(slot)
        self._release_adapter(slot)
        if self.speculative:
            self._hist[slot] = None
        self._sync_slot(slot)
        return req

    def _release_adapter(self, slot):
        """Drop the slot's adapter pin (no-op without a pool or for the
        null adapter) and park the slot on slab slot 0 so the next
        _sync_slot uploads a null-adapter row."""
        if self.adapter_pool is None:
            return
        aid = self._adapter_of[slot]
        if aid is not None:
            self.adapter_pool.release(aid)
            self._adapter_of[slot] = None
        self._aslot[slot] = 0

    def _finish(self, slot):
        # read the stop cause BEFORE release zeroes the slot state:
        # budget exhaustion leaves remaining <= 0, eos leaves budget
        reason = "budget" if self._remaining[slot] <= 0 else "eos"
        req = self._release_slot(slot)
        req.status = "finished"
        self._finish_times.append(self._clock())   # drain-rate window
        self._metrics["requests_finished"].inc()
        if req.t_admit is not None and req.t_finish > req.t_admit \
                and len(req.output_tokens) > 1:
            # per-request decode goodput (tokens/s from first token to
            # finish) — the goodput_min SLO's observation stream
            telemetry.slo.observe_goodput(
                (len(req.output_tokens) - 1)
                / (req.t_finish - req.t_admit),
                priority=req.priority, tenant=req.tenant)
        telemetry.request_log.end(
            req.id, self._eid, "finished", reason=reason,
            tokens=len(req.output_tokens))
        self._stream_close(req)
        self._set_pool_gauges()
        return req
