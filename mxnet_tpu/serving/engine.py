"""Continuous-batching serving engine.

Execution model (docs/SERVING.md):

  * B fixed decode SLOTS share one PagedKVCache page pool. Each slot has
    its own live length; the decode forward runs all B slots through the
    ragged paged-attention kernel, so per-token HBM traffic is the sum
    of LIVE lengths, not B × max_length.
  * PAGE OWNERSHIP is explicit: a host-side ref-counted allocator
    (serving/page_pool.py) hands each admitted request its pages, and a
    radix-tree prefix cache (serving/prefix_cache.py) lets requests
    SHARE the pages of a common prompt prefix — admission does a
    longest-prefix match, maps the cached pages into the slot's table
    by page-table surgery, and prefills only the uncached suffix.
    Shared pages are read-only through the page table (the decode
    kernel is unchanged); the in-program page_lock mask plus a host
    copy-on-write split for fully-cached prompts guarantee no write
    ever lands in a shared page.
  * PREFILL is one compiled program per SUFFIX-length bucket: it writes
    the suffix's KV into the slot's pages at the prefix offset
    (attention reads the cached prefix through the same table) and
    samples the request's first token.
  * DECODE runs K steps per host dispatch via lax.scan — the
    TrainStep.run_steps pattern applied to serving. PERF_NOTES measured
    ~24 ms/step of host dispatch tax over a remote tunnel; at one
    token per step that tax would dominate decode, so the block size K
    amortizes it K-fold.
  * SPECULATIVE mode (speculative=True) replaces the K-step scan with
    ONE multi-query forward per dispatch: a host-side prompt-lookup
    drafter (serving/speculative.py) proposes up to spec_tokens-1
    candidates from each request's own history, the multi-query ragged
    kernel verifies all of them under per-position causal offsets, and
    only the accepted count advances the slot's length — greedy output
    bit-identical to spec-off, sampled output distribution-preserving.
  * Per-slot scalar state (lengths, budgets, sampling knobs, tables,
    page_lock) is DEVICE-RESIDENT between dispatches; admission/finish/
    cancel upload one slot's delta in one jitted scatter (_sync_slot),
    so a decode dispatch pays zero host->device state uploads.
  * Between dispatches the host frees finished slots (releasing page
    leases back to the pool/prefix cache) and admits queued requests
    (FIFO) — continuous batching: nobody waits for the slowest
    sequence in a fixed batch.

Everything per-request (sampling knobs, seeds, eos, budgets) is a
per-slot ARRAY in the compiled program, so admission never recompiles;
the only shape-churn axis is the prefill bucket, and those programs live
in a bounded LRU (gluon.block.LRUTraceCache).

ROBUSTNESS (docs/SERVING.md "Robustness"): step() is supervised — a
dispatch exception no longer wedges the engine. The supervisor catches
it, audits the page pool, rolls the implicated slots back (leases
released, state parked), re-queues innocents with backoff, and
quarantines a request whose dispatches fail `max_retries` times
(terminal reason="error"). Requests carry deadlines (expired queued
work is shed before admission; running work past deadline is cancelled
at the next dispatch boundary) and priority classes; an attached
SheddingPolicy (serving/policy.py) sheds or down-prioritizes work
before it queues and latches graceful degradation under sustained
overload. A re-queued, partially-decoded request restarts by
prefilling prompt+emitted and resuming its RNG counter at the next
token index — per-request streams are keyed (seed, token_index), so
restarted outputs are bit-identical to an uninterrupted run.
"""
from __future__ import annotations

import inspect
import itertools
import time
import weakref
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from ..telemetry import cost as _cost
from ..telemetry import ledger as _ledger
from ..base import MXNetError
from ..gluon.block import LRUTraceCache, _trace_channel
from ..models.kv_cache import PagedKVCache
from ..ndarray.ndarray import NDArray
from ..telemetry import server as _tserver
from ..telemetry import span
from ..models.gpt2 import set_adapter_ctx as _set_adapter_ctx
from .adapters import AdapterPoolExhausted
from .page_pool import PagePool, PagePoolExhausted
from .prefix_cache import PrefixCache
from .sampling import sample_tokens, slot_keys
from .scheduler import (QueueFullError, Request, ShedError,
                        SlotScheduler, TenantQuotaError, _seq_counter)
from .speculative import PromptLookupProposer, verify_tokens

__all__ = ["ServingEngine"]

_engine_ids = itertools.count()

# Engine metrics live as per-engine labeled children (engine=<ordinal>)
# of process-global instruments: `ServingEngine.stats` reads this
# engine's children, the registry/prometheus view aggregates across
# engines. docs/OBSERVABILITY.md catalogs each one.
_E = ("engine",)


def _engine_metrics(eid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    m = {
        "prefills": c("serving_prefill_total",
                      "prefill dispatches (one per admitted request)", _E),
        "prefill_tokens": c(
            "serving_prefill_tokens_total",
            "prompt tokens actually computed by prefill (the uncached "
            "suffix only when the prefix cache hits)", _E),
        "decode_dispatches": c("serving_decode_dispatch_total",
                               "compiled K-step decode blocks run", _E),
        "decode_steps": c("serving_decode_steps_total",
                          "decode steps run (dispatches x K)", _E),
        "tokens_emitted": c("serving_tokens_emitted_total",
                            "tokens sampled and handed to requests", _E),
        "requests_finished": c("serving_requests_finished_total",
                               "requests completed (eos or budget)", _E),
        "requests_rejected": c(
            "serving_requests_rejected_total",
            "submissions refused (queue full / prompt too long)", _E),
        "requests_cancelled": c(
            "serving_requests_cancelled_total",
            "requests aborted via cancel() (queued or running)", _E),
        "prefix_hits": c(
            "serving_prefix_cache_hits_total",
            "admissions whose prompt matched >= 1 cached page", _E),
        "prefix_misses": c(
            "serving_prefix_cache_misses_total",
            "admissions with no cached prefix", _E),
        "prefix_tokens_saved": c(
            "serving_prefix_tokens_saved_total",
            "prompt tokens skipped at prefill (attached from cache)", _E),
        "prefix_evicted_pages": c(
            "serving_prefix_cache_evicted_pages_total",
            "cached pages reclaimed by the LRU-by-leaf policy", _E),
        "spec_draft_tokens": c(
            "serving_spec_draft_tokens_total",
            "draft tokens proposed by the prompt-lookup drafter", _E),
        "spec_accepted_tokens": c(
            "serving_spec_accepted_tokens_total",
            "draft tokens accepted by verification and emitted", _E),
        "spec_rollbacks": c(
            "serving_spec_rollbacks_total",
            "draft tokens rejected by verification (their KV stays "
            "invisible and is overwritten in place)", _E),
        "model_flops": c(
            "serving_model_flops_total",
            "registered cost_analysis FLOPs of every dispatched "
            "prefill/decode/verify program (goodput numerator)", _E),
        "wasted_flops": c(
            "serving_wasted_flops_total",
            "FLOPs spent on drafted-but-rejected speculative "
            "positions (program FLOPs x rejected share)", _E),
        "flops_per_token": g(
            "serving_flops_per_token",
            "model FLOPs per emitted token (goodput: "
            "model_flops_total / tokens_emitted_total)", _E),
        "admission_capacity": g(
            "serving_admission_capacity",
            "estimated max concurrent requests at the current page "
            "budget: active slots + (free + idle cached pages) / "
            "pages per slot", _E),
        "queue_depth": g("serving_queue_depth",
                         "requests waiting for a slot", _E),
        "slot_occupancy": g("serving_slot_occupancy",
                            "slots decoding right now", _E),
        "num_slots": g("serving_slots", "configured decode slots", _E),
        "prefix_cache_pages": g(
            "serving_prefix_cache_pages",
            "KV pages held by the prefix-cache radix tree", _E),
        "prefix_pages_shared": g(
            "serving_prefix_pages_shared",
            "pool pages currently mapped by more than one lease", _E),
        "pool_free_pages": g("serving_page_pool_free",
                             "unallocated pages in the KV page pool", _E),
        "admission_wait": h("serving_admission_wait_seconds",
                            "submit -> slot admission wait", _E),
        "ttft": h("serving_ttft_seconds",
                  "submit -> first token (queue wait + prefill)", _E),
        "token_latency": h(
            "serving_token_latency_seconds",
            "per-token decode latency at decode-block resolution "
            "(dispatch wall / K, weighted by tokens emitted)", _E),
        "prefill_seconds": h("serving_prefill_seconds",
                             "prefill dispatch wall time", _E),
        "decode_seconds": h("serving_decode_dispatch_seconds",
                            "K-step decode block wall time", _E),
        "drain_seconds": h("serving_drain_seconds",
                           "serve(): last submit -> queue+slots empty", _E),
        "dispatch_errors": c(
            "serving_dispatch_errors_total",
            "dispatch faults the engine supervisor caught (batch rolled "
            "back, engine kept serving)", _E),
        "dispatch_retries": c(
            "serving_dispatch_retries_total",
            "requests re-queued with backoff after a caught dispatch "
            "fault or transient allocation failure", _E),
        "requests_failed": c(
            "serving_requests_failed_total",
            "requests quarantined after max_retries failed dispatches "
            "(terminal reason=\"error\")", _E),
        "overload_level": g(
            "serving_overload_level",
            "shedding-policy assessment: 0 ok, 1 elevated, "
            "2 overloaded", _E),
        "degraded": g(
            "serving_degraded",
            "1 while the engine is gracefully degraded (speculation "
            "suspended, /healthz flagged)", _E),
        "retry_after": g(
            "serving_retry_after_seconds",
            "drain-rate estimate of when a rejected submission could "
            "succeed (attached to shed / queue-full rejections)", _E),
        "adapter_page_ins": c(
            "serving_adapter_page_ins_total",
            "LoRA adapters paged into the device slab (slab-slot scatter "
            "on an acquire miss)", _E),
        "adapter_evictions": c(
            "serving_adapter_evictions_total",
            "resident LoRA adapters LRU-evicted to make room for a "
            "page-in (plus explicit evict() calls)", _E),
        "adapter_resident": g(
            "serving_adapter_resident",
            "LoRA adapters currently resident in the device slab", _E),
        "adapter_pinned": g(
            "serving_adapter_pinned",
            "slab slots pinned by active requests (unevictable)", _E),
        "adapter_slab_bytes": g(
            "serving_adapter_slab_bytes",
            "device bytes held by the LoRA adapter slab (A + B + "
            "scale)", _E),
    }
    _shed_family()                  # registered per-process; children
    _tenant_families()
    return {k: inst.labels(eid) for k, inst in m.items()}


def _shed_family():
    """The one three-label family: shed traffic split by reason AND the
    shed request's priority class (aggregate reads stay cheap; the
    split is what capacity debugging needs)."""
    return telemetry.counter(
        "serving_shed_total",
        "requests shed by the robustness layer, by reason (queue_full, "
        "overload, deadline, deadline_queued, deadline_running) and "
        "priority class", ("engine", "reason", "priority"))


def _tenant_families():
    """Per-tenant families (labeled {engine, tenant}); children are
    created lazily as tenants appear in traffic, so an engine without
    tenant_quotas pays nothing."""
    return {
        "admitted": telemetry.counter(
            "serving_tenant_admitted_total",
            "requests admitted to a decode slot, split by tenant",
            ("engine", "tenant")),
        "shed": telemetry.counter(
            "serving_tenant_shed_total",
            "requests shed or rejected, split by tenant and reason "
            "(tenant_quota adds the per-tenant queue bound to the "
            "engine-wide taxonomy)", ("engine", "tenant", "reason")),
        "active": telemetry.gauge(
            "serving_tenant_active_slots",
            "decode slots currently held by each tenant",
            ("engine", "tenant")),
        "queued": telemetry.gauge(
            "serving_tenant_queued",
            "queued (admitted-but-waiting) requests per tenant",
            ("engine", "tenant")),
    }


class ServingEngine:
    """Continuous-batching generation over a model with the GPT-2 cache
    contract (forward(ids, cache) -> (logits, cache), make_cache()).

    num_slots: concurrent decode sequences (the compiled batch).
    max_length: per-slot KV capacity (prompt + generated), rounded down
        to a whole number of pages; defaults to the model's max_length.
    page_size: KV page granularity. decode_block: decode steps fused
    into one dispatch. attn_impl: 'auto' (ragged Pallas kernel on TPU,
    dense XLA elsewhere), 'pallas', 'pallas_interpret' (the kernel in
    interpret mode — CPU tests), or 'xla'. max_queue bounds the
    admission queue (None = unbounded); a full queue rejects submit()
    with QueueFullError and counts serving_requests_rejected_total.

    prefix_cache=True turns on radix-tree prompt reuse: admission
    longest-prefix-matches each prompt against previously served ones
    and attaches the shared KV pages instead of recomputing them.
    prefix_cache_pages sizes BOTH the extra physical pages added to the
    pool for retained prefixes and the tree's eviction budget (default:
    one full slot-set, num_slots * pages_per_slot). Sampled output is
    bit-identical with the cache on or off.

    speculative=True turns on prompt-lookup speculative decoding
    (serving/speculative.py, docs/SERVING.md): each decode dispatch
    feeds spec_tokens positions per slot — the current token plus up to
    spec_tokens-1 n-gram drafts from the request's own history — and
    ONE multi-query verification forward emits every accepted token.
    Greedy output is bit-identical to speculative=False; sampled output
    is distribution-preserving and reproducible across schedules.
    decode_block is ignored in this mode (a dispatch is one forward).
    spec_max_ngram/spec_min_ngram bound the lookup n-gram sizes.

    Every engine reports into mx.telemetry as per-engine labeled
    children (docs/OBSERVABILITY.md): TTFT, admission wait, per-token
    decode latency, queue depth, slot occupancy, dispatch counts/wall
    times, prefix-cache hits/misses/tokens-saved/evictions. `stats` is
    a dict view of this engine's children; `reset_stats()` zeroes them.
    """

    def __init__(self, model, num_slots, max_length=None, page_size=64,
                 decode_block=8, attn_impl="auto", prefill_bucket=None,
                 dtype=None, max_queue=None, prefix_cache=False,
                 prefix_cache_pages=None, speculative=False,
                 spec_tokens=4, spec_max_ngram=3, spec_min_ngram=1,
                 num_priorities=3, policy=None, max_retries=3,
                 retry_backoff_s=0.02, clock=None, adapter_pool=None,
                 tenant_quotas=None):
        self.model = model
        cfg = model.config
        self.num_slots = int(num_slots)
        max_length = int(max_length or cfg.max_length)
        max_length -= max_length % page_size
        if max_length < page_size:
            raise MXNetError(f"max_length {max_length} < one page "
                             f"({page_size})")
        if max_length > cfg.max_length:
            raise MXNetError(f"max_length {max_length} exceeds the "
                             f"model's position range {cfg.max_length}")
        self.max_length = max_length
        self.page_size = int(page_size)
        self.decode_block = int(decode_block)
        if self.decode_block < 1:
            raise MXNetError("decode_block must be >= 1")
        self.attn_impl = attn_impl
        self.prefill_bucket = int(prefill_bucket or page_size)
        self.speculative = bool(speculative)
        self.spec_tokens = int(spec_tokens)
        if self.speculative:
            if self.spec_tokens < 2:
                raise MXNetError("spec_tokens must be >= 2 (the current "
                                 "token + at least one draft)")
            self._proposer = PromptLookupProposer(
                self.spec_tokens - 1, max_ngram=spec_max_ngram,
                min_ngram=spec_min_ngram)
            # per-slot token history (prompt + emitted) the prompt-lookup
            # drafter matches against — the request's OWN history only,
            # so drafting is schedule-independent
            self._hist = [None] * int(num_slots)
        self.scheduler = SlotScheduler(num_slots, max_queue=max_queue,
                                       num_priorities=num_priorities,
                                       tenant_quotas=tenant_quotas)
        # robustness layer (docs/SERVING.md "Robustness"): supervisor
        # retry budget + backoff, optional shedding policy, and an
        # injectable clock so deadline/backoff behavior is testable
        # without wall-time races (the default IS perf_counter)
        self.policy = policy
        self.max_retries = int(max_retries)
        if self.max_retries < 1:
            raise MXNetError("max_retries must be >= 1")
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock if clock is not None else time.perf_counter
        self._degraded = False
        self._draining = False
        self._finish_times = deque(maxlen=64)   # drain-rate window
        # extra lease rows audit_pages() should account for (the
        # fault-injection harness registers pages it holds here)
        self.audit_extra_leases = []

        self._params = list(model.collect_params().values())
        B = self.num_slots
        P = self._pages_per_slot = max_length // page_size
        # pool sizing: every slot can always claim a full P exclusive
        # pages (worst case, zero sharing) + `extra` pages so the prefix
        # cache can retain prefixes across request lifetimes
        extra = 0
        if prefix_cache:
            extra = B * P if prefix_cache_pages is None \
                else int(prefix_cache_pages)
            if extra < 0:
                raise MXNetError("prefix_cache_pages must be >= 0")
        total_pages = B * P + extra
        dt = dtype or jnp.dtype(cfg.dtype)
        pool_shape = (cfg.num_layers, total_pages, page_size,
                      cfg.num_heads, cfg.units // cfg.num_heads)
        self._kp = jnp.zeros(pool_shape, dt)
        self._vp = jnp.zeros(pool_shape, dt)
        self.page_pool = PagePool(total_pages)
        self.prefix_cache = PrefixCache(self.page_pool, page_size,
                                        budget_pages=extra) \
            if prefix_cache else None
        # per-slot page tables are HOST state now (page-table surgery at
        # admission); uploaded with each dispatch
        self._table_host = np.zeros((B, P), np.int32)
        self._mapped = np.zeros(B, bool)   # slot holds page leases
        # per-slot host state (tiny; uploaded per dispatch, fetched back
        # with the decoded tokens — one round trip per K tokens).
        # Unmapped slots park at length == max_length: their in-program
        # decode writes fall off the page table and DROP, so a freed
        # slot can never scribble on pages that were recycled to a new
        # owner or retained by the prefix cache.
        self._lengths = np.full(B, self.max_length, np.int32)
        self._cur_tok = np.zeros(B, np.int32)
        self._done = np.ones(B, bool)          # free slots are inactive
        self._remaining = np.zeros(B, np.int32)
        self._counters = np.zeros(B, np.int32)
        self._seeds = np.zeros(B, np.int32)
        self._temp = np.ones(B, np.float32)
        self._top_k = np.zeros(B, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._eos = np.full(B, -1, np.int32)
        # multi-tenant LoRA (serving/adapters.py, docs/SERVING.md
        # "Multi-tenant LoRA serving"): the pool's slab is device-
        # resident; each slot carries its adapter's SLAB SLOT index as
        # one more per-slot scalar (0 = null adapter = exact zeros), so
        # adapter identity is runtime data — never a program shape axis
        self.adapter_pool = adapter_pool
        self._aslot = np.zeros(B, np.int32)
        self._adapter_of = [None] * B   # slot -> pinned adapter_id

        self._prefill_programs = LRUTraceCache(
            max(2 * (max_length // self.prefill_bucket), 8))
        # decode programs come in two flavors selected PER DISPATCH: the
        # general mixed-sampling one and a greedy-only one that skips
        # the filtered-distribution sort and the RNG draws entirely
        # (greedy batches dominate production serving; greedy rows are
        # bit-identical through either program)
        self._decode_programs = {}

        def _copy_page(kp, vp, src, dst):
            # CoW split: clone one physical page's (L, S, H, D) slab
            return (kp.at[:, dst].set(kp[:, src]),
                    vp.at[:, dst].set(vp[:, src]))

        self._copy_page_fn = jax.jit(_copy_page, donate_argnums=(0, 1))
        # the per-slot scalar state is DEVICE-RESIDENT between decode
        # dispatches: the decode program reads these arrays directly and
        # returns the updated ones, and the host uploads deltas only on
        # admission/finish/cancel (_sync_slot) — not ~12 small
        # jnp.asarray transfers on every dispatch
        self._upload_fn = self._build_slot_upload()
        scalars = [self._lengths, self._cur_tok, self._done,
                   self._remaining, self._counters, self._seeds,
                   self._temp, self._top_k, self._top_p,
                   self._do_sample, self._eos]
        if self.adapter_pool is not None:
            scalars.append(self._aslot)
        self._dstate = tuple(jnp.asarray(a)
                             for a in scalars + [self._table_host])
        self._d_lock = jnp.asarray(self._page_lock_host())
        self._eid = str(next(_engine_ids))
        self._metrics = _engine_metrics(self._eid)
        self._metrics["num_slots"].set(self.num_slots)
        self._shed = _shed_family()
        self._shed_children = {}   # (reason, priority) -> labeled child
        self._shed_counts = {}     # same keys, host-side for stats
        self._tenant_fams = _tenant_families()
        self._tenant_children = {}   # (family, tenant[, reason]) -> child
        self._tenant_shed_counts = {}  # (tenant, reason) -> n
        self._tenants_seen = set()
        self._adapter_page_ins_seen = 0
        self._adapter_evictions_seen = 0
        self._hook_kw_cache = None
        # a collected engine must not leave /healthz stuck degraded
        weakref.finalize(self, _tserver.clear_degraded,
                         f"engine{self._eid}")
        self._evictions_seen = 0
        self._set_pool_gauges()
        # live introspection: /statusz shows this engine's config +
        # occupancy, the flight-recorder watchdog probes its progress
        # (both hold weak refs — a collected engine just drops out),
        # and every request records a lifecycle timeline into
        # telemetry.request_log. dispatch_hook is a test/extension
        # seam called at the top of every step().
        self.dispatch_hook = None
        # device-cost accounting (telemetry.cost, docs/OBSERVABILITY.md
        # "Device-cost accounting"): every program this engine builds is
        # wrapped in a CostedFunction keyed engine<eid>/<program>, so
        # compiles are attributed and MFU/roofline gauges go live.
        # mark_warm() flips the steady flag: any compile after that is a
        # retrace storm the flight recorder latches a dump for.
        self._steady = False
        telemetry.register_status_provider(
            f"engine/{self._eid}", self._statusz)
        telemetry.flight.watch(f"engine{self._eid}", self._flight_probe)
        # /readyz: readiness (warmed AND not degraded AND not draining)
        # is per-component state, distinct from /healthz liveness — an
        # intentionally-draining replica is healthy but not ready
        _tserver.register_ready_probe(f"engine{self._eid}",
                                      self._ready_probe)
        weakref.finalize(self, _tserver.unregister_ready_probe,
                         f"engine{self._eid}")
        # HBM ledger: weights + KV page slab + device-resident slot
        # state, with the prefix-cache-held page subset as an
        # informational detail (it lives inside kv_pages)
        _ledger.register(f"engine/{self._eid}", self._hbm_ledger)

    # -- telemetry ---------------------------------------------------------
    @property
    def stats(self):
        """This engine's counters/gauges as a plain dict (a live read of
        the telemetry children — the PR-1 bare-dict keys kept intact)."""
        m = self._metrics
        return {
            "prefills": int(m["prefills"].value),
            "prefill_tokens": int(m["prefill_tokens"].value),
            "decode_dispatches": int(m["decode_dispatches"].value),
            "decode_steps": int(m["decode_steps"].value),
            "tokens_emitted": int(m["tokens_emitted"].value),
            "requests_finished": int(m["requests_finished"].value),
            "requests_rejected": int(m["requests_rejected"].value),
            "requests_cancelled": int(m["requests_cancelled"].value),
            "prefix_hits": int(m["prefix_hits"].value),
            "prefix_misses": int(m["prefix_misses"].value),
            "prefix_tokens_saved": int(m["prefix_tokens_saved"].value),
            "prefix_evicted_pages": int(m["prefix_evicted_pages"].value),
            "spec_draft_tokens": int(m["spec_draft_tokens"].value),
            "spec_accepted_tokens": int(m["spec_accepted_tokens"].value),
            "spec_rollbacks": int(m["spec_rollbacks"].value),
            "model_flops": int(m["model_flops"].value),
            "wasted_flops": int(m["wasted_flops"].value),
            "admission_capacity": int(m["admission_capacity"].value),
            "prefix_cache_pages": int(m["prefix_cache_pages"].value),
            "prefix_pages_shared": int(m["prefix_pages_shared"].value),
            "pool_free_pages": int(m["pool_free_pages"].value),
            "queue_depth": int(m["queue_depth"].value),
            "slot_occupancy": int(m["slot_occupancy"].value),
            "dispatch_errors": int(m["dispatch_errors"].value),
            "dispatch_retries": int(m["dispatch_retries"].value),
            "requests_failed": int(m["requests_failed"].value),
            "overload_level": int(m["overload_level"].value),
            "degraded": int(m["degraded"].value),
            "draining": self._draining,
            "shed": sum(self._shed_counts.values()),
            "adapter_page_ins": int(m["adapter_page_ins"].value),
            "adapter_evictions": int(m["adapter_evictions"].value),
            "adapter_resident": int(m["adapter_resident"].value),
            "adapter_pinned": int(m["adapter_pinned"].value),
        }

    def tenant_stats(self):
        """Per-tenant occupancy + lifetime accounting: the scheduler's
        queued/active/admitted/quota view plus this engine's shed
        taxonomy split by tenant. Keys are stringified tenant ids."""
        out = self.scheduler.tenants_snapshot()
        for (tenant, reason), n in sorted(self._tenant_shed_counts.items()):
            row = out.setdefault(str(tenant), {})
            row.setdefault("shed", {})[reason] = n
        return out

    def reset_stats(self):
        """Zero this engine's telemetry children (other engines and the
        rest of the registry are untouched)."""
        for inst in self._metrics.values():
            inst.reset()
        for child in self._shed_children.values():
            child.reset()
        self._shed_counts = {}
        for child in self._tenant_children.values():
            child.reset()
        self._tenant_shed_counts = {}
        self._adapter_page_ins_seen = 0
        self._adapter_evictions_seen = 0
        self._metrics["num_slots"].set(self.num_slots)
        self._set_pool_gauges()

    def _shed_inc(self, reason, priority, tenant=None):
        key = (reason, int(priority))
        child = self._shed_children.get(key)
        if child is None:
            child = self._shed.labels(self._eid, reason, str(priority))
            self._shed_children[key] = child
        child.inc()
        self._shed_counts[key] = self._shed_counts.get(key, 0) + 1
        if tenant is not None:
            self._tenant_child("shed", tenant, reason).inc()
            tk = (tenant, reason)
            self._tenant_shed_counts[tk] = \
                self._tenant_shed_counts.get(tk, 0) + 1

    def _tenant_child(self, family, tenant, reason=None):
        key = (family, tenant) if reason is None \
            else (family, tenant, reason)
        child = self._tenant_children.get(key)
        if child is None:
            fam = self._tenant_fams[family]
            child = fam.labels(self._eid, str(tenant)) if reason is None \
                else fam.labels(self._eid, str(tenant), reason)
            self._tenant_children[key] = child
        self._tenants_seen.add(tenant)
        return child

    def _set_load_gauges(self):
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        self._metrics["slot_occupancy"].set(self.scheduler.num_active)
        self._metrics["admission_capacity"].set(
            self.admission_capacity_estimate())
        self._set_tenant_gauges()

    def _set_tenant_gauges(self):
        # one pass over the scheduler's queues/actives; zero the gauges
        # of tenants seen earlier but absent now so they don't stick
        sched = self.scheduler
        if not sched.tenant_quotas and not self._tenants_seen:
            return
        queued, active = {}, {}
        for q in sched._queues:
            for req in q:
                if req.tenant is not None:
                    queued[req.tenant] = queued.get(req.tenant, 0) + 1
        for req in sched._active.values():
            if req.tenant is not None:
                active[req.tenant] = active.get(req.tenant, 0) + 1
        for t in (set(queued) | set(active) | set(sched.tenant_quotas)
                  | self._tenants_seen):
            if t is None:
                continue
            self._tenant_child("queued", t).set(queued.get(t, 0))
            self._tenant_child("active", t).set(active.get(t, 0))

    def admission_capacity_estimate(self):
        """Max concurrent requests the current page budget supports:
        the slots already decoding plus how many more worst-case
        (full-length, zero-sharing) requests the pool could map —
        idle prefix-cache pages count as reclaimable. Derived from the
        same accounting the HBM ledger reports, published as
        serving_admission_capacity (never above num_slots)."""
        free = self.page_pool.num_free
        if self.prefix_cache is not None:
            idle = int((self.prefix_cache.member_mask()
                        & (self.page_pool.refcounts() == 0)).sum())
            free += idle
        return min(self.scheduler.num_active + free // self._pages_per_slot,
                   self.num_slots)

    def _set_pool_gauges(self):
        m = self._metrics
        m["pool_free_pages"].set(self.page_pool.num_free)
        m["prefix_pages_shared"].set(
            int(self.page_pool.shared_mask().sum()))
        pc = self.prefix_cache
        if pc is not None:
            m["prefix_cache_pages"].set(pc.num_pages)
            delta = pc.evicted_pages - self._evictions_seen
            if delta:
                m["prefix_evicted_pages"].inc(delta)
                self._evictions_seen = pc.evicted_pages
        pool = self.adapter_pool
        if pool is not None:
            m["adapter_resident"].set(pool.num_resident)
            m["adapter_pinned"].set(pool.num_pinned)
            m["adapter_slab_bytes"].set(pool.slab_bytes())
            delta = pool.page_ins - self._adapter_page_ins_seen
            if delta:
                m["adapter_page_ins"].inc(delta)
                self._adapter_page_ins_seen = pool.page_ins
            delta = pool.evictions - self._adapter_evictions_seen
            if delta:
                m["adapter_evictions"].inc(delta)
                self._adapter_evictions_seen = pool.evictions

    def _statusz(self):
        """The /statusz + flight-recorder view of this engine: static
        config, the scheduler's slot/queue snapshot, and the headline
        rates derived from this engine's counters."""
        s = self.stats
        lookups = s["prefix_hits"] + s["prefix_misses"]
        drafted = s["spec_draft_tokens"]
        return {
            "config": {
                "num_slots": self.num_slots,
                "max_length": self.max_length,
                "page_size": self.page_size,
                "decode_block": self.decode_block,
                "attn_impl": self.attn_impl,
                "prefill_bucket": self.prefill_bucket,
                "prefix_cache": self.prefix_cache is not None,
                "speculative": self.speculative,
                "spec_tokens": self.spec_tokens
                if self.speculative else None,
                "max_queue": self.scheduler.max_queue,
                "num_priorities": self.scheduler.num_priorities,
                "max_retries": self.max_retries,
                "retry_backoff_s": self.retry_backoff_s,
                "total_pages": self.page_pool.num_pages,
                "steady_state": self._steady,
                "adapter_pool": self.adapter_pool is not None,
                "adapter_slots": self.adapter_pool.slots
                if self.adapter_pool is not None else None,
                "adapter_max_rank": self.adapter_pool.max_rank
                if self.adapter_pool is not None else None,
            },
            "admission_capacity": self.admission_capacity_estimate(),
            "robustness": {
                "degraded": self._degraded,
                "draining": self._draining,
                "warmed": self._steady,
                "overload_level": int(s["overload_level"]),
                "policy": None if self.policy is None
                else self.policy.snapshot(),
                "shed": {f"{r}/p{p}": n
                         for (r, p), n in sorted(self._shed_counts.items())},
                "quarantined": int(s["requests_failed"]),
                "dispatch_errors": int(s["dispatch_errors"]),
                "retry_after_s": self.estimated_queue_wait(),
            },
            "scheduler": self.scheduler.snapshot(),
            "tenants": self.tenant_stats(),
            "adapters": self.adapter_pool.snapshot()
            if self.adapter_pool is not None else None,
            "prefix_hit_rate": s["prefix_hits"] / lookups
            if lookups else None,
            "spec_acceptance": s["spec_accepted_tokens"] / drafted
            if drafted else None,
            "stats": s,
        }

    def _flight_probe(self):
        """Watchdog probe (telemetry.flight): progress is the count of
        host-visible scheduling events; busy while work is pending. A
        busy engine whose progress freezes is a stalled dispatch loop."""
        m = self._metrics
        progress = int(m["prefills"].value
                       + m["decode_dispatches"].value
                       + m["requests_finished"].value
                       + m["requests_cancelled"].value
                       + m["requests_failed"].value
                       + m["dispatch_retries"].value
                       + sum(self._shed_counts.values()))
        return progress, self.scheduler.has_work

    # -- device-cost accounting --------------------------------------------
    def mark_warm(self):
        """Declare warmup over: every program this engine should ever
        need is compiled. Any compile after this point is steady-state
        shape churn — the compile still succeeds, but the event is
        flagged and an armed flight recorder latches a
        `retrace_storm:<program>` dump naming the offending key."""
        self._steady = True

    def _steady_probe(self):
        return self._steady

    def _program(self, name):
        """Program-signature key for telemetry.cost: engine-scoped so
        two engines with different model configs never share (and so
        poison) one cost record."""
        return f"engine{self._eid}/{name}"

    def _wrap_program(self, fn, name, cost_scale=1.0):
        return _cost.CostedFunction(fn, self._program(name),
                                    steady_fn=self._steady_probe,
                                    cost_scale=cost_scale)

    def _account_flops(self, program, wall, wasted_fraction=0.0):
        """Per-dispatch device-cost bookkeeping: attribute the wall to
        the program (live MFU/bandwidth gauges) and advance this
        engine's goodput counters from the program's registered FLOPs."""
        rec = _cost.note_dispatch(program, wall)
        if rec is None or not rec.flops:
            return
        m = self._metrics
        m["model_flops"].inc(rec.flops)
        if wasted_fraction > 0.0:
            m["wasted_flops"].inc(rec.flops * wasted_fraction)
        tokens = m["tokens_emitted"].value
        if tokens:
            m["flops_per_token"].set(m["model_flops"].value / tokens)

    def _hbm_ledger(self):
        """telemetry.ledger provider: where this engine's HBM goes.
        Weights are shared arrays (the ledger dedupes them across
        engines); the prefix-cache figure is a Detail — those pages
        live inside the kv_pages slab already counted above."""
        out = {
            "weights": [p.data() for p in self._params],
            "kv_pages": [self._kp, self._vp],
            "slot_state": list(self._dstate) + [self._d_lock],
        }
        pool = self.adapter_pool
        if pool is not None:
            out["adapter_slab"] = [pool.A, pool.B, pool.scale]
        # gluon-initialized params usually carry gradient buffers even
        # when only serving — account them so /memz reconciles
        grads = [g for g in (getattr(p._data, "_grad", None)
                             for p in self._params if p._data is not None)
                 if g is not None]
        if grads:
            out["weight_grads"] = grads
        pc = self.prefix_cache
        if pc is not None:
            per_page = (int(self._kp.nbytes) + int(self._vp.nbytes)) \
                // self.page_pool.num_pages
            out["prefix_cache_pages"] = _ledger.Detail(
                pc.num_pages * per_page)
        return out

    # -- admission control -------------------------------------------------
    def _drain_rate(self):
        """Recent finishes per second (None until two finishes land in
        the window) — the denominator of every retry-after estimate."""
        ft = self._finish_times
        if len(ft) < 2:
            return None
        dt = ft[-1] - ft[0]
        if dt <= 0:
            return None
        return (len(ft) - 1) / dt

    def estimated_queue_wait(self):
        """Seconds until the current backlog would drain at the recent
        finish rate — the retry-after estimate rejections carry and the
        deadline-feasibility signal the shedding policy uses. None when
        the engine has no recent drain history."""
        rate = self._drain_rate()
        if rate is None:
            return None
        return self.scheduler.num_queued / rate

    def estimated_drain_wait(self):
        """Seconds until EVERYTHING in flight (queued + active) would
        complete at the recent finish rate — the retry-after estimate a
        draining replica attaches to its rejections (retrying sooner
        than the drain completes cannot succeed)."""
        rate = self._drain_rate()
        if rate is None:
            return None
        return (self.scheduler.num_queued
                + self.scheduler.num_active) / rate

    def _reject(self, request, reason, cause=None):
        """Common rejection tail: count, record the terminal timeline
        with structured context, and raise (the scheduler's
        QueueFullError enriched in place, or a fresh ShedError)."""
        depth = self.scheduler.num_queued
        active = self.scheduler.num_active
        wait = self.estimated_drain_wait() if self._draining \
            else self.estimated_queue_wait()
        if wait is not None:
            self._metrics["retry_after"].set(wait)
        request.status = "shed"
        self._metrics["requests_rejected"].inc()
        self._shed_inc(reason, request.priority, request.tenant)
        telemetry.request_log.terminal(
            request.id, self._eid, "rejected", reason=reason,
            priority=request.priority, prompt_len=request.prompt_len,
            queue_depth=depth, active_slots=active,
            retry_after_s=None if wait is None else round(wait, 4))
        suffix = (f" [queue_depth={depth}, active_slots={active}"
                  + (f", retry_after~{wait:.3f}s" if wait is not None
                     else "") + "]")
        if cause is not None:
            telemetry.flight.note_queue_full(f"engine{self._eid}")
            cause.queue_depth = depth
            cause.active_slots = active
            cause.retry_after_s = wait
            cause.args = (str(cause.args[0]) + suffix,)
            raise cause
        telemetry.flight.note_shed(f"engine{self._eid}")
        raise ShedError(
            f"request {request.id} shed ({reason})" + suffix,
            reason=reason, queue_depth=depth, active_slots=active,
            retry_after_s=wait, priority=request.priority)

    # -- drain / readiness (serving/router.py consumes these) --------------
    @property
    def draining(self):
        return self._draining

    @property
    def drained(self):
        """True once a drain() completed: admission closed AND no
        queued or running work remains (slots and pages all released —
        audit_pages() is clean here by construction)."""
        return self._draining and not self.scheduler.has_work

    @property
    def warmed(self):
        """True after mark_warm(): every program is compiled."""
        return self._steady

    def is_ready(self):
        """Readiness for new traffic: warmed AND not degraded AND not
        draining — the /readyz conjunction. Liveness is separate: a
        not-ready engine still serves its in-flight work."""
        return self._steady and not self._degraded \
            and not self._draining

    def _ready_probe(self):
        return {"warmed": self._steady, "degraded": self._degraded,
                "draining": self._draining}

    def drain(self):
        """Begin a rolling-restart drain: new submit() rejects with
        ShedError(reason="draining", retry_after_s=<drain estimate>),
        while queued and running requests keep being served by step()
        until the engine is empty (`drained` flips True, page audit
        clean). Rejoin the fleet with undrain(); readiness also needs
        mark_warm() (a restarted replica recompiles). Idempotent."""
        if self._draining:
            return
        self._draining = True
        telemetry.flight.record("draining", engine=self._eid)

    def undrain(self):
        """Reopen admission after a drain (no-op when not draining)."""
        if not self._draining:
            return
        self._draining = False
        telemetry.flight.record("undrained", engine=self._eid)

    # -- public API --------------------------------------------------------
    def submit(self, request):
        """Queue a Request (validated against this engine's capacity).
        Rejections — over-long prompt, full admission queue, policy
        shed — count into serving_requests_rejected_total (sheds also
        into serving_shed_total{reason,priority}) AND record a terminal
        `rejected` timeline with queue depth / active slots / a
        retry-after estimate, so /requests shows rejected traffic too,
        then raise."""
        if request.prompt_len > self.max_length:
            self._metrics["requests_rejected"].inc()
            telemetry.request_log.terminal(
                request.id, self._eid, "rejected",
                reason="prompt_too_long",
                prompt_len=request.prompt_len)
            raise MXNetError(
                f"prompt of {request.prompt_len} tokens exceeds slot "
                f"capacity {self.max_length}")
        if request.adapter_id not in (None, 0):
            pool = self.adapter_pool
            if pool is None or not pool.has(request.adapter_id):
                self._metrics["requests_rejected"].inc()
                telemetry.request_log.terminal(
                    request.id, self._eid, "rejected",
                    reason="unknown_adapter",
                    adapter_id=str(request.adapter_id))
                raise MXNetError(
                    f"adapter {request.adapter_id!r} is not registered "
                    + ("(engine has no adapter pool)" if pool is None
                       else "with this engine's adapter pool"))
        if self._draining:
            self._reject(request, "draining")
        now = self._clock()
        request.t_submit = now
        request.t_deadline = None if request.deadline_ms is None \
            else now + request.deadline_ms / 1e3
        request.output_tokens = []
        request.token_times = []
        request.dispatch_failures = 0
        request.t_not_before = 0.0
        if self.policy is not None:
            action, reason = self.policy.on_submit(self, request, now)
            if action == "shed":
                self._reject(request, reason)
        try:
            out = self.scheduler.submit(request)
        except QueueFullError as e:
            self._reject(request,
                         "tenant_quota" if isinstance(e, TenantQuotaError)
                         else "queue_full", cause=e)
        request.status = "queued"
        telemetry.request_log.begin(
            request.id, self._eid, prompt_len=request.prompt_len,
            max_new_tokens=request.max_new_tokens,
            priority=request.priority,
            deadline_ms=request.deadline_ms)
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        return out

    def cancel(self, request_id):
        """Abort a request by id, queued OR running. A queued request is
        simply dequeued; a running one releases its slot and its page
        leases immediately (tokens already emitted stay on the Request).
        Returns the cancelled Request, or None when the id is unknown
        (already finished, never submitted). Call from the serving
        thread — cancellation mutates slot state between dispatches."""
        req = self.scheduler.cancel_queued(request_id)
        if req is None:
            slot = self.scheduler.slot_of(request_id)
            if slot is None:
                return None
            req = self._release_slot(slot)
        req.t_finish = self._clock()
        req.status = "cancelled"
        self._metrics["requests_cancelled"].inc()
        telemetry.request_log.end(
            request_id, self._eid, "cancelled",
            tokens=len(req.output_tokens))
        self._set_load_gauges()
        self._set_pool_gauges()
        return req

    # -- migration seams (serving/router.py failover + drain) --------------
    def adopt(self, request, migrated_from=None):
        """Queue a request EXPORTED from another replica, preserving
        its emitted tokens: admission re-prefills prompt+emitted and
        resumes the RNG counter at the next token index (the same
        restart continuation a rolled-back request uses), so a migrated
        output is bit-identical to an unfaulted run on the original
        replica. Unlike submit(), class queue bounds do not apply —
        the fleet already accepted this request — and t_submit /
        t_deadline carry over (router and replicas share one clock
        domain). Raises while draining; rejects oversized sequences."""
        if self._draining:
            self._reject(request, "draining")
        total = request.prompt_len + len(request.output_tokens)
        if total > self.max_length:
            self._metrics["requests_rejected"].inc()
            raise MXNetError(
                f"sequence of {total} tokens (prompt + emitted) exceeds "
                f"slot capacity {self.max_length}")
        now = self._clock()
        if request.t_submit is None:
            request.t_submit = now
        request.priority = min(max(int(request.priority), 0),
                               self.scheduler.num_priorities - 1)
        if request._seq is None:
            request._seq = next(_seq_counter)
        request.dispatch_failures = 0
        request.t_not_before = 0.0
        self.scheduler.requeue(request)
        request.status = "queued"
        telemetry.request_log.begin(
            request.id, self._eid, prompt_len=request.prompt_len,
            max_new_tokens=request.max_new_tokens,
            priority=request.priority,
            deadline_ms=request.deadline_ms,
            migrated_from=migrated_from,
            resumed_tokens=len(request.output_tokens))
        self._metrics["queue_depth"].set(self.scheduler.num_queued)
        return request

    def export_requests(self):
        """Remove and return EVERY queued and in-flight request
        (original submit order), releasing slots and page leases. The
        emitted tokens stay on each Request, so a survivor replica can
        adopt() them and continue bit-identically. Device syncs are
        best-effort — the caller may be abandoning a wedged replica,
        whose device state no longer matters; host-side lease
        accounting is always rolled back."""
        out = list(self.scheduler.queued_requests())
        for q in self.scheduler._queues:
            q.clear()
        for slot in list(self.scheduler.active_slots):
            req = self.scheduler.request_at(slot)
            try:
                self._release_slot(slot)
            except Exception:       # noqa: BLE001 — wedged replica
                try:
                    self.scheduler.release(slot)
                except Exception:   # noqa: BLE001
                    pass
                self._free_slot_pages(slot)
                try:
                    self._release_adapter(slot)
                except Exception:   # noqa: BLE001
                    pass
            out.append(req)
        out.sort(key=lambda r: r._seq if r._seq is not None else -1)
        for req in out:
            req.status = "exported"
            telemetry.request_log.end(
                req.id, self._eid, "migrated",
                tokens=len(req.output_tokens))
        self._set_load_gauges()
        self._set_pool_gauges()
        return out

    @property
    def has_work(self):
        return self.scheduler.has_work

    def step(self):
        """One SUPERVISED scheduling round: shed queued work past its
        deadline, cancel running work past its deadline, admit free
        slots (prefill), run one decode dispatch, free finished slots.

        Dispatch exceptions do NOT propagate. The supervisor catches
        them, runs the page-pool invariant audit, latches a
        flight-recorder dump, rolls the implicated slots back (leases
        released, device state parked), re-queues the requests with
        backoff — and quarantines a request whose dispatches failed
        `max_retries` times (terminal reason="error"). Rolled-back
        requests restart by re-prefilling prompt+emitted with their RNG
        counter resumed, so recovered outputs are bit-identical to an
        uninterrupted run.

        Returns every request that reached a TERMINAL state this round:
        finished, deadline-shed/-cancelled, or quarantined."""
        now = self._clock()
        self._fire_hook("step")
        finished = []
        for req in self.scheduler.pop_expired(now):
            finished.append(self._shed_expired(req))
        for slot in list(self.scheduler.active_slots):
            req = self.scheduler.request_at(slot)
            if req.t_deadline is not None and now >= req.t_deadline:
                finished.append(self._deadline_cancel(slot))
        if self.policy is not None:
            self.policy.on_step(self, now)
        for slot, req in self.scheduler.admit(now):
            try:
                fin = self._admit(slot, req)
            except Exception as e:          # noqa: BLE001 — supervisor
                q = self._on_admit_fault(slot, req, e)
                if q is not None:
                    finished.append(q)
                continue
            if fin is not None:
                finished.append(fin)
        self._set_load_gauges()
        if self.scheduler.num_active:
            try:
                finished.extend(self._decode_block())
            except Exception as e:          # noqa: BLE001 — supervisor
                finished.extend(self._on_decode_fault(e))
            self._set_load_gauges()
        return finished

    def serve(self, requests=()):
        """Submit `requests`, run until the queue and all slots drain,
        and return every TERMINAL request (submission order) —
        finished requests plus any shed, deadline-cancelled, or
        quarantined along the way (check `.status`). Rejected
        submissions raise out of submit() and are not returned. Drain
        wall time (last submit -> empty) lands in
        serving_drain_seconds."""
        done = []
        for r in requests:
            try:
                self.submit(r)
            except (QueueFullError, ShedError):
                done.append(r)      # terminal: status == "shed"
        t_drain0 = self._clock()
        with span("serving.drain", engine=self._eid):
            while self.has_work:
                done.extend(self.step())
        self._metrics["drain_seconds"].observe(
            self._clock() - t_drain0)
        done.sort(key=lambda r: r.t_submit)
        return done

    def generate(self, prompts, max_new_tokens, **request_kw):
        """Convenience: serve a list of prompts with shared settings and
        return their generated token lists in order."""
        reqs = [Request(p, max_new_tokens, **request_kw) for p in prompts]
        by_id = {r.id: r for r in reqs}
        self.serve(reqs)
        return [by_id[r.id].output_tokens for r in reqs]

    # -- dispatch hook ------------------------------------------------------
    def _hook_takes_phase(self, hook):
        """Legacy dispatch hooks take (engine) and fire once per step;
        phase-aware hooks accept phase=/requests= keywords (or **kw)
        and fire at every prefill/decode boundary too — the seam the
        fault-injection harness (serving/faults.py) installs into.
        Detected once per hook identity from its signature."""
        cached = self._hook_kw_cache
        if cached is not None and cached[0] is hook:
            return cached[1]
        try:
            params = inspect.signature(hook).parameters
            takes = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                or name in ("phase", "requests")
                for name, p in params.items())
        except (TypeError, ValueError):
            takes = False
        self._hook_kw_cache = (hook, takes)
        return takes

    def _fire_hook(self, phase, requests=()):
        hook = self.dispatch_hook
        if hook is None:
            return
        if self._hook_takes_phase(hook):
            hook(self, phase=phase, requests=tuple(requests))
        elif phase == "step":
            hook(self)

    # -- graceful degradation ----------------------------------------------
    def _set_degraded(self, on, reason="overload"):
        """Latch / clear graceful degradation. While degraded the
        engine suspends speculative decoding (wasted verify FLOPs are
        pure loss when demand exceeds capacity — the plain decode
        program serves until recovery), serving_degraded flips, and
        /healthz reports the engine degraded."""
        on = bool(on)
        if on == self._degraded:
            return
        self._degraded = on
        self._metrics["degraded"].set(int(on))
        name = f"engine{self._eid}"
        if on:
            _tserver.set_degraded(name, reason)
            telemetry.flight.record("degraded", engine=self._eid,
                                    reason=reason)
        else:
            _tserver.clear_degraded(name)
            telemetry.flight.record("recovered", engine=self._eid)

    # -- deadline enforcement ----------------------------------------------
    def _shed_expired(self, req):
        """A queued request whose deadline passed before admission:
        terminal `rejected(deadline)` — no tokens were produced, no
        slot or page was ever touched."""
        req.status = "shed"
        req.t_finish = self._clock()
        self._shed_inc("deadline_queued", req.priority, req.tenant)
        telemetry.request_log.end(
            req.id, self._eid, "rejected", reason="deadline",
            queued=True, tokens=0)
        return req

    def _deadline_cancel(self, slot):
        """A running request past its deadline, cancelled at the
        dispatch boundary: slot and page leases released; the tokens
        already emitted stay on the Request; terminal
        `finished(deadline)`."""
        req = self._release_slot(slot)
        req.status = "deadline"
        self._shed_inc("deadline_running", req.priority, req.tenant)
        telemetry.request_log.end(
            req.id, self._eid, "finished", reason="deadline",
            tokens=len(req.output_tokens))
        self._set_pool_gauges()
        return req

    # -- fault supervision --------------------------------------------------
    def audit_pages(self, raise_on_error=False):
        """Page-pool invariant audit with this engine's full lease map:
        every mapped slot's table row, any extra lease rows registered
        in `audit_extra_leases` (the fault-injection harness registers
        pages it holds), and the prefix cache's member pages. Returns
        the violation list ([] = clean)."""
        leases = [self._table_host[s] for s in range(self.num_slots)
                  if self._mapped[s]]
        leases.extend(self.audit_extra_leases)
        members = ()
        if self.prefix_cache is not None:
            members = np.nonzero(self.prefix_cache.member_mask())[0]
        return self.page_pool.audit(leases=leases, members=members,
                                    raise_on_error=raise_on_error)

    def audit_adapters(self, raise_on_error=False):
        """Adapter-pool invariant audit with this engine's slot
        assignments: every active slot's pinned adapter must be
        resident with a pin count that matches the assignment count
        exactly (a leaked pin would wedge the slab). Returns the
        violation list ([] = clean; also [] without a pool)."""
        if self.adapter_pool is None:
            return []
        assignments = [aid for aid in self._adapter_of if aid is not None]
        return self.adapter_pool.audit(assignments=assignments,
                                       raise_on_error=raise_on_error)

    def _audit_and_latch(self, phase, exc):
        """Post-fault integrity check: run the page-pool AND
        adapter-pool audits while the implicated slots still hold their
        leases/pins (so the maps are complete) and latch a
        flight-recorder dump naming the fault. Returns the violation
        list (normally empty — the fault was caught BEFORE any
        accounting was rolled back)."""
        violations = self.audit_pages() + self.audit_adapters()
        detail = f"{phase}: {type(exc).__name__}: {exc}"
        if violations:
            detail += " | audit: " + "; ".join(violations)
        telemetry.flight.record("dispatch_error", engine=self._eid,
                                phase=phase, error=str(exc)[:200],
                                audit_violations=len(violations))
        telemetry.flight.trigger(
            f"dispatch_error:engine{self._eid}", detail)
        return violations

    def _quarantine(self, req, error):
        """Terminal failure: this request's dispatches failed
        `max_retries` times — it is poison as far as the engine can
        tell. Terminal `failed(error)`; the engine keeps serving
        everyone else."""
        req.status = "failed"
        req.t_finish = self._clock()
        self._metrics["requests_failed"].inc()
        telemetry.request_log.end(
            req.id, self._eid, "failed", reason="error",
            failures=req.dispatch_failures, error=str(error)[:200],
            tokens=len(req.output_tokens))
        telemetry.flight.record("quarantined", engine=self._eid,
                                request=req.id,
                                failures=req.dispatch_failures)
        return req

    def _requeue(self, req, now, blamed, error=""):
        """Roll one request back to the queue after a caught fault.
        A `blamed` request carries the failure: exponential backoff,
        probation (the scheduler re-tries it alone), quarantine at
        max_retries. Innocents re-queue with one flat backoff tick and
        no blame — their emitted tokens ride along and the restart
        continuation keeps their output bit-identical. Returns the
        quarantined Request when the retry budget is spent, else
        None."""
        if blamed:
            req.dispatch_failures += 1
            if req.dispatch_failures >= self.max_retries:
                return self._quarantine(req, error)
            backoff = self.retry_backoff_s * (
                2 ** (req.dispatch_failures - 1))
        else:
            backoff = self.retry_backoff_s
        req.t_not_before = now + backoff
        self._metrics["dispatch_retries"].inc()
        self.scheduler.requeue(req)
        req.status = "queued"
        telemetry.request_log.event(
            req.id, self._eid, "requeued", blamed=blamed,
            failures=req.dispatch_failures, backoff_s=round(backoff, 4))
        return None

    def _on_admit_fault(self, slot, req, exc):
        """Supervise one failed admission: roll the slot fully back
        (scheduler, page leases, parked device state) and re-queue the
        request. Pool exhaustion is BACKPRESSURE — pages will drain, so
        nobody is blamed and no dump is latched; anything else counts
        against the request's retry budget. Returns the quarantined
        Request, or None."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        backpressure = isinstance(exc, (PagePoolExhausted,
                                        AdapterPoolExhausted))
        self.scheduler.release(slot)
        self._free_slot_pages(slot)
        self._release_adapter(slot)
        self._done[slot] = True
        self._remaining[slot] = 0
        self._lengths[slot] = self.max_length
        self._sync_slot(slot)
        if not backpressure:
            self._audit_and_latch("prefill", exc)
        self._set_pool_gauges()
        return self._requeue(req, now, blamed=not backpressure,
                             error=str(exc))

    def _on_decode_fault(self, exc):
        """Supervise a failed decode dispatch: audit while the batch's
        leases are still mapped, then roll every active slot back.
        Blame assignment: when the batch held probationers (requests
        with prior failures) only THEY are blamed — the scheduler
        admits at most one probationer at a time, so repeat faults
        converge on the poison request; a first fault (no history
        anywhere) blames the whole batch, and a later clean dispatch
        resets the innocents' counters. Returns the requests
        quarantined by this fault."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        self._audit_and_latch("decode", exc)
        active = [(slot, self.scheduler.request_at(slot))
                  for slot in self.scheduler.active_slots]
        probationers = {id(r) for _, r in active
                        if r.dispatch_failures > 0}
        blame_all = not probationers
        quarantined = []
        # reversed + appendleft in requeue() restores admission order
        for slot, req in reversed(active):
            self._release_slot(slot)
            q = self._requeue(
                req, now,
                blamed=blame_all or id(req) in probationers,
                error=str(exc))
            if q is not None:
                quarantined.append(q)
        self._set_pool_gauges()
        return quarantined

    def _scrub_slot_pages(self, slot):
        """Zero the KV of the slot's EXCLUSIVE, non-tree pages (the
        only pages a poisoned write can live in) before their leases
        are released — a recycled page must not carry NaN residue into
        the next owner's attention window, whatever the kernel's
        masking does with out-of-range positions."""
        if not self._mapped[slot]:
            return
        ref = self.page_pool.refcounts()
        member = self.prefix_cache.member_mask() \
            if self.prefix_cache is not None else None
        pages = [int(p) for p in self._table_host[slot]
                 if ref[int(p)] == 1
                 and (member is None or not member[int(p)])]
        if not pages:
            return
        idx = jnp.asarray(pages, jnp.int32)
        zero = jnp.zeros((), self._kp.dtype)
        self._kp = self._kp.at[:, idx].set(zero)
        self._vp = self._vp.at[:, idx].set(zero)

    def _on_bad_slots(self, bad, exc_msg):
        """Slots whose dispatch produced non-finite logits (the
        in-program finite guard): this dispatch's tokens for them are
        already discarded by the caller; scrub their exclusive pages,
        roll them back blamed, and latch a dump. Co-batched finite
        slots keep their tokens — their state never mixed with the
        poison. Returns the requests quarantined."""
        now = self._clock()
        self._metrics["dispatch_errors"].inc()
        self._audit_and_latch("decode_nonfinite",
                              MXNetError(exc_msg))
        quarantined = []
        for slot in reversed(bad):
            req = self.scheduler.request_at(slot)
            telemetry.request_log.event(
                req.id, self._eid, "decode_discarded", slot=slot,
                reason="nonfinite_logits")
            self._scrub_slot_pages(slot)
            self._release_slot(slot)
            q = self._requeue(req, now, blamed=True, error=exc_msg)
            if q is not None:
                quarantined.append(q)
        self._set_pool_gauges()
        return quarantined

    # -- device-resident slot state ----------------------------------------
    def _build_slot_upload(self):
        """One jitted scatter that refreshes EVERY device-resident
        per-slot array for one slot in a single dispatch."""
        def upload(state, slot, vals, row):
            *scalars, table = state
            out = tuple(a.at[slot].set(v) for a, v in zip(scalars, vals))
            return out + (table.at[slot].set(row),)
        return jax.jit(upload, donate_argnums=(0,))

    def _sync_slot(self, slot):
        """Upload one slot's host-side scalar state (plus its page-table
        row and the pool's page_lock mask, which change in the same
        events) to the device-resident copies. Called on admission,
        finish and cancel — never per decode dispatch."""
        vals = (self._lengths[slot], self._cur_tok[slot],
                self._done[slot], self._remaining[slot],
                self._counters[slot], self._seeds[slot],
                self._temp[slot], self._top_k[slot], self._top_p[slot],
                self._do_sample[slot], self._eos[slot])
        if self.adapter_pool is not None:
            vals = vals + (self._aslot[slot],)
        self._dstate = self._upload_fn(self._dstate, np.int32(slot),
                                       vals, self._table_host[slot])
        self._d_lock = jnp.asarray(self._page_lock_host())

    def _adapter_args(self, aslot):
        """The extra dispatch operands when the adapter pool is on: the
        slab-slot index array plus the slab itself (read-only — never
        donated, so page-ins and dispatches interleave freely). () when
        the pool is off, keeping the dispatch signature — and the trace
        — byte-identical to a pre-adapter engine."""
        pool = self.adapter_pool
        if pool is None:
            return ()
        if isinstance(aslot, tuple):    # the _dstate tail
            aslot = aslot[0]
        return (aslot, pool.A, pool.B, pool.scale)

    # -- pages -------------------------------------------------------------
    def _page_lock_host(self):
        """(total_pages,) bool for the decode program: True = this page
        must not be written (shared, cached, or free). Decode writes are
        only legal in pages the writing slot holds EXCLUSIVELY."""
        lock = self.page_pool.refcounts() != 1
        if self.prefix_cache is not None:
            lock |= self.prefix_cache.member_mask()
        return lock

    def _map_slot_pages(self, slot, tokens):
        """Page-table surgery for an admission (`tokens` = the ids the
        slot must hold: the prompt, plus already-emitted tokens when a
        rolled-back request restarts): longest-prefix match, CoW split
        when the whole sequence is cached, exclusive allocation for the
        rest. Returns the prefix offset (tokens NOT recomputed; prefill
        starts there). On an allocation failure every lease taken by
        the match is released before the exception propagates — a
        faulted admission must not leak refcounts."""
        S, P = self.page_size, self._pages_per_slot
        Tp = int(tokens.size)
        pc = self.prefix_cache
        matched = pc.match(tokens) if pc is not None else []
        leased = list(matched)         # every lease match() took
        cow_src = None
        if matched and len(matched) * S >= Tp:
            # Fully cached sequence (page-aligned): the last token must
            # still run through the model for its logits, and that
            # rewrites the KV at position Tp-1 — INSIDE the last cached
            # page. Copy-on-write: re-home that page to an exclusive
            # copy; the other matched pages stay shared.
            cow_src = matched.pop()
        n_shared = len(matched)
        need = P - n_shared
        try:
            if pc is not None and self.page_pool.num_free < need:
                pc.reclaim(need)       # LRU-evict idle cached prefixes
            fresh = self.page_pool.alloc(need)
        except Exception:
            if pc is not None and leased:
                pc.release(leased)
            raise
        if cow_src is not None:
            dst = fresh[0]             # lands at row index n_shared
            self._kp, self._vp = self._copy_page_fn(
                self._kp, self._vp, jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(dst, jnp.int32))
            pc.release([cow_src])      # drop our lease on the source
            offset = Tp - 1
        else:
            offset = n_shared * S
        self._table_host[slot] = np.asarray(matched + fresh, np.int32)
        self._mapped[slot] = True
        return offset

    def _free_slot_pages(self, slot):
        if not self._mapped[slot]:
            return
        row = [int(p) for p in self._table_host[slot]]
        if self.prefix_cache is not None:
            self.prefix_cache.release(row)
        else:
            self.page_pool.free(self.page_pool.decref(row))
        self._mapped[slot] = False

    # -- prefill -----------------------------------------------------------
    def _bucket(self, n, offset=0):
        if n == 1:
            return 1     # CoW / one-token suffixes get their own program
        b = self.prefill_bucket
        return min(((n + b - 1) // b) * b, self.max_length - offset)

    def _build_prefill(self, t_bucket):
        model, params = self.model, self._params

        def prefill(param_arrays, kp, vp, ids, row, offset, true_len,
                    counter0, seed, temp, top_k, top_p, do_sample, eos,
                    *adapter):
            # `adapter` is () (pool disabled: the trace is byte-identical
            # to the pre-adapter program) or (aslot, A, B, scale): the
            # slot's slab index is traced DATA — any adapter mix reuses
            # this one program
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            prev_ctx = None
            if adapter:
                aslot, a_A, a_B, a_scale = adapter
                prev_ctx = _set_adapter_ctx(
                    (a_A, a_B, a_scale, aslot[None]))
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                # the slot's FULL table row: attention reads the cached
                # prefix pages and the freshly written suffix through
                # one gather; length=offset puts the suffix writes (and
                # positions) right after the prefix
                cache = PagedKVCache(kp, vp, row[None, :], offset,
                                     attn_impl=self.attn_impl)
                logits, cache = model.forward(NDArray(ids), cache)
            finally:
                if adapter:
                    _set_adapter_ctx(prev_ctx)
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            last = jnp.take(logits._data[0], true_len - 1, axis=0)
            # the RNG stream is keyed (seed, token_index): counter0 is
            # the index of the token this prefill samples — 0 for a
            # fresh admission, len(output_tokens) for a rolled-back
            # request restarting mid-generation (bit-identical resume)
            key = slot_keys(seed[None], counter0[None])
            first = sample_tokens(last[None], key, do_sample[None],
                                  temp[None], top_k[None], top_p[None])[0]
            done0 = (first == eos) & (eos >= 0)
            return cache.k_pages, cache.v_pages, first, done0

        return jax.jit(prefill, donate_argnums=(1, 2))

    def _admit(self, slot, req):
        # restart continuation: a request rolled back after a caught
        # fault already emitted `base` tokens — re-prefill the prompt
        # PLUS those tokens and resume the RNG stream at token index
        # `base`, making the recovered output bit-identical to an
        # uninterrupted run (streams are keyed (seed, token_index))
        base = len(req.output_tokens)
        tokens = req.prompt if not base else np.concatenate(
            [req.prompt, np.asarray(req.output_tokens, np.int32)])
        Tp = int(tokens.size)
        telemetry.request_log.event(req.id, self._eid, "admitted",
                                    slot=slot)
        if base:
            telemetry.request_log.event(
                req.id, self._eid, "resumed", tokens=base)
        self._fire_hook("prefill", (req,))
        if self.adapter_pool is not None:
            # pin BEFORE the page map: either acquire can raise
            # (AdapterPoolExhausted is backpressure, like
            # PagePoolExhausted) and _on_admit_fault rolls back
            # whatever was taken
            aslot = self.adapter_pool.acquire(req.adapter_id)
            self._adapter_of[slot] = req.adapter_id \
                if req.adapter_id not in (None, 0) else None
            self._aslot[slot] = aslot
        offset = self._map_slot_pages(slot, tokens)
        req.status = "running"
        if req.tenant is not None:
            self._tenant_child("admitted", req.tenant).inc()
        if self.prefix_cache is not None:
            telemetry.request_log.event(
                req.id, self._eid, "prefix_match", cached_tokens=offset)
        suffix = Tp - offset
        Tb = self._bucket(suffix, offset)
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :suffix] = tokens[offset:]
        fn = self._prefill_programs.get(Tb)
        if fn is None:
            fn = self._wrap_program(self._build_prefill(Tb),
                                    f"prefill/{Tb}")
            self._prefill_programs[Tb] = fn
        param_datas = tuple(p.data()._data for p in self._params)
        i32 = lambda v: jnp.asarray(v, jnp.int32)  # noqa: E731
        t0 = self._clock()
        with span("serving.prefill", engine=self._eid, bucket=Tb,
                  cached_tokens=offset):
            kp, vp, first, done0 = fn(
                param_datas, self._kp, self._vp, jnp.asarray(ids),
                jnp.asarray(self._table_host[slot]), i32(offset),
                i32(suffix), i32(base), i32(req.seed),
                jnp.asarray(req.temperature, jnp.float32),
                i32(req.top_k), jnp.asarray(req.top_p, jnp.float32),
                jnp.asarray(req.do_sample), i32(
                    -1 if req.eos_token_id is None
                    else req.eos_token_id),
                *self._adapter_args(i32(self._aslot[slot])))
            self._kp, self._vp = kp, vp
            first = int(first)      # host sync: the prefill is done here
        now = self._clock()
        req.output_tokens.append(first)
        req.token_times.append(now)
        telemetry.request_log.event(
            req.id, self._eid, "prefill", dur=now - t0, bucket=Tb,
            suffix_tokens=suffix, first_token=first)
        m = self._metrics
        m["prefills"].inc()
        m["prefill_tokens"].inc(suffix)
        m["tokens_emitted"].inc()
        if not base:
            # latency SLO metrics describe the FIRST admission only —
            # a restart's wait is retry bookkeeping, not user TTFT
            req.t_admit = now
            m["admission_wait"].observe(t0 - req.t_submit)
            m["ttft"].observe(now - req.t_submit)
        m["prefill_seconds"].observe(now - t0)
        self._account_flops(fn.program, now - t0)
        pc = self.prefix_cache
        if pc is not None:
            if offset:
                m["prefix_hits"].inc()
                m["prefix_tokens_saved"].inc(offset)
            else:
                m["prefix_misses"].inc()
            # adopt the PROMPT's full pages into the radix tree: the
            # next request sharing this prefix attaches instead of
            # recomputing (prefill is host-synced above, so the page
            # contents are final). On a restart the prompt still spans
            # the same leading pages of the rebuilt table.
            n_full = req.prompt_len // self.page_size
            if n_full:
                pc.insert(req.prompt,
                          [int(p) for p in self._table_host[slot][:n_full]])
        if pc is not None or self.adapter_pool is not None:
            self._set_pool_gauges()
        # budget: every decode step writes one KV; the last sampled token
        # is never written, so a sequence of Tp supports up to
        # max_length - Tp + 1 further generated tokens; `base` already
        # spent that much of max_new_tokens
        cap = min(req.max_new_tokens - base, self.max_length - Tp + 1)
        self._lengths[slot] = Tp
        self._cur_tok[slot] = first
        self._remaining[slot] = cap - 1
        self._counters[slot] = base + 1
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = bool(done0) or cap <= 1
        if self._done[slot]:
            return self._finish(slot)       # _release_slot syncs
        if self.speculative:
            self._hist[slot] = list(tokens) + [first]
        self._sync_slot(slot)
        return None

    # -- decode ------------------------------------------------------------
    def _decode_fn(self, spec):
        """The decode program for this dispatch: speculative or plain
        (`spec` — a degraded speculative engine dispatches the PLAIN
        program until recovery), greedy-only (no sort/RNG in-program)
        when no active slot samples. All flavors are cached — at most
        two compiles per mode, never per admission."""
        greedy_only = not bool(
            self._do_sample[self.scheduler.active_slots].any())
        key = (spec, greedy_only)
        fn = self._decode_programs.get(key)
        if fn is None:
            variant = "greedy" if greedy_only else "sampled"
            name = f"verify/S{self.spec_tokens}/{variant}" \
                if spec else f"decode/{variant}"
            # the plain decode program scans K steps per dispatch and
            # XLA costs the scan body once — scale to per-dispatch
            fn = self._wrap_program(
                self._build_spec_decode(greedy_only) if spec
                else self._build_decode(greedy_only), name,
                cost_scale=1.0 if spec else float(self.decode_block))
            self._decode_programs[key] = fn
        return fn

    def _build_decode(self, greedy_only=False):
        model, params = self.model, self._params
        K, impl = self.decode_block, self.attn_impl

        def decode(param_arrays, kp, vp, table, lock, lengths, cur_tok,
                   done, remaining, counters, seeds, temp, top_k, top_p,
                   do_sample, eos, *adapter):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            prev_ctx = None
            if adapter:
                aslot, a_A, a_B, a_scale = adapter
                prev_ctx = _set_adapter_ctx((a_A, a_B, a_scale, aslot))
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr

                def body(carry, _):
                    (kp, vp, lengths, cur_tok, done, remaining,
                     counters, okc) = carry
                    active = (~done) & (remaining > 0)
                    cache = PagedKVCache(kp, vp, table, lengths,
                                         page_lock=lock, attn_impl=impl)
                    tok_in = jnp.where(active, cur_tok, 0)
                    logits, cache = model.forward(
                        NDArray(tok_in[:, None]), cache)
                    step_logits = logits._data[:, -1, :]
                    # in-program finite guard: a slot whose logits went
                    # non-finite (corrupted KV, numeric blowup) is
                    # flagged; the host discards its tokens from this
                    # dispatch and re-prefills the request
                    fin = jnp.isfinite(step_logits).all(axis=-1) \
                        | ~active
                    if greedy_only:
                        nxt = jnp.argmax(step_logits,
                                         axis=-1).astype(jnp.int32)
                    else:
                        keys = slot_keys(seeds, counters)
                        nxt = sample_tokens(step_logits, keys,
                                            do_sample, temp, top_k,
                                            top_p)
                    new_len = jnp.where(active, cache.length, lengths)
                    new_rem = jnp.where(active, remaining - 1, remaining)
                    hit_eos = (nxt == eos) & (eos >= 0)
                    new_done = done | (active & (hit_eos
                                                 | (new_rem <= 0)))
                    carry = (cache.k_pages, cache.v_pages, new_len,
                             jnp.where(active, nxt, cur_tok), new_done,
                             new_rem,
                             jnp.where(active, counters + 1, counters),
                             okc & fin)
                    return carry, (jnp.where(active, nxt, -1), active)

                init = (kp, vp, lengths, cur_tok, done, remaining,
                        counters, jnp.ones_like(done))
                final, (toks, valid) = lax.scan(body, init, None,
                                                length=K)
            finally:
                if adapter:
                    _set_adapter_ctx(prev_ctx)
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            return final + (toks, valid)

        return jax.jit(decode, donate_argnums=(1, 2))

    def _decode_block(self):
        if self.speculative and not self._degraded:
            return self._spec_decode_block()
        self._fire_hook("decode",
                        [self.scheduler.request_at(s)
                         for s in self.scheduler.active_slots])
        fn = self._decode_fn(False)
        param_datas = tuple(p.data()._data for p in self._params)
        st = self._dstate
        (lengths, cur_tok, done, remaining, counters, seeds, temp,
         top_k, top_p, do_sample, eos) = st[:11]
        tail, table = st[11:-1], st[-1]   # (aslot,) with the pool on
        t0 = self._clock()
        with span("serving.decode_block", engine=self._eid,
                  active=self.scheduler.num_active):
            out = fn(
                param_datas, self._kp, self._vp, table, self._d_lock,
                lengths, cur_tok, done, remaining, counters, seeds,
                temp, top_k, top_p, do_sample, eos,
                *self._adapter_args(tail))
            (self._kp, self._vp, lengths, cur_tok, done, remaining,
             counters, okc, toks, valid) = out
            self._dstate = (lengths, cur_tok, done, remaining, counters,
                            seeds, temp, top_k, top_p, do_sample,
                            eos) + tail + (table,)
            # ONE host sync per K decoded tokens: everything small fetches
            # together (the pools stay on device, donated through)
            (self._lengths, self._cur_tok, self._done, self._remaining,
             self._counters) = (
                np.array(lengths), np.array(cur_tok), np.array(done),
                np.array(remaining), np.array(counters))
            toks, valid, ok = (np.asarray(toks), np.asarray(valid),
                               np.asarray(okc))
        now = self._clock()
        dt = now - t0
        m = self._metrics
        m["decode_dispatches"].inc()
        m["decode_steps"].inc(self.decode_block)
        m["decode_seconds"].observe(dt)
        rl = telemetry.request_log
        finished = []
        bad = []
        n_emitted = 0
        for slot in self.scheduler.active_slots:
            req = self.scheduler.request_at(slot)
            if not ok[slot]:
                # non-finite logits: every token this dispatch sampled
                # for the slot is garbage — discard them all, roll the
                # request back (handled below, after accounting)
                bad.append(slot)
                continue
            emitted = toks[valid[:, slot], slot]
            req.output_tokens.extend(int(t) for t in emitted)
            req.token_times.extend([now] * emitted.size)
            # a clean dispatch clears the request's failure history —
            # probation is for consecutive faults, not per-lifetime
            req.dispatch_failures = 0
            req.t_not_before = 0.0
            if self.speculative and self._hist[slot] is not None:
                # degraded spec engine decoding plainly: keep the
                # history current so speculation resumes seamlessly
                self._hist[slot].extend(int(t) for t in emitted)
            if rl.enabled:
                rl.event(req.id, self._eid, "decode", dur=dt,
                         tokens=int(emitted.size))
            n_emitted += int(emitted.size)
            # block resolution: a slot that got n of this dispatch's
            # tokens saw dt/n per token — the ACTUAL emitted count, not
            # the nominal K (a slot can finish mid-block, and under
            # speculation K is not the tokens-per-dispatch at all)
            if emitted.size:
                m["token_latency"].observe(dt / emitted.size,
                                           int(emitted.size))
            if self._done[slot] or self._remaining[slot] <= 0:
                finished.append(self._finish(slot))
        m["tokens_emitted"].inc(n_emitted)
        self._account_flops(fn.program, dt)
        if bad:
            finished.extend(self._on_bad_slots(
                bad, "non-finite logits in decode dispatch"))
        return finished

    # -- speculative decode ------------------------------------------------
    def _build_spec_decode(self, greedy_only=False):
        model, params = self.model, self._params
        S, impl = self.spec_tokens, self.attn_impl

        def decode(param_arrays, kp, vp, table, lock, lengths, cur_tok,
                   done, remaining, counters, drafts, n_draft, seeds,
                   temp, top_k, top_p, do_sample, eos, *adapter):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            prev_ctx = None
            if adapter:
                aslot, a_A, a_B, a_scale = adapter
                prev_ctx = _set_adapter_ctx((a_A, a_B, a_scale, aslot))
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                active = (~done) & (remaining > 0)
                nd = jnp.where(active, n_draft, 0)
                cache = PagedKVCache(kp, vp, table, lengths,
                                     page_lock=lock, attn_impl=impl)
                # ONE forward over [current token, drafts]: the model
                # writes all S positions' KV at lengths..lengths+S-1 and
                # the multi-query ragged kernel applies the per-position
                # causal offsets; logits[:, j] is the distribution of
                # the token after prefix..draft_j
                toks_in = jnp.concatenate(
                    [jnp.where(active, cur_tok, 0)[:, None],
                     jnp.where(active[:, None], drafts, 0)], axis=1)
                logits, cache = model.forward(NDArray(toks_in), cache)
                # in-program finite guard (see _build_decode): flag any
                # slot whose verification logits went non-finite
                ok = jnp.isfinite(logits._data).all(axis=(1, 2)) \
                    | ~active
                emitted, n_acc = verify_tokens(
                    logits._data, drafts, nd, seeds, counters,
                    do_sample, temp, top_k, top_p,
                    greedy_only=greedy_only)
                pos = jnp.arange(S)[None, :]
                # emit the accepted drafts + one verifier token, capped
                # by the remaining budget, truncated at the first eos;
                # only the emitted count advances `lengths` — rejected
                # drafts' KV stays behind the length (invisible) and is
                # overwritten in place by the next dispatch
                n_em = jnp.minimum(n_acc + 1, remaining)
                hit = ((emitted == eos[:, None]) & (eos >= 0)[:, None]
                       & (pos < n_em[:, None]))
                any_hit = hit.any(axis=1)
                n_em = jnp.where(
                    any_hit, jnp.minimum(n_em, jnp.argmax(hit, 1) + 1),
                    n_em)
                n_em = jnp.where(active, n_em, 0)
                toks = jnp.where(pos < n_em[:, None], emitted, -1)
                last = jnp.take_along_axis(
                    emitted, jnp.maximum(n_em - 1, 0)[:, None],
                    axis=1)[:, 0]
                new_len = jnp.where(active, lengths + n_em, lengths)
                new_rem = jnp.where(active, remaining - n_em, remaining)
                new_done = done | (active & (any_hit | (new_rem <= 0)))
                new_cur = jnp.where(active, last, cur_tok)
                new_cnt = jnp.where(active, counters + n_em, counters)
                n_acc_em = jnp.minimum(n_acc, n_em)   # drafts EMITTED
            finally:
                if adapter:
                    _set_adapter_ctx(prev_ctx)
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            return (cache.k_pages, cache.v_pages, new_len, new_cur,
                    new_done, new_rem, new_cnt, ok, toks, n_em,
                    n_acc_em)

        return jax.jit(decode, donate_argnums=(1, 2))

    def _spec_decode_block(self):
        self._fire_hook("decode",
                        [self.scheduler.request_at(s)
                         for s in self.scheduler.active_slots])
        fn = self._decode_fn(True)
        B, S = self.num_slots, self.spec_tokens
        drafts = np.zeros((B, S - 1), np.int32)
        n_draft = np.zeros(B, np.int32)
        for slot in self.scheduler.active_slots:
            d = self._proposer.propose(self._hist[slot])
            n_draft[slot] = d.size
            drafts[slot, :d.size] = d
        param_datas = tuple(p.data()._data for p in self._params)
        st = self._dstate
        (lengths, cur_tok, done, remaining, counters, seeds, temp,
         top_k, top_p, do_sample, eos) = st[:11]
        tail, table = st[11:-1], st[-1]   # (aslot,) with the pool on
        t0 = self._clock()
        with span("serving.spec_decode", engine=self._eid,
                  active=self.scheduler.num_active,
                  drafted=int(n_draft.sum())):
            out = fn(
                param_datas, self._kp, self._vp, table, self._d_lock,
                lengths, cur_tok, done, remaining, counters,
                jnp.asarray(drafts), jnp.asarray(n_draft), seeds, temp,
                top_k, top_p, do_sample, eos,
                *self._adapter_args(tail))
            (self._kp, self._vp, lengths, cur_tok, done, remaining,
             counters, okc, toks, n_em, n_acc) = out
            self._dstate = (lengths, cur_tok, done, remaining, counters,
                            seeds, temp, top_k, top_p, do_sample,
                            eos) + tail + (table,)
            (self._lengths, self._cur_tok, self._done, self._remaining,
             self._counters) = (
                np.array(lengths), np.array(cur_tok), np.array(done),
                np.array(remaining), np.array(counters))
            toks, n_em, n_acc, ok = (np.asarray(toks), np.asarray(n_em),
                                     np.asarray(n_acc),
                                     np.asarray(okc))
        now = self._clock()
        dt = now - t0
        m = self._metrics
        m["decode_dispatches"].inc()
        m["decode_steps"].inc()          # one verification forward
        m["decode_seconds"].observe(dt)
        rl = telemetry.request_log
        finished = []
        bad = []
        n_emitted = 0
        accepted = 0
        for slot in self.scheduler.active_slots:
            req = self.scheduler.request_at(slot)
            if not ok[slot]:
                bad.append(slot)
                continue
            n = int(n_em[slot])
            emitted = [int(t) for t in toks[slot, :n]]
            req.output_tokens.extend(emitted)
            req.token_times.extend([now] * n)
            req.dispatch_failures = 0
            req.t_not_before = 0.0
            if rl.enabled:
                rl.event(req.id, self._eid, "verify", dur=dt,
                         drafted=int(n_draft[slot]),
                         accepted=int(n_acc[slot]), tokens=n)
            if self._hist[slot] is not None:
                self._hist[slot].extend(emitted)
            n_emitted += n
            accepted += int(n_acc[slot])
            if n:
                m["token_latency"].observe(dt / n, n)
            if self._done[slot] or self._remaining[slot] <= 0:
                finished.append(self._finish(slot))
        m["tokens_emitted"].inc(n_emitted)
        drafted = int(n_draft.sum())
        m["spec_draft_tokens"].inc(drafted)
        m["spec_accepted_tokens"].inc(accepted)
        m["spec_rollbacks"].inc(drafted - accepted)
        # goodput: the verify program computes B x S query positions a
        # dispatch; the drafted-but-rejected share of them is speculation
        # waste (inactive-slot padding is a separate, structural cost)
        self._account_flops(
            fn.program, dt,
            wasted_fraction=(drafted - accepted) / (B * S))
        if bad:
            finished.extend(self._on_bad_slots(
                bad, "non-finite logits in verification dispatch"))
        return finished

    def _release_slot(self, slot):
        """Free a slot mid-flight or at completion: scheduler slot back
        to the pool, page leases released, in-program writes parked OOB
        (length = max_length) so the recycled pages can't be touched."""
        req = self.scheduler.release(slot)
        req.t_finish = self._clock()
        self._done[slot] = True
        self._remaining[slot] = 0
        self._lengths[slot] = self.max_length
        self._free_slot_pages(slot)
        self._release_adapter(slot)
        if self.speculative:
            self._hist[slot] = None
        self._sync_slot(slot)
        return req

    def _release_adapter(self, slot):
        """Drop the slot's adapter pin (no-op without a pool or for the
        null adapter) and park the slot on slab slot 0 so the next
        _sync_slot uploads a null-adapter row."""
        if self.adapter_pool is None:
            return
        aid = self._adapter_of[slot]
        if aid is not None:
            self.adapter_pool.release(aid)
            self._adapter_of[slot] = None
        self._aslot[slot] = 0

    def _finish(self, slot):
        # read the stop cause BEFORE release zeroes the slot state:
        # budget exhaustion leaves remaining <= 0, eos leaves budget
        reason = "budget" if self._remaining[slot] <= 0 else "eos"
        req = self._release_slot(slot)
        req.status = "finished"
        self._finish_times.append(self._clock())   # drain-rate window
        self._metrics["requests_finished"].inc()
        telemetry.request_log.end(
            req.id, self._eid, "finished", reason=reason,
            tokens=len(req.output_tokens))
        self._set_pool_gauges()
        return req
