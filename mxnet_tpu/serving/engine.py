"""Continuous-batching serving engine.

Execution model (docs/SERVING.md):

  * B fixed decode SLOTS share one PagedKVCache page pool. Each slot has
    its own live length; the decode forward runs all B slots through the
    ragged paged-attention kernel, so per-token HBM traffic is the sum
    of LIVE lengths, not B × max_length.
  * PREFILL is one compiled program per prompt-length bucket: it writes
    the prompt's KV into the slot's pages (batch-1, attention only over
    the bucket) and samples the request's first token.
  * DECODE runs K steps per host dispatch via lax.scan — the
    TrainStep.run_steps pattern applied to serving. PERF_NOTES measured
    ~24 ms/step of host dispatch tax over a remote tunnel; at one
    token per step that tax would dominate decode, so the block size K
    amortizes it K-fold.
  * Between dispatches the host frees finished slots and admits queued
    requests (FIFO) — continuous batching: nobody waits for the slowest
    sequence in a fixed batch.

Everything per-request (sampling knobs, seeds, eos, budgets) is a
per-slot ARRAY in the compiled program, so admission never recompiles;
the only shape-churn axis is the prefill bucket, and those programs live
in a bounded LRU (gluon.block.LRUTraceCache).
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..gluon.block import LRUTraceCache, _trace_channel
from ..models.kv_cache import PagedKVCache
from ..ndarray.ndarray import NDArray
from .sampling import sample_tokens, slot_keys
from .scheduler import Request, SlotScheduler

__all__ = ["ServingEngine"]


class ServingEngine:
    """Continuous-batching generation over a model with the GPT-2 cache
    contract (forward(ids, cache) -> (logits, cache), make_cache()).

    num_slots: concurrent decode sequences (the compiled batch).
    max_length: per-slot KV capacity (prompt + generated), rounded down
        to a whole number of pages; defaults to the model's max_length.
    page_size: KV page granularity. decode_block: decode steps fused
    into one dispatch. attn_impl: 'auto' (ragged Pallas kernel on TPU,
    dense XLA elsewhere), 'pallas', 'pallas_interpret' (the kernel in
    interpret mode — CPU tests), or 'xla'.
    """

    def __init__(self, model, num_slots, max_length=None, page_size=64,
                 decode_block=8, attn_impl="auto", prefill_bucket=None,
                 dtype=None):
        self.model = model
        cfg = model.config
        self.num_slots = int(num_slots)
        max_length = int(max_length or cfg.max_length)
        max_length -= max_length % page_size
        if max_length < page_size:
            raise MXNetError(f"max_length {max_length} < one page "
                             f"({page_size})")
        if max_length > cfg.max_length:
            raise MXNetError(f"max_length {max_length} exceeds the "
                             f"model's position range {cfg.max_length}")
        self.max_length = max_length
        self.page_size = int(page_size)
        self.decode_block = int(decode_block)
        if self.decode_block < 1:
            raise MXNetError("decode_block must be >= 1")
        self.attn_impl = attn_impl
        self.prefill_bucket = int(prefill_bucket or page_size)
        self.scheduler = SlotScheduler(num_slots)

        self._params = list(model.collect_params().values())
        B = self.num_slots
        P = max_length // page_size
        dt = dtype or jnp.dtype(cfg.dtype)
        pool_shape = (cfg.num_layers, B * P, page_size, cfg.num_heads,
                      cfg.units // cfg.num_heads)
        self._kp = jnp.zeros(pool_shape, dt)
        self._vp = jnp.zeros(pool_shape, dt)
        self._table = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)
        # per-slot host state (tiny; uploaded per dispatch, fetched back
        # with the decoded tokens — one round trip per K tokens)
        self._lengths = np.zeros(B, np.int32)
        self._cur_tok = np.zeros(B, np.int32)
        self._done = np.ones(B, bool)          # free slots are inactive
        self._remaining = np.zeros(B, np.int32)
        self._counters = np.zeros(B, np.int32)
        self._seeds = np.zeros(B, np.int32)
        self._temp = np.ones(B, np.float32)
        self._top_k = np.zeros(B, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._eos = np.full(B, -1, np.int32)

        self._prefill_programs = LRUTraceCache(
            max(2 * (max_length // self.prefill_bucket), 8))
        self._decode_program = None
        self.stats = {"prefills": 0, "decode_dispatches": 0,
                      "decode_steps": 0, "tokens_emitted": 0,
                      "requests_finished": 0}

    # -- public API --------------------------------------------------------
    def submit(self, request):
        """Queue a Request (validated against this engine's capacity)."""
        if request.prompt_len > self.max_length:
            raise MXNetError(
                f"prompt of {request.prompt_len} tokens exceeds slot "
                f"capacity {self.max_length}")
        request.t_submit = time.perf_counter()
        request.output_tokens = []
        request.token_times = []
        return self.scheduler.submit(request)

    @property
    def has_work(self):
        return self.scheduler.has_work

    def step(self):
        """One scheduling round: admit free slots (prefill), run one
        K-step decode block, free finished slots. Returns the requests
        that finished this round."""
        finished = []
        for slot, req in self.scheduler.admit():
            fin = self._admit(slot, req)
            if fin is not None:
                finished.append(fin)
        if self.scheduler.num_active:
            finished.extend(self._decode_block())
        return finished

    def serve(self, requests=()):
        """Submit `requests`, run until the queue and all slots drain,
        and return every finished request (submission order)."""
        for r in requests:
            self.submit(r)
        done = []
        while self.has_work:
            done.extend(self.step())
        done.sort(key=lambda r: r.t_submit)
        return done

    def generate(self, prompts, max_new_tokens, **request_kw):
        """Convenience: serve a list of prompts with shared settings and
        return their generated token lists in order."""
        reqs = [Request(p, max_new_tokens, **request_kw) for p in prompts]
        by_id = {r.id: r for r in reqs}
        self.serve(reqs)
        return [by_id[r.id].output_tokens for r in reqs]

    # -- prefill -----------------------------------------------------------
    def _bucket(self, n):
        b = self.prefill_bucket
        return min(((n + b - 1) // b) * b, self.max_length)

    def _build_prefill(self, t_bucket):
        model, params = self.model, self._params
        table = self._table
        n_pages = t_bucket // self.page_size

        def prefill(param_arrays, kp, vp, ids, slot, true_len, seed,
                    temp, top_k, top_p, do_sample, eos):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr
                row = jnp.take(table, slot, axis=0)       # (P,)
                cache = PagedKVCache(kp, vp, row[None, :n_pages],
                                     jnp.zeros((), jnp.int32),
                                     attn_impl=self.attn_impl)
                logits, cache = model.forward(NDArray(ids), cache)
            finally:
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            last = jnp.take(logits._data[0], true_len - 1, axis=0)
            key = slot_keys(seed[None], jnp.zeros((1,), jnp.int32))
            first = sample_tokens(last[None], key, do_sample[None],
                                  temp[None], top_k[None], top_p[None])[0]
            done0 = (first == eos) & (eos >= 0)
            return cache.k_pages, cache.v_pages, first, done0

        return jax.jit(prefill, donate_argnums=(1, 2))

    def _admit(self, slot, req):
        Tp = req.prompt_len
        Tb = self._bucket(Tp)
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :Tp] = req.prompt
        fn = self._prefill_programs.get(Tb)
        if fn is None:
            fn = self._build_prefill(Tb)
            self._prefill_programs[Tb] = fn
        param_datas = tuple(p.data()._data for p in self._params)
        i32 = lambda v: jnp.asarray(v, jnp.int32)  # noqa: E731
        kp, vp, first, done0 = fn(
            param_datas, self._kp, self._vp, jnp.asarray(ids), i32(slot),
            i32(Tp), i32(req.seed), jnp.asarray(req.temperature,
                                                jnp.float32),
            i32(req.top_k), jnp.asarray(req.top_p, jnp.float32),
            jnp.asarray(req.do_sample), i32(
                -1 if req.eos_token_id is None else req.eos_token_id))
        self._kp, self._vp = kp, vp
        first = int(first)
        now = time.perf_counter()
        req.t_admit = now
        req.output_tokens.append(first)
        req.token_times.append(now)
        self.stats["prefills"] += 1
        self.stats["tokens_emitted"] += 1
        # budget: every decode step writes one KV; the last sampled token
        # is never written, so a prompt of Tp supports up to
        # max_length - Tp + 1 generated tokens
        cap = min(req.max_new_tokens, self.max_length - Tp + 1)
        self._lengths[slot] = Tp
        self._cur_tok[slot] = first
        self._remaining[slot] = cap - 1
        self._counters[slot] = 1
        self._seeds[slot] = req.seed
        self._temp[slot] = req.temperature
        self._top_k[slot] = req.top_k
        self._top_p[slot] = req.top_p
        self._do_sample[slot] = req.do_sample
        self._eos[slot] = -1 if req.eos_token_id is None \
            else req.eos_token_id
        self._done[slot] = bool(done0) or cap <= 1
        if self._done[slot]:
            return self._finish(slot)
        return None

    # -- decode ------------------------------------------------------------
    def _build_decode(self):
        model, params = self.model, self._params
        table, K = self._table, self.decode_block
        impl = self.attn_impl

        def decode(param_arrays, kp, vp, lengths, cur_tok, done,
                   remaining, counters, seeds, temp, top_k, top_p,
                   do_sample, eos):
            saved = [p._data for p in params]
            _trace_channel.push_frame()
            try:
                for p, d in zip(params, param_arrays):
                    arr = NDArray(d)
                    arr._grad_req = "null"
                    p._data = arr

                def body(carry, _):
                    (kp, vp, lengths, cur_tok, done, remaining,
                     counters) = carry
                    active = (~done) & (remaining > 0)
                    cache = PagedKVCache(kp, vp, table, lengths,
                                         attn_impl=impl)
                    tok_in = jnp.where(active, cur_tok, 0)
                    logits, cache = model.forward(
                        NDArray(tok_in[:, None]), cache)
                    keys = slot_keys(seeds, counters)
                    nxt = sample_tokens(logits._data[:, -1, :], keys,
                                        do_sample, temp, top_k, top_p)
                    new_len = jnp.where(active, cache.length, lengths)
                    new_rem = jnp.where(active, remaining - 1, remaining)
                    hit_eos = (nxt == eos) & (eos >= 0)
                    new_done = done | (active & (hit_eos
                                                 | (new_rem <= 0)))
                    carry = (cache.k_pages, cache.v_pages, new_len,
                             jnp.where(active, nxt, cur_tok), new_done,
                             new_rem,
                             jnp.where(active, counters + 1, counters))
                    return carry, (jnp.where(active, nxt, -1), active)

                init = (kp, vp, lengths, cur_tok, done, remaining,
                        counters)
                final, (toks, valid) = lax.scan(body, init, None,
                                                length=K)
            finally:
                _trace_channel.pop_frame()
                for p, d in zip(params, saved):
                    p._data = d
            return final + (toks, valid)

        return jax.jit(decode, donate_argnums=(1, 2))

    def _decode_block(self):
        if self._decode_program is None:
            self._decode_program = self._build_decode()
        param_datas = tuple(p.data()._data for p in self._params)
        out = self._decode_program(
            param_datas, self._kp, self._vp, jnp.asarray(self._lengths),
            jnp.asarray(self._cur_tok), jnp.asarray(self._done),
            jnp.asarray(self._remaining), jnp.asarray(self._counters),
            jnp.asarray(self._seeds), jnp.asarray(self._temp),
            jnp.asarray(self._top_k), jnp.asarray(self._top_p),
            jnp.asarray(self._do_sample), jnp.asarray(self._eos))
        (self._kp, self._vp, lengths, cur_tok, done, remaining, counters,
         toks, valid) = out
        # ONE host sync per K decoded tokens: everything small fetches
        # together (the pools stay on device, donated through)
        (self._lengths, self._cur_tok, self._done, self._remaining,
         self._counters) = (
            np.array(lengths), np.array(cur_tok), np.array(done),
            np.array(remaining), np.array(counters))
        toks, valid = np.asarray(toks), np.asarray(valid)
        now = time.perf_counter()
        self.stats["decode_dispatches"] += 1
        self.stats["decode_steps"] += self.decode_block
        finished = []
        for slot in self.scheduler.active_slots:
            req = self.scheduler.request_at(slot)
            emitted = toks[valid[:, slot], slot]
            req.output_tokens.extend(int(t) for t in emitted)
            req.token_times.extend([now] * emitted.size)
            self.stats["tokens_emitted"] += int(emitted.size)
            if self._done[slot] or self._remaining[slot] <= 0:
                finished.append(self._finish(slot))
        return finished

    def _finish(self, slot):
        req = self.scheduler.release(slot)
        req.t_finish = time.perf_counter()
        # freed slots stay inactive (and write nothing) until re-admitted
        self._done[slot] = True
        self._remaining[slot] = 0
        self.stats["requests_finished"] += 1
        return req
