"""Seeded fault-injection harness for the serving engine.

A `FaultPlan` is a deterministic, seed-driven schedule of faults
injected through the engine's existing `dispatch_hook` seam (the hook
fires at the top of every step and immediately before every prefill
and decode dispatch, with the requests about to be dispatched). The
chaos soak tests (tests/test_robustness.py) and the overload bench
drive the supervisor with it; nothing here runs in production paths.

Fault kinds (each an independent per-dispatch probability under one
`numpy` Generator, so a given seed + workload replays the same plan):

  * dispatch_exception — raise `FaultError` at a prefill/decode
    boundary: the supervisor must roll the batch back, requeue the
    innocents, and keep serving.
  * slow_dispatch      — sleep `slow_s` before the dispatch: exercises
    deadline cancellation and the flight-recorder stall watchdog
    without breaking anything.
  * nan_logits         — corrupt one victim slot's KV: a page the slot
    holds EXCLUSIVELY (never a shared/radix-tree page — the injected
    poison must not outlive the victim through the prefix cache) is
    filled with NaN, so the next forward produces non-finite logits
    for that slot and the engine's in-program finite guard must catch
    it, discard the dispatch's tokens for the slot, and re-prefill.
  * pool_exhaustion    — allocate (up to) all free pages and hold them
    for `exhaust_steps` steps: admissions fail with PagePoolExhausted
    and must retry without blaming the request.
  * alloc_failure      — arm the pool so its next alloc() raises: the
    transient-allocator-failure path, including the lease rollback in
    `_map_slot_pages`.
  * poison             — request ids whose every dispatch (or every
    dispatch of a given phase) raises: the supervisor must quarantine
    them after max_retries and keep every co-batched innocent's output
    bit-identical to a fault-free run.

`install(engine)` claims the engine's dispatch_hook and wraps
`page_pool.alloc`; `uninstall()` restores both and releases any held
pages. `counts` tallies the faults actually injected.
"""
from __future__ import annotations

import time
from collections import defaultdict

import numpy as np

from ..base import MXNetError

__all__ = ["FaultPlan", "FaultError"]


class FaultError(MXNetError):
    """An injected fault (never raised by production code). `kind`
    names the fault; the supervisor treats it like any other dispatch
    exception."""

    def __init__(self, kind, msg=""):
        super().__init__(msg or f"injected fault: {kind}")
        self.kind = kind


class FaultPlan:
    """Deterministic seed-driven fault schedule (module docstring).

    Probabilities are per hooked dispatch (prefill/decode boundary);
    `pool_exhaustion` draws once per step. `poison` is an iterable of
    request ids (fault at every phase) or a {request_id: phase} dict
    with phase in ("prefill", "decode", "both"). `max_faults` caps the
    total number of randomly injected faults (poison is exempt — it
    must keep failing past max_retries to be quarantined)."""

    def __init__(self, seed=0, dispatch_exception=0.0, slow_dispatch=0.0,
                 slow_s=0.001, nan_logits=0.0, pool_exhaustion=0.0,
                 exhaust_steps=3, exhaust_pages=None, alloc_failure=0.0,
                 poison=(), max_faults=None):
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self.dispatch_exception = float(dispatch_exception)
        self.slow_dispatch = float(slow_dispatch)
        self.slow_s = float(slow_s)
        self.nan_logits = float(nan_logits)
        self.pool_exhaustion = float(pool_exhaustion)
        self.exhaust_steps = int(exhaust_steps)
        self.exhaust_pages = exhaust_pages
        self.alloc_failure = float(alloc_failure)
        if isinstance(poison, dict):
            self.poison = {k: str(v) for k, v in poison.items()}
        else:
            self.poison = {rid: "both" for rid in poison}
        self.max_faults = max_faults
        self.counts = defaultdict(int)
        self._injected = 0         # randomly injected faults so far
        self._step = 0
        self._held = []            # [release_at_step, [pages]]
        self._alloc_armed = False
        self._engine = None
        self._orig_alloc = None

    # -- lifecycle ---------------------------------------------------------
    def install(self, engine):
        """Claim `engine.dispatch_hook` and wrap its pool's alloc()."""
        if self._engine is not None:
            raise MXNetError("FaultPlan is already installed")
        self._engine = engine
        engine.dispatch_hook = self.hook
        pool = engine.page_pool
        self._orig_alloc = pool.alloc

        def alloc(n):
            if self._alloc_armed:
                self._alloc_armed = False
                self.counts["alloc_failure"] += 1
                raise FaultError("alloc_failure",
                                 "injected transient allocator failure")
            return self._orig_alloc(n)

        pool.alloc = alloc
        return self

    def uninstall(self):
        """Restore the engine's hook and pool, release held pages."""
        eng = self._engine
        if eng is None:
            return
        if eng.dispatch_hook is self.hook:
            eng.dispatch_hook = None
        if self._orig_alloc is not None:
            eng.page_pool.alloc = self._orig_alloc
        self._release_held(force=True)
        self._engine = None
        self._orig_alloc = None

    # -- the hook ----------------------------------------------------------
    def _budget_left(self):
        return self.max_faults is None or self._injected < self.max_faults

    def _draw(self, p):
        if not p or not self._budget_left():
            return False
        if self._rng.random() >= p:
            return False
        self._injected += 1
        return True

    def _release_held(self, force=False):
        eng = self._engine
        keep = []
        for release_at, pages in self._held:
            if force or self._step >= release_at:
                eng.page_pool.free(eng.page_pool.decref(pages))
                eng.audit_extra_leases.remove(pages)
            else:
                keep.append([release_at, pages])
        self._held = keep

    def _exhaust(self, engine):
        free = engine.page_pool.num_free
        n = free if self.exhaust_pages is None \
            else min(int(self.exhaust_pages), free)
        if n < 1:
            return
        pages = self._orig_alloc(n)
        self._held.append([self._step + self.exhaust_steps, pages])
        # register the hold so the supervisor's audit can account for
        # refcounts no slot table explains
        engine.audit_extra_leases.append(pages)
        self.counts["pool_exhaustion"] += 1

    def _inject_nan(self, engine):
        """NaN one exclusive, non-tree page of one active slot (the
        first page with readable positions that no other slot or the
        radix tree can see). Skips silently when no slot has one."""
        import jax.numpy as jnp
        ref = engine.page_pool.refcounts()
        member = engine.prefix_cache.member_mask() \
            if engine.prefix_cache is not None \
            else np.zeros(engine.page_pool.num_pages, bool)
        S = engine.page_size
        cands = []
        for slot in engine.scheduler.active_slots:
            length = int(engine._lengths[slot])
            for i in range((length + S - 1) // S):
                p = int(engine._table_host[slot][i])
                if ref[p] == 1 and not member[p]:
                    cands.append(p)
                    break
        if not cands:
            return
        page = cands[int(self._rng.integers(len(cands)))]
        bad = jnp.asarray(np.nan, engine._kp.dtype)
        engine._kp = engine._kp.at[:, page].set(bad)
        self.counts["nan_logits"] += 1

    def hook(self, engine, phase="step", requests=()):
        if phase == "step":
            self._step += 1
            self._release_held()
            if self._draw(self.pool_exhaustion) and not self._held:
                self._exhaust(engine)
            return
        for r in requests:
            ph = self.poison.get(getattr(r, "id", None))
            if ph is not None and ph in ("both", phase):
                self.counts["poison"] += 1
                raise FaultError(
                    "poison", f"injected poison dispatch for request "
                              f"{r.id} ({phase})")
        if self._draw(self.slow_dispatch):
            self.counts["slow_dispatch"] += 1
            time.sleep(self.slow_s)
        if phase == "prefill" and self._draw(self.alloc_failure):
            self._alloc_armed = True       # the next pool.alloc raises
        if phase == "decode" and self._draw(self.nan_logits):
            self._inject_nan(engine)
        if self._draw(self.dispatch_exception):
            self.counts["dispatch_exception"] += 1
            raise FaultError("dispatch_exception",
                             f"injected dispatch exception ({phase})")

    def __repr__(self):
        return (f"FaultPlan(seed={self.seed}, injected={self._injected}, "
                f"counts={dict(self.counts)})")
