"""Seeded fault-injection harness for the serving engine.

A `FaultPlan` is a deterministic, seed-driven schedule of faults
injected through the engine's existing `dispatch_hook` seam (the hook
fires at the top of every step and immediately before every prefill
and decode dispatch, with the requests about to be dispatched). The
chaos soak tests (tests/test_robustness.py) and the overload bench
drive the supervisor with it; nothing here runs in production paths.

Fault kinds (each an independent per-dispatch probability under one
`numpy` Generator, so a given seed + workload replays the same plan):

  * dispatch_exception — raise `FaultError` at a prefill/decode
    boundary: the supervisor must roll the batch back, requeue the
    innocents, and keep serving.
  * slow_dispatch      — sleep `slow_s` before the dispatch: exercises
    deadline cancellation and the flight-recorder stall watchdog
    without breaking anything.
  * nan_logits         — corrupt one victim slot's KV: a page the slot
    holds EXCLUSIVELY (never a shared/radix-tree page — the injected
    poison must not outlive the victim through the prefix cache) is
    filled with NaN, so the next forward produces non-finite logits
    for that slot and the engine's in-program finite guard must catch
    it, discard the dispatch's tokens for the slot, and re-prefill.
  * pool_exhaustion    — allocate (up to) all free pages and hold them
    for `exhaust_steps` steps: admissions fail with PagePoolExhausted
    and must retry without blaming the request.
  * alloc_failure      — arm the pool so its next alloc() raises: the
    transient-allocator-failure path, including the lease rollback in
    `_map_slot_pages`.
  * poison             — request ids whose every dispatch (or every
    dispatch of a given phase) raises: the supervisor must quarantine
    them after max_retries and keep every co-batched innocent's output
    bit-identical to a fault-free run.

`install(engine)` claims the engine's dispatch_hook and wraps
`page_pool.alloc`; `uninstall()` restores both and releases any held
pages. `counts` tallies the faults actually injected.

`ReplicaFaultPlan` is the fleet-level analogue: it claims a
`ServingRouter`'s `replica_hook` seam and injects replica-scoped
faults — kill (the replica's step raises, the router must fail it
over), hang (the replica silently stops making progress, the router's
stall watchdog must catch it), and persistent-degrade (the replica
keeps re-entering degraded state, so readiness-based routing must
route around it) — on explicit per-step schedules and/or seeded
per-step probabilities. Composing a per-replica `FaultPlan` with a
fleet `ReplicaFaultPlan` gives the whole-stack chaos soak.
"""
from __future__ import annotations

import time
from collections import defaultdict

import numpy as np

from ..base import MXNetError

__all__ = ["FaultPlan", "FaultError", "ReplicaFaultPlan"]


class FaultError(MXNetError):
    """An injected fault (never raised by production code). `kind`
    names the fault; the supervisor treats it like any other dispatch
    exception."""

    def __init__(self, kind, msg=""):
        super().__init__(msg or f"injected fault: {kind}")
        self.kind = kind


class FaultPlan:
    """Deterministic seed-driven fault schedule (module docstring).

    Probabilities are per hooked dispatch (prefill/decode boundary);
    `pool_exhaustion` draws once per step. `poison` is an iterable of
    request ids (fault at every phase) or a {request_id: phase} dict
    with phase in ("prefill", "decode", "both"). `max_faults` caps the
    total number of randomly injected faults (poison is exempt — it
    must keep failing past max_retries to be quarantined)."""

    def __init__(self, seed=0, dispatch_exception=0.0, slow_dispatch=0.0,
                 slow_s=0.001, nan_logits=0.0, pool_exhaustion=0.0,
                 exhaust_steps=3, exhaust_pages=None, alloc_failure=0.0,
                 poison=(), max_faults=None):
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self.dispatch_exception = float(dispatch_exception)
        self.slow_dispatch = float(slow_dispatch)
        self.slow_s = float(slow_s)
        self.nan_logits = float(nan_logits)
        self.pool_exhaustion = float(pool_exhaustion)
        self.exhaust_steps = int(exhaust_steps)
        self.exhaust_pages = exhaust_pages
        self.alloc_failure = float(alloc_failure)
        if isinstance(poison, dict):
            self.poison = {k: str(v) for k, v in poison.items()}
        else:
            self.poison = {rid: "both" for rid in poison}
        self.max_faults = max_faults
        self.counts = defaultdict(int)
        self._injected = 0         # randomly injected faults so far
        self._step = 0
        self._held = []            # [release_at_step, [pages]]
        self._alloc_armed = False
        self._engine = None
        self._orig_alloc = None

    # -- lifecycle ---------------------------------------------------------
    def install(self, engine):
        """Claim `engine.dispatch_hook` and wrap its pool's alloc()."""
        if self._engine is not None:
            raise MXNetError("FaultPlan is already installed")
        self._engine = engine
        engine.dispatch_hook = self.hook
        pool = engine.page_pool
        self._orig_alloc = pool.alloc

        def alloc(n):
            if self._alloc_armed:
                self._alloc_armed = False
                self.counts["alloc_failure"] += 1
                raise FaultError("alloc_failure",
                                 "injected transient allocator failure")
            return self._orig_alloc(n)

        pool.alloc = alloc
        return self

    def uninstall(self):
        """Restore the engine's hook and pool, release held pages."""
        eng = self._engine
        if eng is None:
            return
        if eng.dispatch_hook is self.hook:
            eng.dispatch_hook = None
        if self._orig_alloc is not None:
            eng.page_pool.alloc = self._orig_alloc
        self._release_held(force=True)
        self._engine = None
        self._orig_alloc = None

    # -- the hook ----------------------------------------------------------
    def _budget_left(self):
        return self.max_faults is None or self._injected < self.max_faults

    def _draw(self, p):
        if not p or not self._budget_left():
            return False
        if self._rng.random() >= p:
            return False
        self._injected += 1
        return True

    def _release_held(self, force=False):
        eng = self._engine
        keep = []
        for release_at, pages in self._held:
            if force or self._step >= release_at:
                eng.page_pool.free(eng.page_pool.decref(pages))
                eng.audit_extra_leases.remove(pages)
            else:
                keep.append([release_at, pages])
        self._held = keep

    def _exhaust(self, engine):
        free = engine.page_pool.num_free
        n = free if self.exhaust_pages is None \
            else min(int(self.exhaust_pages), free)
        if n < 1:
            return
        pages = self._orig_alloc(n)
        self._held.append([self._step + self.exhaust_steps, pages])
        # register the hold so the supervisor's audit can account for
        # refcounts no slot table explains
        engine.audit_extra_leases.append(pages)
        self.counts["pool_exhaustion"] += 1

    def _inject_nan(self, engine):
        """NaN one exclusive, non-tree page of one active slot (the
        first page with readable positions that no other slot or the
        radix tree can see). Skips silently when no slot has one."""
        import jax.numpy as jnp
        ref = engine.page_pool.refcounts()
        member = engine.prefix_cache.member_mask() \
            if engine.prefix_cache is not None \
            else np.zeros(engine.page_pool.num_pages, bool)
        S = engine.page_size
        cands = []
        for slot in engine.scheduler.active_slots:
            length = int(engine._lengths[slot])
            for i in range((length + S - 1) // S):
                p = int(engine._table_host[slot][i])
                if ref[p] == 1 and not member[p]:
                    cands.append(p)
                    break
        if not cands:
            return
        page = cands[int(self._rng.integers(len(cands)))]
        bad = jnp.asarray(np.nan, engine._kp.dtype)
        engine._kp = engine._kp.at[:, page].set(bad)
        self.counts["nan_logits"] += 1

    def hook(self, engine, phase="step", requests=()):
        if phase == "step":
            self._step += 1
            self._release_held()
            if self._draw(self.pool_exhaustion) and not self._held:
                self._exhaust(engine)
            return
        for r in requests:
            ph = self.poison.get(getattr(r, "id", None))
            if ph is not None and ph in ("both", phase):
                self.counts["poison"] += 1
                raise FaultError(
                    "poison", f"injected poison dispatch for request "
                              f"{r.id} ({phase})")
        if self._draw(self.slow_dispatch):
            self.counts["slow_dispatch"] += 1
            time.sleep(self.slow_s)
        if phase == "prefill" and self._draw(self.alloc_failure):
            self._alloc_armed = True       # the next pool.alloc raises
        if phase == "decode" and self._draw(self.nan_logits):
            self._inject_nan(engine)
        if self._draw(self.dispatch_exception):
            self.counts["dispatch_exception"] += 1
            raise FaultError("dispatch_exception",
                             f"injected dispatch exception ({phase})")

    def __repr__(self):
        return (f"FaultPlan(seed={self.seed}, injected={self._injected}, "
                f"counts={dict(self.counts)})")


def _schedule(spec):
    """Normalize {step: replica | [replicas]} / [(step, replica)] into
    {step: [replicas]}."""
    out = {}
    items = spec.items() if isinstance(spec, dict) else spec
    for step, who in items:
        idxs = [who] if isinstance(who, int) else list(who)
        out.setdefault(int(step), []).extend(int(i) for i in idxs)
    return out


class ReplicaFaultPlan:
    """Deterministic replica-level fault schedule for a ServingRouter
    (module docstring). Steps count ROUTER steps (the fleet tick fires
    once per `router.step()`).

    kill / hang / degrade: explicit schedules — {step: replica} (or a
        list of replicas, or [(step, replica), ...]). A kill makes the
        replica's next step raise FaultError("replica_kill"); a hang
        freezes it (the hook answers "skip" — no engine.step() — for
        `hang_ticks` router steps, or forever with hang_ticks=None);
        degrade re-asserts `_set_degraded(True)` on the replica every
        tick from then on — a persistent fault that readiness-based
        placement must route around, not a one-shot blip.
    kill_p / hang_p: additional per-replica per-step probabilities
        under the plan's seeded Generator (a given seed + fleet replays
        the same chaos). `max_faults` caps the RANDOM faults only;
        scheduled ones always fire.
    """

    def __init__(self, seed=0, kill=(), hang=(), degrade=(),
                 hang_ticks=40, kill_p=0.0, hang_p=0.0,
                 max_faults=None):
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self.kill = _schedule(kill)
        self.hang = _schedule(hang)
        self.degrade = _schedule(degrade)
        self.hang_ticks = hang_ticks
        self.kill_p = float(kill_p)
        self.hang_p = float(hang_p)
        self.max_faults = max_faults
        self.counts = defaultdict(int)
        self._injected = 0
        self._step = 0
        self._pending_kill = set()     # replica idxs to kill on touch
        self._hung_until = {}          # replica idx -> last hung step
        self._degraded = set()         # replica idxs under degrade
        self._router = None

    # -- lifecycle ---------------------------------------------------------
    def install(self, router):
        """Claim `router.replica_hook`."""
        if self._router is not None:
            raise MXNetError("ReplicaFaultPlan is already installed")
        self._router = router
        router.replica_hook = self.hook
        return self

    def uninstall(self):
        """Restore the router's hook; scheduled state stays as-is
        (a killed replica is the router's to rejoin())."""
        router = self._router
        if router is None:
            return
        if router.replica_hook is self.hook:
            router.replica_hook = None
        self._router = None

    # -- the hook ----------------------------------------------------------
    def _budget_left(self):
        return self.max_faults is None or self._injected < self.max_faults

    def _draw(self, p):
        if not p or not self._budget_left():
            return False
        if self._rng.random() >= p:
            return False
        self._injected += 1
        return True

    def _start_hang(self, idx):
        until = None if self.hang_ticks is None \
            else self._step + int(self.hang_ticks)
        self._hung_until[idx] = until
        self.counts["hang"] += 1

    def hook(self, router, idx, engine):
        if idx is None:                 # fleet tick
            self._step += 1
            for i in self.kill.get(self._step, ()):
                self._pending_kill.add(i)
            for i in self.hang.get(self._step, ()):
                self._start_hang(i)
            for i in self.degrade.get(self._step, ()):
                self._degraded.add(i)
                self.counts["degrade"] += 1
            up = [i for i, rep in enumerate(router.replicas)
                  if rep.state == "up"]
            # at most one random fault per tick: a seeded draw should
            # not take the whole fleet down in one step
            for i in up:
                if self._draw(self.kill_p):
                    self._pending_kill.add(i)
                    break
                if self._draw(self.hang_p):
                    self._start_hang(i)
                    break
            return None
        if idx in self._degraded:
            # persistent-degrade: re-assert every tick — the engine's
            # flight-recorder rearm must not bring it back
            engine._set_degraded(True, "injected persistent degrade")
        if idx in self._pending_kill:
            self._pending_kill.discard(idx)
            self._hung_until.pop(idx, None)
            self.counts["kill"] += 1
            raise FaultError("replica_kill",
                             f"injected replica kill (replica {idx}, "
                             f"router step {self._step})")
        until = self._hung_until.get(idx, -1)
        if until is None or until > self._step:
            self.counts["hang_ticks"] += 1
            return "skip"               # frozen: no step, no progress
        return None

    def __repr__(self):
        return (f"ReplicaFaultPlan(seed={self.seed}, step={self._step}, "
                f"counts={dict(self.counts)})")
