"""Cross-process serving fleet: worker processes behind a versioned
wire protocol, with optional disaggregated prefill/decode roles.

Layers (docs/SERVING.md "Cross-process fleet & disaggregated
prefill/decode"):

* `wire`     — the versioned migration blob format (the in-process
               export/adopt contract, serialized byte-for-byte)
* `worker`   — `FleetWorker`: one ServingEngine behind the serving
               HTTP frontend plus the /fleet/* control plane; runnable
               as `python -m mxnet_tpu.serving.fleet.worker`
* `client`   — `WorkerClient` RPC stubs + the WorkerGone /
               WorkerRejected failure taxonomy
* `router`   — `FleetRouter`: rendezvous placement, hedging, health
               watchdog, SIGKILL failover, prefill->decode handoff
* `launch`   — subprocess supervision (`spawn_worker`/`spawn_fleet`)
* `observe`  — `FleetCollector`: the fleet observability plane —
               scrape/merge every worker's metrics (counters summed,
               gauges per-worker, histograms bucket-wise), assemble
               one clock-aligned Perfetto trace across processes,
               judge fleet-global SLOs, latch correlated fleet flight
               dumps, serve /fleetz
"""
from .wire import WIRE_VERSION, WireVersionError, encode_request, \
    decode_request
from .client import WorkerClient, WorkerGone, WorkerRejected
from .worker import FleetWorker, build_engine, warm_engine
from .router import FleetRouter
from .launch import WorkerProc, FleetProcs, spawn_worker, spawn_fleet
from .observe import FleetCollector, fleet_chrome_trace

__all__ = [
    "WIRE_VERSION", "WireVersionError", "encode_request",
    "decode_request", "WorkerClient", "WorkerGone", "WorkerRejected",
    "FleetWorker", "build_engine", "warm_engine", "FleetRouter",
    "WorkerProc", "FleetProcs", "spawn_worker", "spawn_fleet",
    "FleetCollector", "fleet_chrome_trace",
]
