"""HTTP stubs for talking to fleet workers — stdlib-only.

`WorkerClient` wraps one worker's base URL with the RPC surface the
router needs: generate (proxied SSE), prefill/adopt (the disaggregated
handoff), cancel, drain/undrain, health and stats. Failure taxonomy is
the whole point of this module:

* `WorkerGone` — connection-level evidence the worker process is gone
  or wedged: refused, reset, timed out, or the response stream hit EOF
  before its `done` event. The router treats it as replica-down and
  fails the work over.
* `WorkerRejected` — the worker ANSWERED with a structured rejection
  (429 queue-full/quota, 503 overload/draining, 409 wire-version
  mismatch, 400 invalid). The structured body fields ride on the
  exception so the router can re-raise the engine-shaped error at its
  own admission edge.

Retries are bounded with exponential backoff and apply to CONNECT
failures only — a request that may have reached the worker is never
replayed blindly (the router owns replay, via the migration contract,
where it is deterministic).
"""
from __future__ import annotations

import http.client
import json
import time
from urllib.parse import urlparse

from ...base import MXNetError
from . import wire

__all__ = ["WorkerClient", "WorkerGone", "WorkerRejected", "SSEStream"]


class WorkerGone(MXNetError):
    """Connection-level failure: the worker is unreachable or its
    stream died before completing. Replica-down evidence."""


class WorkerRejected(MXNetError):
    """The worker answered with an HTTP error and (when well-formed) a
    structured JSON body {"error": {type, reason, message, ...}}."""

    def __init__(self, code, body=None):
        body = body if isinstance(body, dict) else {}
        err = body.get("error") or {}
        if not isinstance(err, dict):
            err = {"message": str(err)}
        super().__init__(
            f"worker rejected ({code}): "
            f"{err.get('reason') or err.get('type') or 'error'}: "
            f"{err.get('message')}")
        self.code = int(code)
        self.body = body
        self.type = err.get("type")
        self.reason = err.get("reason")
        self.retry_after_s = err.get("retry_after_s")
        self.queue_depth = err.get("queue_depth")
        self.active_slots = err.get("active_slots")


class SSEStream:
    """Iterator over one close-delimited SSE response: yields
    (event, data_dict) pairs, skipping keepalive comments. EOF before
    the stream's `done` event — or any socket error — raises
    WorkerGone, because a close-delimited stream that ends early IS
    the worker dying mid-request."""

    def __init__(self, conn, resp):
        self._conn = conn
        self._resp = resp
        self.done = False

    def __iter__(self):
        event, data = None, None
        while True:
            try:
                line = self._resp.readline()
            except (OSError, http.client.HTTPException) as e:
                self.close()
                raise WorkerGone(f"worker stream died mid-read: "
                                 f"{type(e).__name__}: {e}")
            if not line:            # EOF — the close that delimits
                self.close()
                if not self.done:
                    raise WorkerGone(
                        "worker stream ended before its 'done' event")
                return
            line = line.decode("utf-8", "replace").rstrip("\r\n")
            if not line:            # frame boundary
                if event is not None:
                    if event == "done":
                        self.done = True
                    yield event, data
                    if self.done:
                        self.close()
                        return
                    event, data = None, None
                continue
            if line.startswith(":"):
                continue            # keepalive comment
            if line.startswith("event:"):
                event = line[len("event:"):].strip()
            elif line.startswith("data:"):
                try:
                    data = json.loads(line[len("data:"):].strip())
                except ValueError:
                    data = None

    def close(self):
        try:
            self._conn.close()
        except Exception:           # noqa: BLE001 — teardown
            pass


class WorkerClient:
    """One worker's RPC surface. Connection-per-RPC (HTTP/1.0 on the
    worker side anyway); per-RPC timeouts; bounded connect retries."""

    def __init__(self, url, timeout_s=30.0, connect_retries=2,
                 backoff_s=0.05):
        u = urlparse(url if "://" in url else "http://" + url)
        if not u.hostname or not u.port:
            raise MXNetError(f"worker url needs host:port, got {url!r}")
        self.host = u.hostname
        self.port = int(u.port)
        self.url = f"http://{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self.connect_retries = int(connect_retries)
        self.backoff_s = float(backoff_s)

    def __repr__(self):
        return f"WorkerClient({self.url})"

    # -- plumbing ----------------------------------------------------------
    def _open(self, timeout=None):
        """Connect with bounded retries + exponential backoff. Only
        the connect is retried: once bytes may have reached the
        worker, a blind replay could double-submit."""
        last = None
        for attempt in range(self.connect_retries + 1):
            conn = http.client.HTTPConnection(
                self.host, self.port,
                timeout=self.timeout_s if timeout is None else timeout)
            try:
                conn.connect()
                return conn
            except OSError as e:
                conn.close()
                last = e
                if attempt < self.connect_retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        raise WorkerGone(f"{self.url}: connect failed: "
                         f"{type(last).__name__}: {last}")

    def _request(self, method, path, body=None, timeout=None,
                 headers=()):
        conn = self._open(timeout)
        try:
            data = None
            hdrs = dict(headers)
            if body is not None:
                data = body if isinstance(body, bytes) \
                    else json.dumps(body).encode("utf-8")
                hdrs.setdefault("Content-Type", "application/json")
            conn.request(method, path, body=data, headers=hdrs)
            return conn, conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise WorkerGone(f"{self.url}{path}: "
                             f"{type(e).__name__}: {e}")

    def _json(self, method, path, body=None, timeout=None):
        conn, resp = self._request(method, path, body, timeout)
        try:
            try:
                raw = resp.read()
            except (OSError, http.client.HTTPException) as e:
                raise WorkerGone(f"{self.url}{path}: read failed: {e}")
        finally:
            conn.close()
        try:
            obj = json.loads(raw) if raw else {}
        except ValueError:
            obj = {"raw": raw[:200].decode("utf-8", "replace")}
        if resp.status >= 400:
            raise WorkerRejected(resp.status, obj)
        return obj

    def _sse(self, path, body, timeout=None, headers=()):
        conn, resp = self._request("POST", path, body, timeout, headers)
        if resp.status != 200:
            try:
                raw = resp.read()
                obj = json.loads(raw) if raw else {}
            except (OSError, ValueError, http.client.HTTPException):
                obj = {}
            finally:
                conn.close()
            raise WorkerRejected(resp.status, obj)
        return SSEStream(conn, resp)

    # -- data plane --------------------------------------------------------
    def generate(self, body, traceparent=None, timeout=None):
        """POST /v1/generate with "stream": true -> SSEStream. The
        traceparent header carries the router-owned trace id so the
        worker's timeline joins the request's single trace."""
        hdrs = (("traceparent", traceparent),) if traceparent else ()
        return self._sse("/v1/generate", dict(body, stream=True),
                         timeout=timeout, headers=hdrs)

    def prefill(self, body, traceparent=None, timeout=None):
        """POST /fleet/prefill: submit, run prefill to the first
        token, export with KV payload. Returns the wire blob dict
        (blob["final"] set when the request went terminal during
        prefill and there is nothing to hand off)."""
        hdrs = (("traceparent", traceparent),) if traceparent else ()
        blob = self._json("POST", "/fleet/prefill", body,
                          timeout=timeout or self.timeout_s)
        wire.check_version(blob)
        return blob

    def adopt(self, blob, timeout=None):
        """POST /fleet/adopt with a wire blob -> SSEStream of the
        continuation (an `adopted` event, then `tokens` events indexed
        from the blob's token count)."""
        return self._sse("/fleet/adopt", wire.dumps(blob),
                         timeout=timeout)

    def cancel(self, request_id, timeout=5.0):
        return self._json("POST", "/fleet/cancel",
                          {"request_id": request_id}, timeout=timeout)

    def export(self, timeout=None):
        """POST /fleet/export: drain-style export of every in-flight
        request as replay blobs (no KV payloads)."""
        out = self._json("POST", "/fleet/export", {}, timeout=timeout)
        return out.get("requests", [])

    # -- control plane -----------------------------------------------------
    def drain(self, timeout=5.0):
        return self._json("POST", "/fleet/drain", {}, timeout=timeout)

    def undrain(self, timeout=5.0):
        return self._json("POST", "/fleet/undrain", {}, timeout=timeout)

    def stats(self, timeout=10.0):
        return self._json("GET", "/fleet/stats", timeout=timeout)

    def requests(self, n=None, timeout=10.0):
        """GET /fleet/requests — the worker's recent request
        timelines; `n` bounds the pull (the collector caps it so a
        scrape cycle's cost stays flat as the log fills)."""
        path = "/fleet/requests" if n is None \
            else f"/fleet/requests?n={int(n)}"
        return self._json("GET", path, timeout=timeout)

    def sloz(self, timeout=10.0):
        """GET /fleet/sloz — the worker's SLO snapshot + clock stamp."""
        return self._json("GET", "/fleet/sloz", timeout=timeout)

    def flightz(self, timeout=10.0):
        """GET /fleet/flightz — the worker's flight-recorder state
        (latched reasons, dump paths, breadcrumb tail)."""
        return self._json("GET", "/fleet/flightz", timeout=timeout)

    def healthz(self, timeout=2.0):
        try:
            self._json("GET", "/healthz", timeout=timeout)
            return True
        except (WorkerGone, WorkerRejected):
            return False

    def metrics_text(self, timeout=10.0):
        conn, resp = self._request("GET", "/metrics", timeout=timeout)
        try:
            raw = resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise WorkerGone(f"{self.url}/metrics: read failed: {e}")
        finally:
            conn.close()
        if resp.status >= 400:
            raise WorkerRejected(resp.status, {})
        return raw.decode("utf-8", "replace")
