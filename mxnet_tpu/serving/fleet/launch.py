"""Spawn and supervise fleet worker subprocesses.

`spawn_worker` launches `python -m mxnet_tpu.serving.fleet.worker`
with a JSON spec written to a temp file, waits for the worker's
`FLEET_WORKER_READY {json}` line (model build + warmup included —
readiness means the steady-state programs are compiled), and returns a
`WorkerProc` handle that can kill (SIGKILL — the chaos tests' murder
weapon), terminate, and reap the process. `spawn_fleet` brings up a
whole topology and tears it down as a context manager.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

from ...base import MXNetError

__all__ = ["WorkerProc", "spawn_worker", "spawn_fleet", "FleetProcs"]

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))


class WorkerProc:
    """One spawned worker subprocess + its READY announcement."""

    def __init__(self, proc, url, role, worker_id, spec_path):
        self.proc = proc
        self.url = url
        self.role = role
        self.worker_id = worker_id
        self.pid = proc.pid
        self._spec_path = spec_path

    @property
    def alive(self):
        return self.proc.poll() is None

    def kill(self):
        """SIGKILL — no goodbye, no flushing; the router must notice
        via connection loss, exactly like a real machine loss."""
        if self.alive:
            try:
                self.proc.kill()
            except OSError:
                pass
        self.wait(10)

    def terminate(self):
        if self.alive:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def wait(self, timeout=30):
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass
        self._cleanup()

    def _cleanup(self):
        try:
            os.unlink(self._spec_path)
        except OSError:
            pass

    def __repr__(self):
        return (f"WorkerProc(pid={self.pid}, url={self.url}, "
                f"role={self.role}, alive={self.alive})")


def _drain_output(proc, sink):
    """Keep reading the child's combined stdout/stderr after READY so
    the pipe never fills and blocks it (and keep a bounded tail for
    post-mortems)."""
    def run():
        for line in proc.stdout:
            sink.append(line.rstrip("\n"))
            del sink[:-200]
    threading.Thread(target=run, daemon=True,
                     name=f"mx-fleet-drain:{proc.pid}").start()


def spawn_worker(spec, role="mixed", host="127.0.0.1", port=0,
                 ship_payload=True, warmup=True, env=None,
                 ready_timeout_s=600.0):
    """Launch one worker process and block until it is READY (or dead).
    Returns a WorkerProc. The spec travels via a temp file, so big
    engine configs never hit argv limits."""
    fd, spec_path = tempfile.mkstemp(prefix="mx_fleet_spec_",
                                     suffix=".json")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        json.dump(spec, f)
    cmd = [sys.executable, "-m", "mxnet_tpu.serving.fleet.worker",
           "--spec", spec_path, "--role", role,
           "--host", host, "--port", str(port)]
    if not ship_payload:
        cmd.append("--no-ship-payload")
    if not warmup:
        cmd.append("--no-warmup")
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = _REPO_ROOT + os.pathsep \
        + child_env.get("PYTHONPATH", "")
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    child_env.update(env or {})
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=_REPO_ROOT, env=child_env)
    tail = []
    deadline = time.monotonic() + float(ready_timeout_s)
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise MXNetError(
                    "fleet worker died before READY (rc="
                    f"{proc.returncode}):\n" + "\n".join(tail[-40:]))
            time.sleep(0.01)
            continue
        line = line.rstrip("\n")
        tail.append(line)
        del tail[:-200]
        if line.startswith("FLEET_WORKER_READY "):
            info = json.loads(line[len("FLEET_WORKER_READY "):])
            wp = WorkerProc(proc, info["url"], info.get("role", role),
                            info.get("worker_id"), spec_path)
            wp.output_tail = tail
            _drain_output(proc, tail)
            return wp
    proc.kill()
    raise MXNetError(
        f"fleet worker not READY within {ready_timeout_s}s:\n"
        + "\n".join(tail[-40:]))


class FleetProcs:
    """A spawned topology: `workers` in spawn order. Context manager;
    exit SIGKILLs anything still alive."""

    def __init__(self, workers):
        self.workers = list(workers)

    @property
    def urls(self):
        return [w.url for w in self.workers]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        for w in self.workers:
            w.kill()


def spawn_fleet(spec, roles=("mixed", "mixed"), **kw):
    """Bring up one worker per role entry (serially — model build is
    memory-hungry enough that parallel cold starts thrash small
    hosts). Returns a FleetProcs."""
    procs = []
    try:
        for role in roles:
            procs.append(spawn_worker(spec, role=role, **kw))
    except Exception:
        for p in procs:
            p.kill()
        raise
    return FleetProcs(procs)
