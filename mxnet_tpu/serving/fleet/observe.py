"""FleetCollector: one metrics/trace/SLO view over the worker fleet.

PR 18 made serving a multi-process fleet; this module makes it ONE
observable system. A pull-based collector runs beside `FleetRouter`
and periodically scrapes every worker's `/metrics`, `/fleet/requests`,
`/fleet/sloz`, and `/fleet/flightz` over the existing control plane
(bounded per-RPC timeouts — a dead or wedged worker marks itself stale
via `fleet_scrape_errors_total{worker}` and NEVER blocks the loop),
then merges the answers into one registry with the correct aggregation
per instrument kind:

  * counters SUM across workers (the fleet emitted N tokens),
  * gauges stay PER-WORKER — `worker_id`/`role` labels are appended
    (a fleet-summed slot occupancy is meaningless),
  * histograms merge BUCKET-WISE via `Histogram.merge()` — never by
    averaging per-worker percentiles, which is wrong the moment two
    workers see different load (docs/OBSERVABILITY.md "Fleet
    observability").

On top of the merged view:

  * **cross-process trace assembly** — `fleet_chrome_trace()` gathers
    every worker's timeline ring, aligns each onto the collector's
    clock using the per-worker offset measured at scrape time (the
    worker answers its wall-anchored `now`; offset = worker_now minus
    the scrape round-trip midpoint), and emits one Perfetto file with
    one process track per worker pid. A disaggregated request's
    prefill → handoff → decode spans land on different process tracks
    under a single stitched trace_id.
  * a **fleet-global SLO engine** — a second `SLOEngine` fed from the
    merged first-token/finish event stream (deduplicated across
    scrapes and across workers, so a migrated request counts once),
    publishing `slo_fleet_*` instruments. TTFT p99 and goodput
    objectives are judged fleet-wide, not per process.
  * a **correlated fleet flight dump** — any worker's flight latch
    (mirrored from `/fleet/flightz`) or a fleet SLO fast burn latches
    ONE dump per reason: every worker's metrics + requests + flight
    state plus the merged registry, snapshotted into one directory
    with the same atomic .tmp → rename discipline as the per-process
    flight recorder.
  * the **/fleetz** payload (`fleetz()`), served by the router
    process's introspection server once the collector registers
    itself: per-worker health/role/weight_dtype/steady-compiles,
    fleet tokens/sec and tokens/sec/chip at the current merged TTFT
    p99, and scrape staleness.

Stdlib-only, like the rest of the control plane: the collector talks
HTTP to workers and never imports jax.
"""
from __future__ import annotations

import itertools
import json
import math
import os
import re
import threading
import time

from ...base import MXNetError
from ... import telemetry
from ...telemetry.instruments import Histogram, Registry
from .client import WorkerClient, WorkerGone, WorkerRejected

__all__ = ["FleetCollector", "parse_prometheus", "merge_exports",
           "fleet_chrome_trace"]

_collector_ids = itertools.count()
_C = ("collector",)

# label pairs inside the braces of one sample line
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# label-string -> parsed dict. Label SETS are low-cardinality and
# stable across scrape cycles while VALUES change every line, so the
# brace content is the natural memo key — it turns the per-line regex
# walk into a dict hit on the scrape hot path. Cached dicts are shared:
# callers must treat them as frozen.
_label_cache = {}
_suffix_cache = {}                     # name -> (base, suffix) or ""


def _parse_labels(rawlab):
    d = _label_cache.get(rawlab)
    if d is None:
        d = {k: v.replace('\\"', '"').replace("\\\\", "\\")
             for k, v in _LABEL_RE.findall(rawlab)}
        if len(_label_cache) > 8192:   # bound both memo tables
            _label_cache.clear()
            _suffix_cache.clear()
        _label_cache[rawlab] = d
    return d


def _hist_suffix(name):
    r = _suffix_cache.get(name)
    if r is None:
        r = ""
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                r = (name[:-len(suffix)], suffix)
                break
        _suffix_cache[name] = r
    return r


def parse_prometheus(text):
    """Parse a Prometheus text exposition (0.0.4) into
    {family: {"kind", "help", "samples": [(labels_dict, value)],
    "hist": {label_key: {"labels", "bounds", "cumulative", "sum",
    "count"}}}}. Histogram `_bucket`/`_sum`/`_count` series fold back
    into their family; `cumulative` keeps the raw cumulative counts
    (including +Inf, last) so `Histogram.from_cumulative` can
    reconstruct per-bucket counts. Label dicts come from a shared memo
    (label sets repeat across lines and scrape cycles) — treat them as
    read-only."""
    fams = {}

    def fam(name):
        f = fams.get(name)
        if f is None:
            f = fams[name] = {"kind": "untyped", "help": "",
                              "samples": [], "hist": {}}
        return f

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                fam(parts[2])["kind"] = parts[3].strip() \
                    if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam(parts[2])["help"] = parts[3] if len(parts) > 3 else ""
            continue
        # "name value" | "name{labels} value" — the value is the text
        # after the last space (label values may themselves contain
        # spaces, but they sit inside the braces)
        sp = line.rfind(" ")
        if sp <= 0:
            continue
        head = line[:sp].rstrip()
        try:
            value = float(line[sp + 1:])
        except ValueError:
            continue
        if head.endswith("}"):
            br = head.find("{")
            if br <= 0:
                continue
            name = head[:br]
            labels = _parse_labels(head[br + 1:-1])
        else:
            name, labels = head, {}
            if " " in name or "{" in name:
                continue
        hs = _hist_suffix(name)
        base = None
        if hs and fams.get(hs[0], {}).get("kind") == "histogram":
            base = hs[0]
        if base is not None:
            hl = {k: v for k, v in labels.items() if k != "le"}
            key = tuple(sorted(hl.items()))
            h = fam(base)["hist"].setdefault(
                key, {"labels": hl, "bounds": [], "cumulative": [],
                      "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                le = labels.get("le", "+Inf")
                b = math.inf if le == "+Inf" else float(le)
                h["bounds"].append(b)
                h["cumulative"].append(value)
            elif name.endswith("_sum"):
                h["sum"] = value
            else:
                h["count"] = int(value)
        else:
            fams[name] = fam(name)
            fams[name]["samples"].append((labels, value))
    return fams


def _scan_counter_total(text, name):
    """Sum every sample of one counter family straight off the raw
    exposition text — the scrape loop's per-cycle rate bookkeeping
    needs exactly one family, and a C-speed `str.find` walk over the
    few matching lines beats parsing the whole export."""
    total = 0.0
    i = text.find(name)
    while i != -1:
        if i == 0 or text[i - 1] == "\n":      # line start == a sample
            j = text.find("\n", i)
            line = text[i:j] if j != -1 else text[i:]
            sp = line.rfind(" ")
            if sp > 0:
                try:
                    total += float(line[sp + 1:])
                except ValueError:
                    pass
        i = text.find(name, i + 1)
    return total


def _hist_from_export(name, help, h):
    """One scraped histogram series -> a reconstructed Histogram."""
    pairs = sorted(zip(h["bounds"], h["cumulative"]))
    bounds = tuple(b for b, _ in pairs if b != math.inf)
    cum = [c for _, c in pairs]
    if len(cum) == len(bounds):       # exposition without +Inf line
        cum.append(float(h["count"]))
    return Histogram.from_cumulative(bounds, cum, h["sum"], h["count"],
                                     name=name, help=help)


def merge_exports(exports, out=None):
    """Merge per-worker Prometheus exports into one Registry.

    `exports` is [(worker_id, role, families_dict)] with families as
    `parse_prometheus` returns them. Counters sum across workers per
    label-set; gauges append (worker_id, role) labels and stay
    per-worker; histograms merge bucket-wise. Families whose shape
    disagrees across workers (labelnames or bucket bounds) are skipped
    and returned in the conflict list: (registry, [family, ...])."""
    target = out if out is not None else Registry()
    conflicts = []
    names = []
    for _wid, _role, fams in exports:
        for name in fams:
            if name not in names:
                names.append(name)
    for name in names:
        try:
            _merge_family(target, name, exports)
        except MXNetError:
            conflicts.append(name)
    return target, conflicts


def _merge_family(target, name, exports):
    kind = help = None
    for _wid, _role, fams in exports:
        f = fams.get(name)
        if f is None:
            continue
        if kind is None:
            kind, help = f["kind"], f["help"]
        elif f["kind"] != kind:
            raise MXNetError(f"family {name!r}: kind disagrees")
    if kind == "counter":
        totals = {}                   # label tuple -> (labels, sum)
        for _wid, _role, fams in exports:
            for labels, value in fams.get(name, {}).get("samples", ()):
                key = tuple(sorted(labels.items()))
                prev = totals.get(key)
                totals[key] = (labels, (prev[1] if prev else 0.0) + value)
        labelnames = _labelnames(v[0] for v in totals.values())
        inst = target.counter(name, help, labelnames)
        for labels, total in totals.values():
            child = inst.labels(**labels) if labelnames else inst
            child.inc(max(total, 0.0))
    elif kind == "gauge":
        rows = []
        for wid, role, fams in exports:
            for labels, value in fams.get(name, {}).get("samples", ()):
                rows.append((wid, role, labels, value))
        labelnames = _labelnames(r[2] for r in rows) \
            + ("worker_id", "role")
        inst = target.gauge(name, help, labelnames)
        for wid, role, labels, value in rows:
            inst.labels(**dict(labels, worker_id=wid,
                               role=role)).set(value)
    elif kind == "histogram":
        series = {}                   # label tuple -> (labels, [Hist])
        for _wid, _role, fams in exports:
            for key, h in fams.get(name, {}).get("hist", {}).items():
                series.setdefault(key, (h["labels"], []))[1].append(
                    _hist_from_export(name, help, h))
        bounds = None
        for _labels, hists in series.values():
            for h in hists:
                if bounds is None:
                    bounds = h.buckets
                elif h.buckets != bounds:
                    raise MXNetError(f"family {name!r}: buckets disagree")
        if bounds is None:
            return
        labelnames = _labelnames(v[0] for v in series.values())
        inst = target.histogram(name, help, labelnames, buckets=bounds)
        for labels, hists in series.values():
            child = inst.labels(**labels) if labelnames else inst
            for h in hists:
                child.merge(h)
    # untyped families (none today) are dropped: no aggregation rule


def _labelnames(labeldicts):
    """The union'd label-name tuple for one family, in first-seen
    order — every worker renders the same declaration, so in practice
    this is just the declared order."""
    names = []
    for d in labeldicts:
        for k in d:
            if k not in names:
                names.append(k)
    return tuple(names)


class _WorkerView:
    """One worker as the collector sees it: the client stub, learned
    identity, the measured clock offset, and the last good scrape."""

    def __init__(self, index, client):
        self.index = index
        self.client = client
        self.worker_id = client.url      # until the first stats answer
        self.role = "unknown"
        self.pid = None
        self.offset = 0.0                # worker clock - collector clock
        self.stats = {}
        self._text = ""                  # raw /metrics exposition
        self._fams = None                # parsed lazily from _text
        self.requests = []
        self.sloz = {}
        self.flightz = {}
        self.last_ok = None              # collector clock, last full scrape
        self.errors = 0
        self.last_error = None

    @property
    def families(self):
        """Parsed metric families, parsed LAZILY from the last scraped
        exposition text: the scrape cycle itself never pays the parse —
        only readers that need the structured view (merged registry,
        fleet dumps) do."""
        if self._fams is None:
            self._fams = parse_prometheus(self._text) if self._text \
                else {}
        return self._fams

    @property
    def stale(self):
        return self.last_ok is None or self.last_error is not None


def _fleet_collector_metrics(cid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    return {
        "errors": c(
            "fleet_scrape_errors_total",
            "scrape failures per worker (connection loss, timeout, "
            "HTTP error) — the worker's view goes stale, the loop "
            "keeps going", ("collector", "worker")),
        "cycles": c(
            "fleet_scrape_cycles_total",
            "completed collector scrape cycles", _C).labels(cid),
        "scrape_s": h(
            "fleet_scrape_seconds",
            "wall time of one full scrape cycle across every worker "
            "(serial RPCs, bounded per-RPC timeouts)", _C).labels(cid),
        "age": g(
            "fleet_scrape_age_seconds",
            "seconds since each worker's last successful scrape "
            "(staleness; grows while a worker is down)",
            ("collector", "worker")),
        "stale": g(
            "fleet_workers_stale",
            "workers whose last scrape failed (their merged view is "
            "from an earlier cycle)", _C).labels(cid),
        "tok_s": g(
            "fleet_tokens_per_sec",
            "fleet-wide token emission rate over the trailing scrape "
            "window (delta of the merged "
            "serving_tokens_emitted_total)", _C).labels(cid),
        "tok_s_chip": g(
            "fleet_tokens_per_sec_per_chip",
            "fleet tokens/sec divided by the chips serving them "
            "(sum of per-worker tp_shards) — ROADMAP item 1's "
            "headline, at the merged TTFT p99", _C).labels(cid),
        "dumps": c(
            "fleet_flight_dumps_total",
            "correlated fleet flight dumps written, by reason "
            "(worker:<id>:<latch> or slo_fleet_burn:<objective>)",
            ("collector", "reason")),
    }


def _fleet_slo_metrics():
    c, g = telemetry.counter, telemetry.gauge
    return {
        "events": c(
            "slo_fleet_events_total",
            "fleet-wide SLO observations from the merged event "
            "stream, classified per objective (verdict=good|bad)",
            ("objective", "verdict")),
        "burn": g(
            "slo_fleet_burn_rate",
            "fleet-wide error-budget burn rate per objective and "
            "window (judged over every worker's merged events)",
            ("objective", "window")),
        "burning": g(
            "slo_fleet_fast_burning",
            "1 while the fleet-wide fast-window burn rate is at/over "
            "threshold, else 0", ("objective",)),
    }


class FleetCollector:
    """Scrape-merge-judge loop over one fleet (see module docstring).

    workers: base URLs or WorkerClient instances (a router's live
    clients work — `FleetRouter.observe()` wires exactly that).
    router: optional FleetRouter whose identity/stats ride along in
    `fleetz()`. objectives: fleet-global `telemetry.SLO` list.
    interval_s: scrape period of the background loop (`start()`);
    `scrape()` may also be driven by hand. out_dir: where correlated
    fleet dumps land. requests_n: per-worker timeline pull bound per
    cycle — the knob that keeps a cycle's cost flat as the request log
    fills (raise it if a worker finishes more than requests_n requests
    per interval, or the SLO feed samples rather than sees them all).
    clock: injectable for tests — defaults to the wall-anchored
    telemetry clock, the axis every aligned event timestamp lives on.
    """

    def __init__(self, workers, *, router=None, interval_s=1.0,
                 scrape_timeout_s=5.0, objectives=(),
                 out_dir="flight_dumps", rate_window_s=10.0,
                 requests_n=32, clock=None, cid=None):
        if not workers:
            raise MXNetError("FleetCollector needs at least one worker")
        self.cid = str(cid) if cid is not None \
            else str(next(_collector_ids))
        self.interval_s = float(interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.out_dir = str(out_dir)
        self.rate_window_s = float(rate_window_s)
        self.requests_n = int(requests_n)
        self.router = router
        self._clock = clock if clock is not None else telemetry.now
        self._views = []
        for i, w in enumerate(workers):
            client = w if isinstance(w, WorkerClient) else WorkerClient(w)
            self._views.append(_WorkerView(i, client))
        self._m = _fleet_collector_metrics(self.cid)
        self._lock = threading.Lock()
        self._merged = Registry()
        self._merge_conflicts = []
        self._merge_stamp = None      # cycle the lazy merge is valid for
        self._seen_slo = set()        # (request_id, kind) fed to the SLO
        self._tok_marks = []          # (t, fleet tokens total) per cycle
        self._tok_rate = 0.0
        self._chips = 0
        self._cycles = 0
        self._dumped = set()          # latched correlated-dump reasons
        self._dump_paths = []
        self._stop = threading.Event()
        self._thread = None
        self._slo = telemetry.slo.SLOEngine(
            objectives, clock=self._clock,
            metrics=_fleet_slo_metrics(),
            on_fast_burn=lambda name, detail: self.fleet_dump(
                f"slo_fleet_burn:{name}", detail))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Run the scrape loop on a daemon thread and publish
        `fleetz()` on this process's introspection server."""
        if self._thread is not None:
            return self
        from ...telemetry import server as _tserver
        _tserver.register_fleetz_provider(self.fleetz)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mx-fleet-collector:{self.cid}")
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.scrape_timeout_s
                   * (len(self._views) * 4 + 2) + self.interval_s)
        from ...telemetry import server as _tserver
        _tserver.unregister_fleetz_provider(self.fleetz)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._cycle()
            except Exception:         # noqa: BLE001 — loop must survive
                pass
            self._stop.wait(self.interval_s)

    # -- the scrape cycle ---------------------------------------------------
    def scrape(self):
        """One full cycle (see `_cycle`), then the merged registry.
        The background loop runs `_cycle` alone — the parse/merge cost
        of building the registry view is paid lazily, by readers."""
        self._cycle(full=True)
        return self.merged

    def _cycle(self, full=None):
        """One scrape cycle: pull every worker, update the fleet
        rates, feed + evaluate the fleet SLO engine, mirror worker
        flight latches. Never raises on worker failure — a failing
        worker only bumps `fleet_scrape_errors_total{worker}` and
        leaves its last good snapshot in place, stale. The merged
        registry is NOT rebuilt here: the raw exposition text is
        stashed per worker and `merged` re-parses on demand, so the
        periodic loop stays off the serving path even on saturated
        single-core hosts. The sloz/flightz planes change slowly, so
        the periodic loop refreshes them every 4th cycle only (a
        worker flight latch is still mirrored within 4 intervals);
        manual `scrape()` always pulls everything."""
        if full is None:
            full = self._cycles % 4 == 0
        t_cycle0 = self._clock()
        for w in self._views:
            self._scrape_worker(w, full)
        self._update_rates()
        self._feed_slo()
        self._slo.evaluate(self._clock())
        self._mirror_worker_latches()
        now = self._clock()
        for w in self._views:
            self._m["age"].labels(self.cid, w.worker_id).set(
                now - w.last_ok if w.last_ok is not None else math.inf)
        self._m["stale"].set(sum(w.stale for w in self._views))
        self._m["cycles"].inc()
        self._m["scrape_s"].observe(max(now - t_cycle0, 0.0))
        with self._lock:
            self._cycles += 1

    def _scrape_worker(self, w, full=True):
        tmo = self.scrape_timeout_s
        try:
            t0 = self._clock()
            stats = w.client.stats(timeout=tmo)
            t1 = self._clock()
            text = w.client.metrics_text(timeout=tmo)
            requests = w.client.requests(n=self.requests_n, timeout=tmo)
            if full:
                try:
                    sloz = w.client.sloz(timeout=tmo)
                    flightz = w.client.flightz(timeout=tmo)
                except WorkerRejected:  # pre-PR-20 worker: optional planes
                    sloz, flightz = {}, {}
            else:                     # slow planes: keep the last pull
                sloz, flightz = w.sloz, w.flightz
        except (WorkerGone, WorkerRejected, ValueError, KeyError) as e:
            w.errors += 1
            w.last_error = f"{type(e).__name__}: {e}"
            self._m["errors"].labels(self.cid, w.worker_id).inc()
            return
        w.worker_id = str(stats.get("worker_id") or w.client.url)
        w.role = str(stats.get("role") or "unknown")
        w.pid = stats.get("pid")
        if "now" in stats:
            # the worker's wall-anchored clock minus the round-trip
            # midpoint on OURS: subtracting this from a worker
            # timestamp lands it on the collector's axis, good to
            # ~RTT/2 — far inside a handoff's wall time
            w.offset = float(stats["now"]) - 0.5 * (t0 + t1)
        w.stats = stats
        w._text = text
        w._fams = None                # re-parsed lazily on next read
        w.requests = requests if isinstance(requests, list) else []
        w.sloz = sloz
        w.flightz = flightz
        w.last_ok = self._clock()
        w.last_error = None

    def _update_rates(self):
        total = 0.0
        for w in self._views:
            total += _scan_counter_total(w._text,
                                         "serving_tokens_emitted_total")
        t = self._clock()
        marks = self._tok_marks
        marks.append((t, total))
        while len(marks) > 2 and marks[0][0] < t - self.rate_window_s:
            marks.pop(0)
        dt = t - marks[0][0]
        self._tok_rate = (total - marks[0][1]) / dt if dt > 0 else 0.0
        chips = 0
        for w in self._views:
            st = (w.stats or {}).get("stats") or {}
            chips += max(int(st.get("tp_shards") or 1), 1)
        self._chips = max(chips, 1)
        self._m["tok_s"].set(self._tok_rate)
        self._m["tok_s_chip"].set(self._tok_rate / self._chips)

    # -- fleet SLO feed ------------------------------------------------------
    def _feed_slo(self):
        """Feed the fleet SLO engine from the merged request streams:
        one ttft observation per request (the first `first_token` any
        worker recorded) and one goodput observation per finished
        request, deduplicated across scrape cycles AND across workers
        so a migrated/handed-off request counts once fleet-wide.
        Observation timestamps are the ALIGNED event times, so burn
        windows are exact even when a scrape arrives late."""
        if not self._slo.objectives:
            return
        by_req = {}
        for w in self._views:
            for tr in w.requests:
                by_req.setdefault(
                    str(tr.get("request_id")), []).append((w, tr))
        for rid, pieces in by_req.items():
            first = None              # (aligned ts, ttft, pri, tenant)
            finish = None             # (aligned ts, tokens, pri, tenant)
            t_first = None
            for w, tr in pieces:
                pri = tr.get("priority")
                ten = tr.get("tenant")
                for ev in tr.get("events") or ():
                    ts = float(ev.get("ts", 0.0)) - w.offset
                    if ev.get("event") == "first_token":
                        if first is None or ts < first[0]:
                            first = (ts, ev.get("ttft"), pri, ten)
                        if t_first is None or ts < t_first:
                            t_first = ts
                    elif ev.get("event") == "finished" \
                            and tr.get("status") == "finished":
                        finish = (ts, ev.get("tokens"), pri, ten)
            if first is not None and first[1] is not None \
                    and (rid, "ttft") not in self._seen_slo:
                self._seen_slo.add((rid, "ttft"))
                self._slo.observe_ttft(float(first[1]),
                                       priority=first[2],
                                       tenant=first[3], t=first[0])
            if finish is not None and (rid, "finish") not in self._seen_slo:
                ts, tokens, pri, ten = finish
                t0 = t_first if t_first is not None else None
                if t0 is not None and tokens and int(tokens) > 1 \
                        and ts > t0:
                    self._seen_slo.add((rid, "finish"))
                    self._slo.observe_goodput(
                        (int(tokens) - 1) / (ts - t0),
                        priority=pri, tenant=ten, t=ts)
        if len(self._seen_slo) > 65536:   # bound across long soaks
            self._seen_slo.clear()

    # -- correlated fleet dump ----------------------------------------------
    def _mirror_worker_latches(self):
        for w in self._views:
            for reason in (w.flightz or {}).get("latched") or ():
                self.fleet_dump(f"worker:{w.worker_id}:{reason}",
                                {"worker": w.worker_id,
                                 "worker_reason": str(reason)})

    def fleet_dump(self, reason, detail=None):
        """Snapshot EVERY worker's last-scraped metrics + requests +
        flight state (plus the merged registry and the fleetz payload)
        into one directory — once per reason, like the per-process
        flight recorder's latch. Returns the path, or None when the
        reason already fired."""
        reason = str(reason)
        with self._lock:
            if reason in self._dumped:
                return None
            self._dumped.add(reason)
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "-"
                       for ch in reason)[:80]
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(self.out_dir,
                            f"fleet-{safe}-{stamp}-{os.getpid()}")
        path = base
        n = 0
        while os.path.exists(path) or os.path.exists(path + ".tmp"):
            n += 1
            path = f"{base}.{n}"
        tmp = path + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        merged_text = self.merged.render_prometheus()
        for w in self._views:
            wdir = os.path.join(tmp, "".join(
                ch if ch.isalnum() or ch in "-_" else "-"
                for ch in w.worker_id)[:60] or f"worker{w.index}")
            os.makedirs(wdir, exist_ok=True)
            with open(os.path.join(wdir, "metrics.prom"), "w") as f:
                f.write("".join(self._render_export(w.families)))
            for fname, obj in (("stats.json", w.stats),
                               ("requests.json", w.requests),
                               ("sloz.json", w.sloz),
                               ("flightz.json", w.flightz)):
                with open(os.path.join(wdir, fname), "w") as f:
                    json.dump(obj, f, indent=1, sort_keys=True,
                              default=str)
        with open(os.path.join(tmp, "merged.prom"), "w") as f:
            f.write(merged_text)
        with open(os.path.join(tmp, "trace.json"), "w") as f:
            json.dump(self.fleet_chrome_trace(), f)
        with open(os.path.join(tmp, "fleet.json"), "w") as f:
            json.dump({"reason": reason, "detail": detail,
                       "ts": time.time(), "fleetz": self.fleetz()},
                      f, indent=1, sort_keys=True, default=str)
        os.rename(tmp, path)
        self._m["dumps"].labels(self.cid, reason).inc()
        with self._lock:
            self._dump_paths.append(path)
        telemetry.flight.record("fleet_dump", collector=self.cid,
                                reason=reason, path=path)
        return path

    @staticmethod
    def _render_export(fams):
        """Re-render a parsed export (dump fidelity beats keeping the
        raw text around per worker)."""
        for name, f in sorted(fams.items()):
            yield f"# TYPE {name} {f['kind']}\n"
            for labels, value in f["samples"]:
                lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
                yield f"{name}{{{lab}}} {value:g}\n" if lab \
                    else f"{name} {value:g}\n"
            for h in f["hist"].values():
                lab = ",".join(f'{k}="{v}"'
                               for k, v in h["labels"].items())
                sep = "," if lab else ""
                for b, cum in sorted(zip(h["bounds"], h["cumulative"])):
                    le = "+Inf" if b == math.inf else "%g" % b
                    yield (f'{name}_bucket{{{lab}{sep}le="{le}"}}'
                           f" {cum:g}\n")
                suffix = f"{{{lab}}}" if lab else ""
                yield f"{name}_sum{suffix} {h['sum']:g}\n"
                yield f"{name}_count{suffix} {h['count']}\n"

    def rearm(self, reason=None):
        """Un-latch one correlated-dump reason (or all)."""
        with self._lock:
            if reason is None:
                self._dumped.clear()
            else:
                self._dumped.discard(str(reason))

    # -- views ---------------------------------------------------------------
    @property
    def merged(self):
        """The merged Registry over the most recent scrape cycle —
        rebuilt lazily and memoized per cycle. Readers (fleetz, dumps,
        `render_prometheus`) pay the parse + merge; the periodic
        scrape loop never does."""
        with self._lock:
            stamp = self._cycles
            if self._merge_stamp == stamp:
                return self._merged
        exports = [(w.worker_id, w.role, w.families)
                   for w in self._views if w._text]
        merged, conflicts = merge_exports(exports)
        with self._lock:
            self._merged = merged
            self._merge_conflicts = conflicts
            self._merge_stamp = stamp
            return self._merged

    @property
    def workers(self):
        return list(self._views)

    def render_prometheus(self):
        return self.merged.render_prometheus()

    def fleet_chrome_trace(self):
        """ONE Perfetto trace over the whole fleet: every worker's
        last-scraped timelines, clock-aligned, one process track per
        worker pid (see module docstring)."""
        snaps = []
        for w in self._views:
            snaps.append({"worker_id": w.worker_id, "role": w.role,
                          "pid": w.pid, "offset": w.offset,
                          "requests": w.requests})
        return fleet_chrome_trace(snaps, collector=self.cid)

    def fleetz(self):
        """The /fleetz payload: per-worker health + identity + steady
        compiles, fleet throughput at the current merged p99, scrape
        staleness, the fleet SLO snapshot, correlated dumps."""
        now = self._clock()
        merged = self.merged
        rows = []
        for w in self._views:
            st = (w.stats or {}).get("stats") or {}
            eng = (w.stats or {}).get("engine") or {}
            rows.append({
                "worker_id": w.worker_id, "role": w.role,
                "pid": w.pid, "url": w.client.url,
                "state": "stale" if w.stale else "ok",
                "scrape_age_s": (now - w.last_ok)
                if w.last_ok is not None else None,
                "scrape_errors": w.errors,
                "last_error": w.last_error,
                "clock_offset_s": w.offset,
                "draining": (w.stats or {}).get("draining"),
                "weight_dtype": eng.get("weight_dtype"),
                "kv_dtype": eng.get("kv_dtype"),
                "steady_state_compiles": st.get("steady_state_compiles"),
                "handoffs": (w.stats or {}).get("handoffs"),
                "flight_latched": (w.flightz or {}).get("latched") or [],
            })
        p99_ms = None
        ttft = merged.get("serving_ttft_seconds")
        if ttft is not None:
            merged_ttft = None
            for _values, child in ttft._samples():
                if merged_ttft is None:
                    merged_ttft = Histogram(
                        "_fleetz_ttft", buckets=child.buckets)
                merged_ttft.merge(child)
            if merged_ttft is not None and merged_ttft.count:
                p99_ms = merged_ttft.percentile(99) * 1e3
        with self._lock:
            cycles = self._cycles
            conflicts = list(self._merge_conflicts)
            dumps = list(self._dump_paths)
        out = {
            "collector": self.cid,
            "now": now,
            "interval_s": self.interval_s,
            "cycles": cycles,
            "workers": rows,
            "fleet": {
                "workers_total": len(self._views),
                "workers_stale": sum(w.stale for w in self._views),
                "chips": self._chips,
                "tokens_per_sec": self._tok_rate,
                "tokens_per_sec_per_chip": self._tok_rate
                / max(self._chips, 1),
                "ttft_p99_ms": p99_ms,
            },
            "slo": self._slo.snapshot(self._clock()),
            "fleet_dumps": dumps,
            "merge_conflicts": conflicts,
        }
        if self.router is not None:
            out["router"] = {
                "router": self.router._rid,
                "disaggregated": self.router.disaggregated,
                "workers_up": sum(r.state == "up"
                                  for r in self.router.workers),
            }
        return out

    # -- SLO surface ---------------------------------------------------------
    @property
    def slo_engine(self):
        return self._slo


def _unique_pid(pid, used, index):
    """Track pid for one worker: its real OS pid when free — in-process
    test fleets share one pid, so collisions fall back to a derived,
    stable id (the trace args keep the real pid)."""
    cand = int(pid) if pid else 1000000 + index
    while cand in used:
        cand = cand * 10 + index + 1
    used.add(cand)
    return cand


def fleet_chrome_trace(worker_snaps, collector=""):
    """Assemble per-worker timeline snapshots into ONE Chrome/Perfetto
    trace: `worker_snaps` is [{"worker_id", "role", "pid", "offset",
    "requests": [timeline dict, ...]}]. Each worker becomes one
    process track (pid = the worker's OS pid); every timeline's
    timestamps are shifted by -offset onto the collector's clock
    before emission, so spans of one `trace_id` that crossed processes
    (prefill → handoff → decode) line up on one consistent axis."""
    from ...telemetry.request_trace import chrome_trace
    events = []
    used = set()
    offsets = {}
    for i, snap in enumerate(worker_snaps):
        reqs = [_align_timeline(tr, snap.get("offset") or 0.0)
                for tr in snap.get("requests") or ()]
        if not reqs:
            continue
        sub = chrome_trace(requests=reqs, spans=[])["traceEvents"]
        pid = _unique_pid(snap.get("pid"), used, i)
        wid = snap.get("worker_id", f"worker{i}")
        offsets[str(wid)] = snap.get("offset") or 0.0
        pname = (f"worker {wid} ({snap.get('role', '?')}) "
                 f"pid {snap.get('pid')}")
        for ev in sub:
            ev = dict(ev, pid=pid)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev["args"] = {"name": pname}
            events.append(ev)
    events.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                               e.get("ts", 0.0)))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {
                "exporter": "mx.serving.fleet.observe.fleet_chrome_trace",
                "collector": str(collector),
                "clock": "per-worker wall-anchored clocks aligned onto "
                         "the collector's axis (offset = worker now - "
                         "scrape round-trip midpoint)",
                "clock_offsets_s": offsets,
            }}


def _align_timeline(tr, offset):
    """Shift one timeline dict onto the collector's clock: absolute
    timestamps (t_begin, t_end, event ts) move by -offset; durations
    and phase budgets are differences and stay untouched."""
    out = dict(tr)
    if out.get("t_begin") is not None:
        out["t_begin"] = float(out["t_begin"]) - offset
    if out.get("t_end") is not None:
        out["t_end"] = float(out["t_end"]) - offset
    evs = []
    for ev in out.get("events") or ():
        ev = dict(ev)
        if "ts" in ev:
            ev["ts"] = float(ev["ts"]) - offset
        evs.append(ev)
    out["events"] = evs
    return out
