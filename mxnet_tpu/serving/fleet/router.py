"""FleetRouter: place requests across worker PROCESSES and survive
their deaths.

This is PR 8's in-process ServingRouter taken out of process: replicas
are `WorkerClient` stubs over HTTP instead of engines in the same
interpreter, so every interaction — placement, streaming, failover,
the disaggregated prefill->decode handoff — crosses the wire format
(fleet/wire.py). The router duck-types the ServingFrontend backend
protocol (submit/cancel/step/has_work/estimated_drain_wait), so a
frontend can serve a whole fleet on one ingress port and the existing
chaos-soak machinery drives real subprocesses unchanged.

Placement is rendezvous hashing over the prompt head (+ adapter id):
each request ranks every eligible worker by crc32(affinity_key + "/" +
worker_index) — sticky for prefix-cache affinity, stable under
membership churn (a worker's death reshuffles only ITS requests).

Two topologies:

* **Mixed** — every worker runs prefill + decode. A request streams
  from its affinity worker; optional pre-first-token hedging races a
  second worker and cancels the loser (deterministic generation makes
  the race safe — both would emit identical tokens).
* **Disaggregated** — prompts go to `prefill` workers, which run to
  the first token and export WITH the KV page payload; the router
  ships the blob to a `decode` worker, which scatters the pages in
  and streams the continuation. Client tokens are withheld until the
  decode worker acks adoption, so client TTFT includes the handoff.

Failure contract: a worker death mid-request (connection loss, EOF
before `done`) marks the replica down and re-places the request as a
restart blob synthesized from the ROUTER's own record — prompt,
received tokens, the trace stitch, and a natural-grid `kv_history`
(the dead process cannot be asked how it chunked, and on the natural
grid the schedule is deterministic — the int8 replay contract needs
it). The survivor adopts and continues bit-identically; the client
stream never breaks and the request's timeline reads as ONE stitched
trace.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
import zlib

import numpy as np

from ...base import MXNetError
from ... import telemetry
from ..scheduler import (Request, RejectedError, QueueFullError,
                         ShedError, TERMINAL_STATUSES)
from . import wire
from .client import WorkerClient, WorkerGone, WorkerRejected

__all__ = ["FleetRouter"]

_router_ids = itertools.count()
_R = ("router",)


def _fleet_metrics(rid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    placements = c(
        "fleet_placements_total",
        "requests placed on a worker, by placement kind (affinity = "
        "rendezvous first choice, spill = first choice rejected, "
        "failover = re-placed after a worker death, hedge = "
        "speculative second stream)", ("router", "kind"))
    hedges = c(
        "fleet_hedges_total",
        "pre-first-token hedges by outcome (fired = second stream "
        "opened, won = hedge delivered first, lost = primary "
        "delivered first)", ("router", "outcome"))
    return {
        "workers_up": g(
            "fleet_workers_up",
            "worker processes currently considered up by the router's "
            "health watchdog", _R).labels(rid),
        "deaths": c(
            "fleet_worker_deaths_total",
            "up->down transitions observed (connection loss mid-RPC "
            "or failed health probes)", _R).labels(rid),
        "failovers": c(
            "fleet_failovers_total",
            "requests re-placed onto a survivor after a worker died "
            "mid-flight (the restart blob preserves bit-identity)",
            _R).labels(rid),
        "handoffs": c(
            "fleet_handoffs_total",
            "disaggregated prefill->decode handoffs the router "
            "brokered", _R).labels(rid),
        "handoff_s": h(
            "fleet_handoff_seconds",
            "prefill export stamp -> decode adoption ack, as the "
            "router observes it (the wall-clock cost disaggregation "
            "adds to TTFT)", _R).labels(rid),
        "placements": placements,
        "hedges": hedges,
    }


class _Replica:
    """One worker process as the router sees it."""

    def __init__(self, index, client, info):
        self.index = index
        self.client = client
        self.state = "up"
        self.down_reason = None
        self.refresh(info)

    def refresh(self, info):
        self.info = info
        self.worker_id = info.get("worker_id")
        self.role = info.get("role", "mixed")
        eng = info.get("engine") or {}
        self.chunk_tokens = int(eng.get("chunk_tokens") or 0)

    def eligible(self, want):
        if self.state != "up":
            return False
        if want == "prefill":
            return self.role in ("prefill", "mixed")
        if want == "decode":
            return self.role in ("decode", "mixed")
        return True

    def __repr__(self):
        return (f"_Replica({self.index}, {self.client.url}, "
                f"{self.role}, {self.state})")


class _Track:
    """Router-side record of one in-flight request — the source of
    truth a failover rebuilds from."""

    def __init__(self, req, trace_id, t_begin):
        self.req = req
        self.trace_id = trace_id
        self.t_begin = t_begin
        self.rep = None
        self.error = None
        self.stream_error = None
        self.t_first = None
        self.done = threading.Event()


class FleetRouter:
    """Route requests across fleet worker processes (see module
    docstring). `workers` is a list of base URLs or WorkerClient
    instances; every worker must speak this build's WIRE_VERSION and
    (for bit-identical failover) share one chunk grid."""

    def __init__(self, workers, *, affinity_tokens=8,
                 hedge_after_s=None, max_failovers=3,
                 watchdog_interval_s=0.25, prefill_rpc_timeout_s=150.0,
                 rid=None):
        if not workers:
            raise MXNetError("FleetRouter needs at least one worker")
        self._rid = str(rid) if rid is not None else \
            str(next(_router_ids))
        self.affinity_tokens = int(affinity_tokens)
        self.hedge_after_s = None if hedge_after_s is None \
            else float(hedge_after_s)
        self.max_failovers = int(max_failovers)
        self.prefill_rpc_timeout_s = float(prefill_rpc_timeout_s)
        self._m = _fleet_metrics(self._rid)
        self._lock = threading.Lock()
        self._live = {}             # request id -> _Track
        self._collector = None      # FleetCollector via observe()
        self._closed = False
        self._reps = []
        for i, w in enumerate(workers):
            client = w if isinstance(w, WorkerClient) else WorkerClient(w)
            info = client.stats()
            if info.get("wire_version") != wire.WIRE_VERSION:
                raise MXNetError(
                    f"worker {client.url} speaks wire_version "
                    f"{info.get('wire_version')!r}, this router speaks "
                    f"{wire.WIRE_VERSION} — refusing to build a fleet "
                    "that cannot migrate requests")
            self._reps.append(_Replica(i, client, info))
        grids = {r.chunk_tokens for r in self._reps if r.chunk_tokens}
        if len(grids) > 1:
            raise MXNetError(
                f"workers disagree on chunk_tokens {sorted(grids)}: "
                "bit-identical failover replays the dead worker's "
                "write schedule on the natural grid, which requires "
                "ONE grid fleet-wide")
        self._chunk_tokens = grids.pop() if grids else 0
        self._disagg = any(r.role != "mixed" for r in self._reps)
        if self._disagg:
            for want in ("prefill", "decode"):
                if not any(r.eligible(want) for r in self._reps):
                    raise MXNetError(
                        f"disaggregated fleet has no {want}-capable "
                        "worker")
        self._m["workers_up"].set(len(self._reps))
        self._watchdog_interval_s = float(watchdog_interval_s)
        self._watchdog = threading.Thread(
            target=self._watch, daemon=True,
            name=f"mx-fleet-watchdog:{self._rid}")
        self._watchdog.start()
        telemetry.flight.record(
            "fleet_router_up", router=self._rid,
            workers=len(self._reps), disagg=self._disagg)

    # -- lifecycle ---------------------------------------------------------
    @property
    def workers(self):
        return list(self._reps)

    @property
    def disaggregated(self):
        return self._disagg

    def observe(self, **kw):
        """Build + start a `FleetCollector` over this router's workers
        (fleet/observe.py): the scrape-merge loop, the fleet SLO
        engine, correlated fleet dumps, and the /fleetz payload on
        this process's introspection server. Keyword args pass through
        to the collector (interval_s, objectives, out_dir, ...); the
        router closes it with itself."""
        if self._collector is not None:
            return self._collector
        from .observe import FleetCollector
        self._collector = FleetCollector(
            [r.client for r in self._reps], router=self, **kw)
        return self._collector.start()

    @property
    def collector(self):
        return self._collector

    def close(self):
        self._closed = True
        if self._collector is not None:
            self._collector.close()
            self._collector = None
        with self._lock:
            live = list(self._live.values())
        for tr in live:
            st = getattr(tr.req, "stream", None)
            if st is not None:
                st.close("aborted")
            tr.done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _watch(self):
        while not self._closed:
            time.sleep(self._watchdog_interval_s)
            up = 0
            for rep in self._reps:
                ok = rep.client.healthz()
                if ok and rep.state == "down":
                    # rejoin: refresh its declared shape first
                    try:
                        rep.refresh(rep.client.stats())
                    except (WorkerGone, WorkerRejected):
                        ok = False
                    else:
                        rep.state = "up"
                        rep.down_reason = None
                        telemetry.flight.record(
                            "fleet_worker_rejoined", router=self._rid,
                            worker=rep.index)
                elif not ok and rep.state == "up":
                    self._replica_down(rep, "health probe failed")
                up += rep.state == "up"
            self._m["workers_up"].set(up)

    def _replica_down(self, rep, reason):
        if rep.state == "down":
            return
        rep.state = "down"
        rep.down_reason = reason
        self._m["deaths"].inc()
        self._m["workers_up"].set(
            sum(r.state == "up" for r in self._reps))
        telemetry.flight.record(
            "fleet_worker_down", router=self._rid, worker=rep.index,
            reason=str(reason)[:200])

    # -- ServingFrontend backend protocol ----------------------------------
    @property
    def has_work(self):
        with self._lock:
            return bool(self._live)

    def step(self):
        return []                   # workers own their serving loops

    def estimated_drain_wait(self):
        return None

    def submit(self, request):
        """Admit and start routing one Request. Mixed fleets get a
        synchronous admission verdict (a worker rejection re-raises
        here as the engine-shaped QueueFullError/ShedError, so an
        ingress frontend keeps its 429/503 contract); disaggregated
        fleets admit at the prefill worker inside the runner thread
        and surface rejections on the request's stream/status."""
        if self._closed:
            raise MXNetError("router is closed")
        req = request
        t = dict(getattr(req, "trace", None) or {})
        t.setdefault("trace_id", telemetry.new_trace_id())
        t.setdefault("t_begin", telemetry.request_trace.now())
        req.trace = t
        track = _Track(req, t["trace_id"], t["t_begin"])
        if not isinstance(getattr(req, "phases", None), dict):
            req.phases = {}
        if self._disagg:
            runner, args = self._run_disagg, ()
        else:
            sse, rep, kind = self._open_generate(track)
            self._m["placements"].labels(self._rid, kind).inc()
            track.rep = rep
            runner, args = self._run_mixed, (sse, rep)
        with self._lock:
            self._live[req.id] = track
        threading.Thread(
            target=self._guard, args=(runner, track) + args,
            daemon=True,
            name=f"mx-fleet-run:{self._rid}:{req.id}").start()
        return req

    def cancel(self, request_id):
        with self._lock:
            track = self._live.get(request_id)
        if track is None:
            return False
        self._cancel_on_worker(track)
        return True

    # -- public conveniences ----------------------------------------------
    def result(self, request, timeout=None):
        """Block until `request` (a Request previously submitted)
        reaches a terminal status; returns it."""
        with self._lock:
            track = self._live.get(request.id)
        if track is not None and not track.done.wait(timeout):
            raise MXNetError(f"request {request.id} still in flight "
                             f"after {timeout}s")
        return request

    def fleet_stats(self):
        out = {"router": self._rid, "disaggregated": self._disagg,
               "workers": []}
        for rep in self._reps:
            entry = {"index": rep.index, "url": rep.client.url,
                     "state": rep.state, "role": rep.role,
                     "down_reason": rep.down_reason}
            if rep.state == "up":
                try:
                    entry["stats"] = rep.client.stats()
                except (WorkerGone, WorkerRejected):
                    pass
            out["workers"].append(entry)
        return out

    # -- placement ---------------------------------------------------------
    def _order(self, req, want, exclude=()):
        """Rendezvous order over eligible up workers: stable per
        (prompt head, adapter), uniform across requests."""
        key = np.asarray(req.prompt[:self.affinity_tokens],
                         np.int32).tobytes()
        key += f"|{req.adapter_id or ''}".encode("utf-8")
        cands = [r for r in self._reps
                 if r.eligible(want) and r.index not in exclude]
        return sorted(
            cands, reverse=True,
            key=lambda r: zlib.crc32(key + b"/%d" % r.index))

    def _open_generate(self, track, exclude=()):
        """Open the primary stream on the best eligible worker;
        spill down the rendezvous order on structured rejection, mark
        down and keep going on connection failure. All-rejected
        re-raises the least-loaded rejection engine-shaped."""
        req = track.req
        rejections = []
        tp = telemetry.format_traceparent(track.trace_id)
        for i, rep in enumerate(self._order(req, "any", exclude)):
            try:
                sse = rep.client.generate(self._body_of(req),
                                          traceparent=tp)
                return sse, rep, ("affinity" if i == 0 and not exclude
                                  else "spill")
            except WorkerGone as e:
                self._replica_down(rep, str(e))
            except WorkerRejected as e:
                rejections.append(e)
        raise self._admission_error(rejections)

    @staticmethod
    def _admission_error(rejections):
        if not rejections:
            return ShedError("no fleet workers available",
                             reason="no_workers")
        best = min(rejections,
                   key=lambda e: e.retry_after_s
                   if e.retry_after_s is not None else float("inf"))
        kw = dict(reason=best.reason, queue_depth=best.queue_depth,
                  active_slots=best.active_slots,
                  retry_after_s=best.retry_after_s)
        cls = QueueFullError if best.code == 429 else ShedError
        return cls(str(best), **kw)

    def _body_of(self, req):
        body = {"prompt": [int(t) for t in req.prompt],
                "max_new_tokens": int(req.max_new_tokens),
                "request_id": req.id,
                "do_sample": bool(req.do_sample),
                "temperature": float(req.temperature),
                "top_k": int(req.top_k), "top_p": float(req.top_p),
                "seed": int(req.seed), "stream": True}
        for k in ("eos_token_id", "priority", "deadline_ms",
                  "adapter_id", "tenant"):
            v = getattr(req, k, None)
            if v is not None:
                body[k] = v
        return body

    # -- the runner threads ------------------------------------------------
    def _guard(self, runner, track, *args):
        try:
            runner(track, *args)
        except Exception as e:      # noqa: BLE001 — never leak a hang
            self._finish(track, "failed", error=e)

    def _run_mixed(self, track, sse, rep):
        req = track.req
        attempts = 0
        base = 0
        while True:
            try:
                if base == 0 and self.hedge_after_s is not None \
                        and not req.output_tokens:
                    status = self._consume_hedged(track, sse, rep)
                else:
                    status = self._consume(track, iter(sse), base)
                self._finish(track, status)
                return
            except WorkerGone as e:
                sse.close()
                self._replica_down(rep, str(e))
                attempts += 1
                if attempts > self.max_failovers or self._closed:
                    self._finish(track, "failed", error=e)
                    return
                self._m["failovers"].inc()
                got = self._adopt_once(
                    track, self._restart_blob(track), "any",
                    kind="failover")
                if got is None:
                    self._finish(track, "failed",
                                 error=track.error or e)
                    return
                rep, sse = got
                track.rep = rep
                base = len(req.output_tokens)

    def _run_disagg(self, track):
        req = track.req
        tp = telemetry.format_traceparent(track.trace_id)
        blob = None
        rejections = []
        for i, rep in enumerate(self._order(req, "prefill")):
            try:
                blob = rep.client.prefill(
                    self._body_of(req), traceparent=tp,
                    timeout=self.prefill_rpc_timeout_s)
                track.rep = rep
                self._m["placements"].labels(
                    self._rid, "affinity" if i == 0 else "spill").inc()
                break
            except WorkerGone as e:
                # nothing streamed yet — a prefill retry elsewhere is
                # a plain deterministic resubmit
                self._replica_down(rep, str(e))
            except WorkerRejected as e:
                rejections.append(e)
        if blob is None:
            err = self._admission_error(rejections)
            self._finish(track,
                         "shed" if rejections else "failed", error=err)
            return
        # tokens the prefill produced: withheld until the decode
        # worker acks adoption, so the client's TTFT includes the
        # handoff (the trace's phase budget says the same thing)
        held = [int(t) for t in blob.get("output_tokens", [])]
        for k, v in (blob.get("phases") or {}).items():
            req.phases[str(k)] = float(v)
        if blob.get("final"):
            self._deliver(track, held[len(req.output_tokens):])
            self._finish(track, str(blob.get("status") or "finished"))
            return
        cur_blob = blob
        attempts = 0
        kind = "affinity"
        while True:
            got = self._adopt_once(track, cur_blob, "decode", kind=kind)
            if got is None:
                self._finish(track, "failed",
                             error=track.error
                             or MXNetError("no decode workers"))
                return
            rep, sse = got
            track.rep = rep
            it = iter(sse)
            try:
                ev, data = next(it)
                if ev == "adopted":
                    kvp = cur_blob.get("kv_payload")
                    if kvp is not None:
                        self._m["handoff_s"].observe(max(
                            0.0, telemetry.request_trace.now()
                            - float(kvp["t_export"])))
                    self._m["handoffs"].inc()
                    if held:
                        self._deliver(
                            track, held[len(req.output_tokens):])
                        held = []
                    status = self._consume(track, it,
                                           len(req.output_tokens))
                else:
                    st = self._apply_event(track, ev, data,
                                           len(req.output_tokens))
                    status = st if st is not None else self._consume(
                        track, it, len(req.output_tokens))
                self._finish(track, status)
                return
            except WorkerGone as e:
                sse.close()
                self._replica_down(rep, str(e))
                attempts += 1
                if attempts > self.max_failovers or self._closed:
                    self._finish(track, "failed", error=e)
                    return
                self._m["failovers"].inc()
                kind = "failover"
                if held:
                    # died before the adoption ack: nothing reached
                    # the client, the exported payload is still the
                    # exact continuation — re-ship the SAME blob
                    continue
                # decode had progressed: the payload is stale (its
                # cursor predates tokens the client already has) —
                # rebuild as a replay restart from the router's record
                cur_blob = self._restart_blob(track)

    # -- stream consumption ------------------------------------------------
    def _deliver(self, track, new):
        """Append NEW tokens (callers have already trimmed overlap)
        to the record and the client stream."""
        req = track.req
        if not new:
            return
        if track.t_first is None:
            track.t_first = telemetry.request_trace.now()
        req.output_tokens.extend(new)
        st = getattr(req, "stream", None)
        if st is not None and not st.emit(new):
            # slow client: mirror the engine's overflow policy —
            # cancel at the source rather than buffer unboundedly
            self._cancel_on_worker(track)

    def _apply_event(self, track, ev, data, base):
        """Fold one SSE event into the track. Returns the terminal
        status on `done`, else None. Token indices are re-based and
        de-overlapped, so replays from a failover or hedge are
        harmless."""
        req = track.req
        if ev == "tokens" and isinstance(data, dict):
            gidx = base + int(data.get("index", 0))
            toks = [int(t) for t in data.get("tokens", [])]
            have = len(req.output_tokens)
            if gidx > have:
                raise WorkerGone(
                    f"worker skipped ahead (index {gidx}, have {have})")
            # overlap with what a prior stream already delivered (a
            # failover/hedge replay) is trimmed, never re-emitted
            self._deliver(track, toks[have - gidx:]
                          if have > gidx else toks)
        elif ev == "error" and isinstance(data, dict):
            track.stream_error = data
        elif ev == "done":
            data = data if isinstance(data, dict) else {}
            for k, v in (data.get("phases") or {}).items():
                req.phases[str(k)] = float(v)
            status = str(data.get("status") or "finished")
            if status not in TERMINAL_STATUSES:
                # "exported"/"aborted": the worker let go of the
                # request without finishing it — re-place
                raise WorkerGone(f"worker released the request "
                                 f"({status})")
            return status
        return None

    def _consume(self, track, events, base):
        for ev, data in events:
            status = self._apply_event(track, ev, data, base)
            if status is not None:
                return status
        raise WorkerGone("stream ended without a done event")

    def _consume_hedged(self, track, sse, rep):
        """Pre-first-token hedging: if the primary stays silent for
        hedge_after_s, open the SAME request on the next-ranked
        worker and let the first tokens event win; the loser is
        cancelled at its source. Safe because generation is
        deterministic — both streams would emit identical tokens."""
        req = track.req
        q = queue.Queue()
        streams = {0: (sse, rep)}
        dead = set()
        winner = None
        hedged = False

        def pump(tag, s):
            def run():
                try:
                    for item in s:
                        q.put((tag,) + item)
                    q.put((tag, "__eof__", None))
                except WorkerGone as e:
                    q.put((tag, "__gone__", e))
            threading.Thread(
                target=run, daemon=True,
                name=f"mx-fleet-pump:{req.id}:{tag}").start()

        pump(0, sse)
        deadline = time.monotonic() + self.hedge_after_s
        while True:
            try:
                if winner is None and not hedged:
                    tag, ev, data = q.get(
                        timeout=max(0.0, deadline - time.monotonic()))
                else:
                    tag, ev, data = q.get()
            except queue.Empty:
                hedged = True
                try:
                    order = self._order(req, "any",
                                        exclude={rep.index})
                    if order:
                        s2 = order[0].client.generate(
                            self._body_of(req),
                            traceparent=telemetry.format_traceparent(
                                track.trace_id))
                        streams[1] = (s2, order[0])
                        pump(1, s2)
                        self._m["hedges"].labels(
                            self._rid, "fired").inc()
                        self._m["placements"].labels(
                            self._rid, "hedge").inc()
                except (WorkerGone, WorkerRejected):
                    pass
                continue
            if ev == "__eof__":
                continue
            if ev == "__gone__":
                dead.add(tag)
                if tag == winner or dead >= set(streams):
                    for t, (s, _r) in streams.items():
                        if t not in dead:
                            s.close()
                    raise data
                self._replica_down(streams[tag][1], str(data))
                continue
            if winner is None and ev == "tokens" \
                    and isinstance(data, dict) and data.get("tokens"):
                winner = tag
                track.rep = streams[tag][1]
                if hedged and 1 in streams:
                    self._m["hedges"].labels(
                        self._rid, "won" if tag == 1 else "lost").inc()
                for t, (s, r) in streams.items():
                    if t != winner and t not in dead:
                        s.close()
                        try:
                            r.client.cancel(req.id)
                        except (WorkerGone, WorkerRejected):
                            pass
            if winner is not None and tag != winner:
                continue
            status = self._apply_event(track, ev, data, 0)
            if status is not None:
                return status

    # -- failover plumbing -------------------------------------------------
    def _restart_blob(self, track):
        """Rebuild the migration blob from the router's OWN record —
        the dead worker cannot be asked. `kv_history` is synthesized
        on the natural chunk grid over the prompt (how every fleet
        engine feeds a fresh admission), which the int8 replay
        contract needs to regenerate identical KV codes; emitted
        tokens replay as 1-token writes, exactly how decode wrote
        them."""
        req = track.req
        blob = wire.encode_request(req)
        blob["status"] = "exported"
        blob["kv_payload"] = None
        blob["kv_attach"] = 0
        blob["trace"] = {"trace_id": track.trace_id,
                         "t_begin": track.t_begin}
        hist, left = [], int(req.prompt_len)
        chunk = self._chunk_tokens or left
        while left > 0:
            hist.append(min(chunk, left))
            left -= hist[-1]
        blob["kv_history"] = hist
        return blob

    def _adopt_once(self, track, blob, want, kind):
        """Ship a blob to the best eligible worker and open the
        continuation stream. Marks connection-dead targets down and
        keeps walking the order; structured rejections (incl. the 409
        wire-version refusal) land on track.error."""
        req = track.req
        for i, rep in enumerate(self._order(req, want)):
            try:
                sse = rep.client.adopt(blob)
                self._m["placements"].labels(
                    self._rid,
                    kind if kind == "failover"
                    else ("affinity" if i == 0 else "spill")).inc()
                return rep, sse
            except WorkerGone as e:
                self._replica_down(rep, str(e))
            except WorkerRejected as e:
                track.error = e
        return None

    def _cancel_on_worker(self, track):
        rep = track.rep
        if rep is None:
            return
        try:
            rep.client.cancel(track.req.id)
        except (WorkerGone, WorkerRejected):
            pass

    def _finish(self, track, status, error=None):
        req = track.req
        if error is not None:
            track.error = error
        req.status = status if status in TERMINAL_STATUSES else "failed"
        st = getattr(req, "stream", None)
        if st is not None:
            st.close(req.status)
        with self._lock:
            self._live.pop(req.id, None)
        track.done.set()
