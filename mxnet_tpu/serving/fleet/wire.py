"""Versioned wire format for cross-process request migration.

Everything the in-process migration contract moves on a `Request`
(engine.export_requests / engine.adopt — prompt + emitted tokens, the
sampling knobs that feed the per-request RNG, `kv_history` for the
int8 replay contract, the trace stitch {trace_id, t_begin}, accumulated
TTFT phases) plus the optional cross-process KV handoff payload
(engine.export_handoff — the request's used KV pages and decode-cursor
scalars) is serialised to a JSON-safe dict here, byte-for-byte
recoverable. The encoding is deliberately boring: JSON with ndarrays
as {dtype, shape, base64} triples, so any worker build can at least
*parse* a blob from any other build and reject it with a structured
error when the schema version does not match.

Version discipline: `WIRE_VERSION` bumps on any change to the blob
layout. A worker adopting a blob with a mismatched version must refuse
with `WireVersionError` (the fleet worker maps it to HTTP 409 with a
structured body) — adopting a half-understood blob would corrupt KV
state silently, which is strictly worse than failing the handoff and
letting the router fall back to the replay restart.
"""
from __future__ import annotations

import base64
import json

import numpy as np

from ...base import MXNetError
from ..scheduler import Request

__all__ = ["WIRE_VERSION", "WireVersionError", "encode_request",
           "decode_request", "dumps", "loads"]

#: Schema version of the migration blob. Bump on ANY layout change.
WIRE_VERSION = 1


class WireVersionError(MXNetError):
    """A blob whose `wire_version` this build does not speak. The
    receiver must reject (structurally, not by guessing) — the sender
    falls back to the replay restart, which is bit-identical anyway."""

    def __init__(self, got, want=WIRE_VERSION):
        super().__init__(
            f"wire schema version {got!r} != {want}: refusing to adopt "
            "(a mismatched worker rejects rather than risk corrupting "
            "KV state)")
        self.got = got
        self.want = want


def _nd_enc(arr):
    a = np.ascontiguousarray(arr)
    return {"__nd__": {
        "dtype": a.dtype.str,
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }}


def _nd_dec(obj):
    nd = obj["__nd__"]
    a = np.frombuffer(base64.b64decode(nd["data"]),
                      dtype=np.dtype(nd["dtype"]))
    return a.reshape([int(s) for s in nd["shape"]]).copy()


def encode_request(req):
    """Request -> JSON-safe dict covering the full migration contract.
    `kv_payload` (set by engine.export_handoff) rides along when
    present; `req.stream` and engine-local clock fields (`t_submit`,
    deadlines in the submitting process's clock domain) deliberately
    do not — clocks do not ship across processes, and the adopting
    side re-derives its own."""
    d = {
        "wire_version": WIRE_VERSION,
        "id": str(req.id),
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "do_sample": bool(req.do_sample),
        "temperature": float(req.temperature),
        "top_k": int(req.top_k),
        "top_p": float(req.top_p),
        "seed": int(req.seed),
        "eos_token_id": (None if req.eos_token_id is None
                         else int(req.eos_token_id)),
        "priority": int(req.priority),
        "deadline_ms": (None if req.deadline_ms is None
                        else float(req.deadline_ms)),
        "adapter_id": req.adapter_id,
        "tenant": req.tenant,
        "status": str(req.status),
        "output_tokens": [int(t) for t in req.output_tokens],
        "phases": {str(k): float(v)
                   for k, v in (req.phases or {}).items()},
        "trace": dict(req.trace) if req.trace else None,
        "kv_history": [int(c) for c in (req.kv_history or [])],
        "kv_attach": int(getattr(req, "kv_attach", 0) or 0),
        "kv_payload": None,
    }
    kvp = getattr(req, "kv_payload", None)
    if kvp is not None:
        d["kv_payload"] = {
            "length": int(kvp["length"]),
            "cur_tok": int(kvp["cur_tok"]),
            "remaining": int(kvp["remaining"]),
            "counters": int(kvp["counters"]),
            "t_export": float(kvp["t_export"]),
            "pages": [{name: _nd_enc(leaf)
                       for name, leaf in page.items()}
                      for page in kvp["pages"]],
        }
    return d


def decode_request(d):
    """JSON-safe dict -> Request, the exact inverse of
    encode_request: re-encoding the result yields an equal dict (the
    round-trip tests pin this byte-for-byte, base64 payloads
    included). Raises WireVersionError on a version mismatch."""
    check_version(d)
    req = Request(
        d["prompt"], d["max_new_tokens"], request_id=d["id"],
        do_sample=d.get("do_sample", False),
        temperature=d.get("temperature", 1.0),
        top_k=d.get("top_k", 0), top_p=d.get("top_p", 1.0),
        seed=d.get("seed", 0), eos_token_id=d.get("eos_token_id"),
        priority=d.get("priority", 1),
        deadline_ms=d.get("deadline_ms"),
        adapter_id=d.get("adapter_id"), tenant=d.get("tenant"),
        trace=dict(d["trace"]) if d.get("trace") else None)
    req.status = d.get("status", "exported")
    # engine-local bookkeeping submit() would normally create: the
    # recorded instants are another process's clock, so adoption
    # starts them fresh here
    req.token_times = []
    req.output_tokens = [int(t) for t in d.get("output_tokens", [])]
    req.phases = {str(k): float(v)
                  for k, v in (d.get("phases") or {}).items()}
    req.kv_history = [int(c) for c in (d.get("kv_history") or [])]
    req.kv_attach = int(d.get("kv_attach", 0) or 0)
    kvp = d.get("kv_payload")
    if kvp is not None:
        req.kv_payload = {
            "length": int(kvp["length"]),
            "cur_tok": int(kvp["cur_tok"]),
            "remaining": int(kvp["remaining"]),
            "counters": int(kvp["counters"]),
            "t_export": float(kvp["t_export"]),
            "pages": [{name: _nd_dec(leaf)
                       for name, leaf in page.items()}
                      for page in kvp["pages"]],
        }
    return req


def check_version(d):
    if not isinstance(d, dict):
        raise WireVersionError(None)
    if d.get("wire_version") != WIRE_VERSION:
        raise WireVersionError(d.get("wire_version"))


def dumps(d):
    """Blob dict -> canonical bytes (sorted keys, so equal dicts give
    equal bytes — the round-trip tests compare at this layer)."""
    return json.dumps(d, sort_keys=True).encode("utf-8")


def loads(raw):
    """Bytes -> blob dict, with the version checked before anything
    downstream trusts the layout."""
    try:
        d = json.loads(raw)
    except (ValueError, TypeError) as e:
        raise MXNetError(f"malformed wire blob: {e}")
    check_version(d)
    return d
