"""Fleet worker: one ServingEngine behind a control-plane HTTP server.

`FleetWorker` subclasses `ServingFrontend` — it keeps the whole data
plane (`POST /v1/generate` SSE streaming, /healthz, /readyz, /metrics,
the serving-loop thread that owns the engine) and adds the fleet
control plane on the SAME port:

    GET  /fleet/stats     role, wire version, engine stats (including
                          chunk_tokens and steady_state_compiles — the
                          router reads both)
    GET  /fleet/requests  this engine's recent request timelines (the
                          soak verifies stitched traces here)
    POST /fleet/prefill   submit, run prefill to the first token, then
                          export WITH the KV page payload -> wire blob
    POST /fleet/adopt     decode a wire blob, adopt it (payload
                          scatter or replay restart), stream the
                          continuation as SSE
    POST /fleet/export    drain-style export of everything in flight
                          as replay blobs (no payloads)
    POST /fleet/cancel    cancel by request id
    POST /fleet/drain     stop admitting (engine + frontend); in-flight
                          work keeps serving
    POST /fleet/undrain   reopen admission

Threading discipline is inherited: handler threads never touch the
engine. The one extension is a generic `("call", (fn, box))` command —
control RPCs (export, adopt, drain) run `fn(engine)` ON the serving
loop between step() calls, exactly where @loop_only methods are legal.

Run as a process: `python -m mxnet_tpu.serving.fleet.worker --spec
SPEC.json [--role prefill|decode|mixed] [--port N]`. The spec fully
determines the model (config + init seed), so every worker in a fleet
builds bit-identical weights without shipping checkpoints; the worker
warms the steady-state programs (including one export->adopt handoff
round-trip, so disaggregation costs zero steady-state compiles) and
then prints one `FLEET_WORKER_READY {json}` line for the supervisor.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from urllib.parse import parse_qs, urlparse

from ...base import MXNetError
from ... import telemetry
from ..frontend import (ServingFrontend, TokenStream, _FrontendServer,
                        _Handler, _drain_rejection, _invalid_body,
                        _rejection_body, _DISCONNECT_ERRORS)
from ..scheduler import (Request, RejectedError, QueueFullError,
                         TERMINAL_STATUSES)
from . import wire

__all__ = ["FleetWorker", "build_engine", "warm_engine", "main"]

ROLES = ("prefill", "decode", "mixed")


class _CallBox:
    """Result slot for a generic serving-loop call."""
    __slots__ = ("outcome", "error", "result", "event")

    def __init__(self):
        self.outcome = None
        self.error = None
        self.result = None
        self.event = threading.Event()


class _WorkerHandler(_Handler):
    server_version = "mx-fleet-worker/1.0"

    @property
    def fw(self):
        return self.server.owner.frontend

    def do_GET(self):               # noqa: N802 (stdlib handler name)
        path = urlparse(self.path).path
        try:
            if path == "/fleet/stats":
                self._reply(self.fw.fleet_stats())
                return
            if path == "/fleet/requests":
                q = parse_qs(urlparse(self.path).query)
                try:
                    n = max(1, int(q["n"][0])) if "n" in q else 100
                except ValueError:
                    n = 100
                self._reply(self.fw.recent_requests(n))
                return
            if path == "/fleet/sloz":
                self._reply(self.fw.fleet_sloz())
                return
            if path == "/fleet/flightz":
                self._reply(self.fw.fleet_flightz())
                return
        except _DISCONNECT_ERRORS:
            return
        except Exception as e:      # noqa: BLE001 — must answer
            self._reply({"error": f"{type(e).__name__}: {e}"}, code=500)
            return
        super().do_GET()

    def do_POST(self):              # noqa: N802 (stdlib handler name)
        path = urlparse(self.path).path
        route = {
            "/fleet/prefill": self._fleet_prefill,
            "/fleet/adopt": self._fleet_adopt,
            "/fleet/export": self._fleet_export,
            "/fleet/cancel": self._fleet_cancel,
            "/fleet/drain": self._fleet_drain,
            "/fleet/undrain": self._fleet_undrain,
        }.get(path)
        if route is None:
            super().do_POST()
            return
        try:
            route()
        except _DISCONNECT_ERRORS:
            pass
        except Exception as e:      # noqa: BLE001 — must answer
            self._counted_reply(
                {"error": {"type": type(e).__name__,
                           "reason": "internal",
                           "message": str(e)}}, 500)

    # -- plumbing ----------------------------------------------------------
    def _read_body(self):
        return self.rfile.read(
            int(self.headers.get("Content-Length") or 0))

    def _read_json(self):
        body = json.loads(self._read_body() or b"{}")
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        return body

    # -- control plane -----------------------------------------------------
    def _fleet_cancel(self):
        try:
            body = self._read_json()
            rid = str(body["request_id"])
        except Exception as e:      # noqa: BLE001 — malformed request
            self._counted_reply(_invalid_body(e), 400)
            return
        self.fw.cancel(rid)
        self._reply({"ok": True, "request_id": rid})

    def _fleet_drain(self):
        self.fw.begin_drain()
        self.fw.call_on_loop(lambda eng: eng.drain())
        self._reply({"ok": True, "draining": True})

    def _fleet_undrain(self):
        self.fw.call_on_loop(lambda eng: eng.undrain())
        self.fw.end_drain()
        self._reply({"ok": True, "draining": False})

    def _fleet_export(self):
        blobs = self.fw.call_on_loop(
            lambda eng: [wire.encode_request(r)
                         for r in self.fw.close_streams(
                             eng.export_requests())])
        self._reply({"requests": blobs, "wire_version": wire.WIRE_VERSION})

    # -- disaggregation data plane -----------------------------------------
    def _fleet_prefill(self):
        """Admit, run prefill to the first emitted token, export the
        request WITH its KV payload, answer the wire blob. A request
        that goes terminal during prefill (1-token budget, instant
        EOS, deadline) comes back as a `final` blob — nothing left to
        hand off."""
        fw = self.fw
        try:
            body = self._read_json()
        except Exception as e:      # noqa: BLE001 — malformed request
            self._counted_reply(_invalid_body(e), 400)
            return
        if fw.draining:
            self._reject_reply(_drain_rejection(fw), 503)
            return
        try:
            req = fw._build_request(body)
        except (MXNetError, TypeError, ValueError, KeyError) as e:
            self._counted_reply(_invalid_body(e), 400)
            return
        tp = telemetry.parse_traceparent(self.headers.get("traceparent"))
        req.trace = {"trace_id": tp[0], "parent_span": tp[1]} \
            if tp is not None else {"trace_id": telemetry.new_trace_id()}
        outcome, err = fw._submit_via_loop(req)
        if outcome == "rejected":
            code = 429 if isinstance(err, QueueFullError) else 503
            self._reject_reply(_rejection_body(err), code)
            return
        if outcome == "invalid":
            self._counted_reply(_invalid_body(err), 400)
            return
        if outcome != "ok":
            self._counted_reply(
                {"error": {"type": "Internal", "reason": "internal",
                           "message": str(err)}}, 500)
            return
        deadline = time.monotonic() + fw.prefill_timeout_s
        while time.monotonic() < deadline:
            if req.output_tokens or req.status in TERMINAL_STATUSES:
                break
            time.sleep(0.002)
        exported = None
        if req.status not in TERMINAL_STATUSES:
            exported = fw.call_on_loop(
                lambda eng: eng.export_handoff(req.id))
        if exported is None:
            if req.status in TERMINAL_STATUSES:
                blob = wire.encode_request(req)
                blob["final"] = True
                fw._note_handoff(final=True)
                self._counted_reply(blob, 200)
                return
            # still mid-prefill at the timeout: give the slot back
            fw.cancel(req.id)
            self._counted_reply(
                {"error": {"type": "Timeout",
                           "reason": "prefill_timeout",
                           "message": "prefill did not reach its "
                                      "first token in "
                                      f"{fw.prefill_timeout_s}s"}}, 500)
            return
        if not fw.ship_payload:
            # replay fallback mode: the blob carries kv_history only,
            # the decode worker re-prefills (bit-identical, just
            # slower) — the bench's ablation arm
            exported.kv_payload = None
        blob = wire.encode_request(exported)
        blob["final"] = False
        fw._note_handoff(final=False)
        self._counted_reply(blob, 200)

    def _fleet_adopt(self):
        """Decode a wire blob, adopt it on the serving loop, and
        stream the continuation. Version mismatch -> 409 with the
        structured reason (never a guess-and-adopt)."""
        fw = self.fw
        try:
            blob = wire.loads(self._read_body())
        except wire.WireVersionError as e:
            fw._note_version_reject()
            self._counted_reply(
                {"error": {"type": "WireVersionError",
                           "reason": "wire_version_mismatch",
                           "message": str(e),
                           "got": e.got, "want": e.want}}, 409)
            return
        except MXNetError as e:
            self._counted_reply(_invalid_body(e), 400)
            return
        try:
            req = wire.decode_request(blob)
        except (MXNetError, KeyError, TypeError, ValueError) as e:
            self._counted_reply(_invalid_body(e), 400)
            return
        if fw.draining:
            self._reject_reply(_drain_rejection(fw), 503)
            return
        stream = TokenStream(
            capacity=max(fw.stream_buffer, req.max_new_tokens + 8))
        req.stream = stream
        base = len(req.output_tokens)
        try:
            fw.call_on_loop(
                lambda eng: eng.adopt(req, migrated_from="wire"))
        except RejectedError as e:
            code = 429 if isinstance(e, QueueFullError) else 503
            self._reject_reply(_rejection_body(e), code)
            return
        except MXNetError as e:
            self._counted_reply(_invalid_body(e), 400)
            return
        fw._register(req, stream)
        try:
            self._adopt_stream(fw, req, stream, base)
        finally:
            fw._unregister(req)

    def _adopt_stream(self, fw, req, stream, base):
        """SSE continuation of an adopted request. The `adopted` event
        acks the handoff (the router withholds client tokens until it
        lands, so client TTFT includes the handoff); `tokens` indices
        are LOCAL — index 0 is global token `base` — and the router
        re-bases them."""
        try:
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/event-stream; charset=utf-8")
            self.send_header("Cache-Control", "no-store")
            self.send_header("X-Request-Id", req.id)
            if req.trace:
                self.send_header(
                    "traceparent",
                    telemetry.format_traceparent(req.trace["trace_id"]))
            self.send_header("Connection", "close")
            self.end_headers()
            self._send_event("adopted", {
                "request_id": req.id, "base": base,
                "worker": fw.worker_id})
        except _DISCONNECT_ERRORS:
            fw._on_disconnect(req)
            return
        fw._code_inc(200)
        sent = 0
        while True:
            toks, closed = stream.take(timeout=fw.keepalive_s)
            try:
                if toks:
                    self._send_event("tokens",
                                     {"tokens": toks, "index": sent})
                    sent += len(toks)
                if closed is not None:
                    status = req.status \
                        if req.status in TERMINAL_STATUSES else closed
                    if stream.overflowed:
                        fw._note_overflow()
                        self._send_event("error", {
                            "error": "overflow", "sent": sent,
                            "message": "client fell behind on the "
                                       "adopted stream; request "
                                       "cancelled"})
                    else:
                        tail = [int(t) for t
                                in req.output_tokens[base + sent:]]
                        if tail:
                            self._send_event(
                                "tokens",
                                {"tokens": tail, "index": sent})
                            sent += len(tail)
                    self._send_event("done", {
                        "request_id": req.id, "status": status,
                        "emitted": len(req.output_tokens),
                        "sent": sent,
                        # the full stitched phase budget (handoff
                        # included) — the router and bench read TTFT
                        # decomposition from here
                        "phases": {k: float(v) for k, v
                                   in (req.phases or {}).items()}})
                    return
                if not toks:
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
            except _DISCONNECT_ERRORS:
                fw._on_disconnect(req)
                return


class _WorkerServer(_FrontendServer):
    handler_class = _WorkerHandler
    name_prefix = "mx-fleet-worker-http"


class FleetWorker(ServingFrontend):
    """ServingFrontend + the fleet control plane (one port, one
    engine, one serving loop). `role` is a declaration the router
    honors — "prefill" workers take new prompts and export at first
    token, "decode" workers adopt and stream, "mixed" does both; the
    worker itself never refuses a data-plane call based on role, so a
    degraded fleet can still route around losses."""

    server_class = _WorkerServer

    def __init__(self, engine, role="mixed", worker_id=None,
                 ship_payload=True, prefill_timeout_s=120.0, **kw):
        if role not in ROLES:
            raise MXNetError(f"role must be one of {ROLES}, got {role!r}")
        self.role = role
        self.worker_id = str(worker_id) if worker_id is not None \
            else f"w{os.getpid()}"
        self.ship_payload = bool(ship_payload)
        self.prefill_timeout_s = float(prefill_timeout_s)
        self._fleet_lock = threading.Lock()
        self._handoffs = 0
        self._handoffs_final = 0
        self._version_rejects = 0
        self._steady_compiles = 0
        # count compiles flagged steady (post-mark_warm shape churn)
        # that belong to THIS worker's engine — the disaggregation
        # acceptance bar is steady_state_compiles == 0 per worker
        prefix = f"engine{engine._eid}/"

        def _on_compile(ev, _prefix=prefix):
            if ev.get("steady") and str(ev.get("program", "")).startswith(
                    _prefix):
                with self._fleet_lock:
                    self._steady_compiles += 1

        self._compile_hook = _on_compile
        telemetry.cost.add_compile_hook(_on_compile)
        super().__init__(engine, **kw)

    @property
    def engine(self):
        return self._backend

    # -- serving-loop extension: generic calls -----------------------------
    def _drain_cmds(self, fail=False):
        """Full override of ServingFrontend._drain_cmds (the base
        treats every non-"submit" kind as a cancel payload): adds the
        ("call", (fn, box)) command that control RPCs use to run
        @loop_only engine methods on the owning thread."""
        while True:
            try:
                kind, payload = self._cmd_q.get_nowait()
            except queue.Empty:
                return
            if kind == "submit":
                req, box = payload
                if fail:
                    box.outcome = "error"
                    box.error = MXNetError("worker closed")
                    box.event.set()
                    continue
                self._do_submit(req, box)
            elif kind == "call":
                fn, box = payload
                if fail:
                    box.outcome = "error"
                    box.error = MXNetError("worker closed")
                    box.event.set()
                    continue
                try:
                    box.result = fn(self._backend)
                    box.outcome = "ok"
                except Exception as e:  # noqa: BLE001 — surfaced to caller
                    box.outcome, box.error = "error", e
                box.event.set()
            else:
                self._do_cancel(payload)

    def call_on_loop(self, fn, timeout=None):
        """Run `fn(engine)` on the serving loop and return its result
        (exceptions re-raise here). The only legal path from a handler
        thread to a @loop_only engine method."""
        box = _CallBox()
        self._cmd_q.put(("call", (fn, box)))
        self._wake.set()
        if not box.event.wait(timeout or self.submit_timeout_s):
            raise MXNetError("serving-loop call timed out")
        if box.outcome != "ok":
            raise box.error
        return box.result

    def close(self):
        telemetry.cost.remove_compile_hook(self._compile_hook)
        super().close()

    # -- control-plane helpers (handler threads) ---------------------------
    def end_drain(self):
        """Reopen frontend admission after /fleet/drain (the engine
        side is undrained separately, on the loop)."""
        self._draining = False
        telemetry.flight.record("frontend_undrained",
                                frontend=self._fid)

    def close_streams(self, reqs, status="exported"):
        """Close any attached client streams on exported requests —
        over the wire the blob carries the tokens, and the stream's
        reader learns the request moved via its `done` event."""
        for r in reqs:
            st = getattr(r, "stream", None)
            if st is not None:
                st.close(status)
                r.stream = None
        return reqs

    def _note_handoff(self, final):
        with self._fleet_lock:
            self._handoffs += 1
            if final:
                self._handoffs_final += 1

    def _note_version_reject(self):
        with self._fleet_lock:
            self._version_rejects += 1

    def fleet_stats(self):
        eng = self._backend
        return {
            "worker_id": self.worker_id,
            "role": self.role,
            "pid": os.getpid(),
            "url": self.url,
            # THIS process's wall-anchored request-trace clock, sampled
            # at answer time — the fleet collector brackets the RPC
            # with its own clock and derives a per-worker offset, so
            # cross-process trace assembly can align every worker's
            # timeline onto the collector's axis
            "now": telemetry.now(),
            "wire_version": wire.WIRE_VERSION,
            "ship_payload": self.ship_payload,
            "draining": self.draining,
            "handoffs": self._handoffs,
            "handoffs_final": self._handoffs_final,
            "wire_version_rejects": self._version_rejects,
            "engine": {
                "chunk_tokens": eng.chunk_tokens,
                "prefill_chunk_budget": eng.prefill_chunk_budget,
                "page_size": eng.page_size,
                "max_length": eng.max_length,
                "num_slots": eng.num_slots,
                "kv_dtype": eng.kv_dtype,
                "weight_dtype": eng.weight_dtype,
            },
            "stats": dict(eng.stats,
                          steady_state_compiles=self._steady_compiles),
            "frontend": self.stats,
        }

    def recent_requests(self, n=100):
        """This engine's recent request timelines only — two in-process
        workers share the process-global request log, so the engine id
        scopes the answer."""
        eid = str(self._backend._eid)
        return [t for t in telemetry.request_log.recent(max(n * 4, 64))
                if str(t.get("engine")) == eid][-n:]

    def fleet_sloz(self):
        """GET /fleet/sloz — this process's SLO engine snapshot plus
        the clock stamp the collector's alignment needs."""
        return {"worker_id": self.worker_id, "now": telemetry.now(),
                "slo": telemetry.slo.snapshot()}

    def fleet_flightz(self):
        """GET /fleet/flightz — this process's flight-recorder state:
        latched reasons (the collector mirrors any NEW latch into a
        correlated fleet dump), completed dump paths, and a bounded
        tail of the breadcrumb ring."""
        rec = telemetry.flight.get()
        out = {"worker_id": self.worker_id, "now": telemetry.now(),
               "armed": rec is not None,
               "latched": telemetry.flight.latched_reasons()}
        if rec is not None:
            out["dumps"] = [str(p) for p in rec.dumps]
            out["events_tail"] = rec.events()[-64:]
        return out


# -- spec-driven process entry ---------------------------------------------

def build_engine(spec):
    """Build (model, config, engine) from a JSON-safe spec:
    {"config": GPT2Config kwargs, "seed": int, "init_std": float,
    "engine": ServingEngine kwargs}. The seed pins initialization, so
    every process given the same spec holds bit-identical weights —
    the fleet's substitute for shipping checkpoints."""
    import mxnet_tpu as mx
    from ...models import GPT2Config, GPT2ForCausalLM
    from ..engine import ServingEngine

    cfg = GPT2Config(**spec.get("config", {}))
    mx.rng.seed(int(spec.get("seed", 3)))
    net = GPT2ForCausalLM(cfg)
    net.initialize(mx.init.Normal(float(spec.get("init_std", 0.05))))
    eng = ServingEngine(net, **spec.get("engine", {}))
    return net, cfg, eng


def warm_engine(eng, cfg, spec=None):
    """Compile the full steady-state program set BEFORE declaring
    ready: greedy + sampled serving across EVERY prefill bucket a
    prompt (or a migrated re-prefill of prompt + emitted tokens) can
    land in, and one export_handoff -> adopt round-trip so the tier
    gather/scatter (and int8 zero-scale) programs are warm — a
    disaggregated fleet must run with steady_state_compiles == 0,
    handoffs included. Ends with mark_warm() + reset_stats()."""
    import numpy as np
    spec = spec or {}
    rng = np.random.default_rng(int(spec.get("warmup_seed", 17)))
    vocab = int(cfg.vocab_size)
    mk = lambda n, i, samp: Request(    # noqa: E731 — local shorthand
        rng.integers(0, vocab, n).tolist(), 4, seed=9900 + i,
        do_sample=samp, request_id=f"_warm{i}")
    page = int(eng.page_size)
    lens = [4, 5] + list(range(page, int(eng.max_length), page))
    # two passes, one per program variant: the engine picks greedy-only
    # vs mixed-sampling by whether ANY active slot samples, so a serve()
    # that interleaves both leaves whichever variant the scheduler never
    # isolated uncompiled — an all-greedy pass then an all-sampled pass
    # pins both, across every bucket
    i = 0
    for samp in (False, True):
        eng.serve([mk(n, (i := i + 1), samp) for n in lens])
    # the round-trip prompt spans two KV pages so multi-page handoffs
    # are compiled too
    r = mk(page + 3, i, True)
    eng.submit(r)
    for _ in range(64):
        eng.step()
        if r.output_tokens or r.status in TERMINAL_STATUSES:
            break
    e = eng.export_handoff(r.id)
    if e is not None:
        eng.adopt(e, migrated_from="warmup")
    while eng.has_work:
        eng.step()
    eng.mark_warm()
    eng.reset_stats()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="run one fleet worker process")
    ap.add_argument("--spec", required=True,
                    help="model+engine spec: a JSON file path or an "
                         "inline JSON object")
    ap.add_argument("--role", default=None, choices=ROLES)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--worker-id", default=None)
    ap.add_argument("--no-ship-payload", action="store_true",
                    help="handoff blobs carry kv_history only (replay "
                         "restart on the decode side) — the ablation "
                         "arm")
    ap.add_argument("--no-warmup", action="store_true")
    args = ap.parse_args(argv)
    raw = args.spec
    if os.path.exists(raw):
        with open(raw, "r", encoding="utf-8") as f:
            raw = f.read()
    spec = json.loads(raw)
    _net, cfg, eng = build_engine(spec)
    if not args.no_warmup:
        warm_engine(eng, cfg, spec)
    fw = FleetWorker(
        eng, role=args.role or spec.get("role", "mixed"),
        worker_id=args.worker_id, port=args.port, host=args.host,
        ship_payload=not args.no_ship_payload,
        **spec.get("frontend", {}))
    print("FLEET_WORKER_READY " + json.dumps(
        {"url": fw.url, "pid": os.getpid(), "role": fw.role,
         "worker_id": fw.worker_id}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        fw.close()


if __name__ == "__main__":
    main()
