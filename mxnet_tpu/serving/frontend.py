"""Streaming HTTP ingress for the serving stack — stdlib-only.

`ServingFrontend` turns a `ServingEngine` (or a `ServingRouter` fleet)
into a servable endpoint on the same ThreadingHTTPServer stack as
telemetry/server.py (docs/SERVING.md "HTTP front-end"):

    POST /v1/generate   generate from a JSON body; the default
                        response is an SSE stream (`tokens` events as
                        they land, a structured `error` event on
                        overflow, one final `done` event), or a single
                        JSON body with "stream": false
    GET  /healthz       process liveness (shared with telemetry)
    GET  /readyz        readiness — flips 503 the moment shutdown()
                        starts draining (?component= scoping works)
    GET  /metrics       Prometheus text exposition of the registry

Three robustness properties anchor the design:

* **Backpressure maps to HTTP.** The engine's structured rejections
  become status codes — `QueueFullError`/`TenantQuotaError` -> 429,
  `ShedError` (overload, draining, infeasible deadline) -> 503 — and
  every rejection carries a `Retry-After` header from the engine's
  drain-rate estimate plus the full structured body (reason,
  queue_depth, active_slots, priority, tenant, retry_after_s).

* **Disconnects cancel.** Every write to the client doubles as a
  liveness probe (idle streams get `: keepalive` SSE comments); a
  failed write means the client hung up, and the handler routes
  `cancel(request_id)` onto the serving thread — slot, page, and
  adapter leases release immediately. Cancellation is idempotent, so
  the disconnect vs natural-finish race is harmless.

* **Bounded memory end to end.** Tokens flow through a bounded
  `TokenStream`; when a slow client lets it fill, the engine cancels
  the request (`_overflow_cancel`) instead of buffering unboundedly,
  and the client gets a structured `overflow` error event.

Threading model: HTTP handler threads NEVER touch the engine. They
parse, enqueue a submit/cancel command, and read the Request + its
TokenStream. One serving-loop thread owns every backend mutation —
it drains the command queue between `step()` calls, which is exactly
the "call from the serving thread" contract engine.cancel() states.
A frontend fronting a ServingRouter inherits the fleet's failover: a
replica kill mid-stream migrates the Request (stream attached) via
export/adopt, and the client's stream continues bit-identically.
"""
from __future__ import annotations

import itertools
import json
import math
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler
from urllib.parse import parse_qs, urlparse

from ..base import MXNetError
from ..analysis import assertions_enabled, claim_ownership, thread_safe
from .. import telemetry
from ..telemetry import server as _tserver
from .scheduler import (Request, RejectedError, QueueFullError,
                        TERMINAL_STATUSES)

__all__ = ["ServingFrontend", "TokenStream"]

_frontend_ids = itertools.count()
_F = ("frontend",)

# socket errors that mean "the client hung up" (ConnectionResetError
# and BrokenPipeError are OSError subclasses; ValueError covers a
# write on a handler-closed file object)
_DISCONNECT_ERRORS = (OSError, ValueError)


def _frontend_metrics(fid):
    c, g, h = telemetry.counter, telemetry.gauge, telemetry.histogram
    m = {
        "active_streams": g(
            "http_active_streams",
            "response streams currently open on /v1/generate", _F),
        "disconnects": c(
            "http_disconnects_total",
            "client disconnects detected mid-request (each one routes "
            "a cancel onto the serving thread)", _F),
        "overflows": c(
            "http_stream_overflows_total",
            "streams whose bounded token buffer overflowed (slow "
            "client) — the engine cancelled the request rather than "
            "buffer unboundedly", _F),
        "ttfb": h(
            "http_ttfb_seconds",
            "request arrival at the frontend -> first token event "
            "written to the socket (client-observable first byte of "
            "generated output)", _F),
    }
    _code_family()
    return {k: inst.labels(fid) for k, inst in m.items()}


def _code_family():
    return telemetry.counter(
        "http_requests_total",
        "requests answered on /v1/generate, by final HTTP status code "
        "(200 stream/body, 400 invalid, 429 queue-full/quota, 503 "
        "overload/draining, 500 internal)", ("frontend", "code"))


class TokenStream:
    """Bounded bridge from the engine's dispatch loop to one HTTP
    response thread. The engine calls emit()/close() (duck-typed via
    `Request.stream`); the handler thread blocks in take(). emit()
    returns False — and latches `overflowed` — when the buffer can't
    absorb a dispatch's tokens: the engine's slow-client policy then
    cancels the request. close() is first-wins and idempotent."""

    def __init__(self, capacity=256):
        self.capacity = int(capacity)
        self.overflowed = False
        self.emitted = 0            # tokens accepted into the buffer
        self._buf = []
        self._closed = None         # terminal status string once closed
        self._cv = threading.Condition()

    def emit(self, tokens):
        tokens = list(tokens)
        with self._cv:
            if self._closed is not None:
                return True         # late emit after close: drop quietly
            if not tokens:
                return True
            if len(self._buf) + len(tokens) > self.capacity:
                self.overflowed = True
                self._cv.notify_all()
                return False
            self._buf.extend(tokens)
            self.emitted += len(tokens)
            self._cv.notify_all()
            return True

    def close(self, status):
        with self._cv:
            if self._closed is None:
                self._closed = str(status)
            self._cv.notify_all()

    @property
    def closed(self):
        with self._cv:
            return self._closed

    def take(self, timeout=None):
        """Block until tokens arrive or the stream closes (or
        `timeout` elapses — the handler's keepalive cadence). Returns
        (tokens, closed_status_or_None); buffered tokens always drain
        before/alongside the close."""
        with self._cv:
            if not self._buf and self._closed is None:
                self._cv.wait(timeout)
            toks, self._buf = self._buf, []
            return toks, self._closed


class _Handler(BaseHTTPRequestHandler):
    server_version = "mx-serving/1.0"
    protocol_version = "HTTP/1.0"   # close-delimited: SSE needs no
                                    # Content-Length and no chunk framing

    def log_message(self, fmt, *args):
        pass                        # traffic must not spam stderr

    @property
    def fe(self):
        return self.server.owner.frontend

    # -- plumbing ----------------------------------------------------------
    def _reply(self, body, code=200, ctype="application/json",
               headers=()):
        if isinstance(body, (dict, list)):
            body = json.dumps(body, sort_keys=True, default=str)
        if isinstance(body, str):
            body = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_event(self, event, data):
        self.wfile.write(
            (f"event: {event}\ndata: {json.dumps(data, default=str)}"
             "\n\n").encode("utf-8"))
        self.wfile.flush()

    # -- GET: health/readiness/metrics reuse the telemetry surface ---------
    def do_GET(self):               # noqa: N802 (stdlib handler name)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/healthz":
                self._reply(_tserver.healthz_body(),
                            ctype="text/plain; charset=utf-8")
            elif url.path == "/readyz":
                body, code = _tserver.readyz_body(
                    q.get("component", [None])[0])
                self._reply(body, code=code)
            elif url.path == "/metrics":
                self._reply(telemetry.render_prometheus(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
            elif url.path in ("/", "/index.html"):
                self._reply({"endpoints": ["/v1/generate", "/healthz",
                                           "/readyz", "/metrics"]})
            else:
                self._reply({"error": "not found", "path": url.path},
                            code=404)
        except _DISCONNECT_ERRORS:
            pass                    # scraper hung up: nothing to do
        except Exception as e:      # noqa: BLE001 — must answer
            self._reply({"error": f"{type(e).__name__}: {e}"}, code=500)

    # -- POST /v1/generate -------------------------------------------------
    def do_POST(self):              # noqa: N802 (stdlib handler name)
        fe = self.fe
        url = urlparse(self.path)
        if url.path != "/v1/generate":
            self._counted_reply(
                {"error": {"type": "NotFound", "reason": "not_found",
                           "message": url.path}}, 404)
            return
        t0 = time.perf_counter()
        try:
            raw = self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
        except OSError:
            return                  # client hung up mid-upload
        except ValueError as e:     # malformed Content-Length
            self._counted_reply(_invalid_body(e), 400)
            return
        try:
            body = json.loads(raw or b"{}")
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        except Exception as e:      # noqa: BLE001 — malformed request
            self._counted_reply(_invalid_body(e), 400)
            return
        if fe.draining:
            self._reject_reply(_drain_rejection(fe), 503)
            return
        try:
            req = fe._build_request(body)
        except (MXNetError, TypeError, ValueError, KeyError) as e:
            self._counted_reply(_invalid_body(e), 400)
            return
        # W3C trace context: adopt the caller's trace id (invalid
        # headers are ignored per spec, never 400), else mint one —
        # the id rides the Request through router/engine/migration
        # and comes back on the response's own traceparent header
        tp = telemetry.parse_traceparent(self.headers.get("traceparent"))
        if tp is not None:
            req.trace = {"trace_id": tp[0], "parent_span": tp[1]}
        else:
            req.trace = {"trace_id": telemetry.new_trace_id()}
        want_stream = bool(body.get("stream", True))
        if want_stream:
            # the client may advertise a SMALLER buffer than the
            # server default (a flow-control window: "cancel me rather
            # than buffer more than this on my behalf"); the server's
            # bound stays the ceiling
            cap = fe.stream_buffer
            try:
                asked = body.get("stream_buffer")
                if asked is not None:
                    cap = max(1, min(int(asked), cap))
            except (TypeError, ValueError) as e:
                self._counted_reply(_invalid_body(e), 400)
                return
        else:
            # non-stream responses drain the buffer only at the end,
            # so the bound must cover the request's whole token
            # budget — still finite, still the request's own number
            cap = max(fe.stream_buffer, req.max_new_tokens + 8)
        stream = TokenStream(capacity=cap)
        req.stream = stream
        outcome, err = fe._submit_via_loop(req)
        if outcome == "rejected":
            code = 429 if isinstance(err, QueueFullError) else 503
            self._reject_reply(_rejection_body(err), code)
            return
        if outcome == "invalid":
            self._counted_reply(_invalid_body(err), 400)
            return
        if outcome != "ok":
            self._counted_reply(
                {"error": {"type": "Internal", "reason": "internal",
                           "message": str(err)}}, 500)
            return
        fe._register(req, stream)
        try:
            if want_stream:
                self._stream_response(fe, req, stream, t0)
            else:
                self._json_response(fe, req, stream)
        finally:
            fe._unregister(req)

    def _counted_reply(self, body, code, headers=()):
        self.fe._code_inc(code)
        try:
            self._reply(body, code=code, headers=headers)
        except _DISCONNECT_ERRORS:
            pass                    # client gone before the reply

    def _reject_reply(self, body, code):
        """429/503 with Retry-After (integer seconds, >= 1) alongside
        the structured JSON rejection body."""
        wait = body["error"].get("retry_after_s")
        retry = max(1, math.ceil(wait)) if wait else 1
        self._counted_reply(body, code,
                            headers=(("Retry-After", str(retry)),))

    def _stream_response(self, fe, req, stream, t0):
        try:
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/event-stream; charset=utf-8")
            self.send_header("Cache-Control", "no-store")
            self.send_header("X-Request-Id", req.id)
            if req.trace:
                self.send_header("traceparent", telemetry.format_traceparent(
                    req.trace["trace_id"]))
            self.send_header("Connection", "close")
            self.end_headers()
        except _DISCONNECT_ERRORS:
            fe._on_disconnect(req)
            return
        fe._code_inc(200)
        sent = 0
        first = True
        while True:
            toks, closed = stream.take(timeout=fe.keepalive_s)
            try:
                if toks:
                    self._send_event("tokens",
                                     {"tokens": toks, "index": sent})
                    if first:
                        fe._observe_ttfb(time.perf_counter() - t0)
                        first = False
                    sent += len(toks)
                if closed is not None:
                    status = req.status \
                        if req.status in TERMINAL_STATUSES else closed
                    if stream.overflowed:
                        fe._note_overflow()
                        self._send_event("error", {
                            "error": "overflow",
                            "message": "client fell behind: the "
                                       "bounded stream buffer "
                                       f"({stream.capacity} tokens) "
                                       "overflowed and the request "
                                       "was cancelled",
                            "sent": sent})
                    else:
                        # terminal reconciliation: tokens that reached
                        # the Request but not the buffer (hedge-won
                        # graft, close racing the last dispatch)
                        tail = [int(t) for t
                                in req.output_tokens[sent:]]
                        if tail:
                            self._send_event(
                                "tokens",
                                {"tokens": tail, "index": sent})
                            if first:
                                fe._observe_ttfb(
                                    time.perf_counter() - t0)
                                first = False
                            sent += len(tail)
                    self._send_event("done", {
                        "request_id": req.id, "status": status,
                        "emitted": len(req.output_tokens),
                        "sent": sent})
                    return
                if not toks:
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
            except _DISCONNECT_ERRORS:
                fe._on_disconnect(req)
                return

    def _json_response(self, fe, req, stream):
        while True:
            _, closed = stream.take(timeout=fe.keepalive_s)
            if closed is not None:
                break
        status = req.status if req.status in TERMINAL_STATUSES \
            else closed
        body = {
            "request_id": req.id,
            "status": status,
            "output_tokens": [int(t) for t in req.output_tokens],
            "usage": {"prompt_tokens": req.prompt_len,
                      "completion_tokens": len(req.output_tokens)},
        }
        fe._code_inc(200)
        hdrs = [("X-Request-Id", req.id)]
        if req.trace:
            hdrs.append(("traceparent", telemetry.format_traceparent(
                req.trace["trace_id"])))
        try:
            self._reply(body, code=200, headers=tuple(hdrs))
        except _DISCONNECT_ERRORS:
            fe._on_disconnect(req)


def _rejection_body(exc):
    return {"error": {
        "type": type(exc).__name__,
        "reason": getattr(exc, "reason", None),
        "message": str(exc),
        "queue_depth": getattr(exc, "queue_depth", None),
        "active_slots": getattr(exc, "active_slots", None),
        "retry_after_s": getattr(exc, "retry_after_s", None),
        "priority": getattr(exc, "priority", None),
        "tenant": getattr(exc, "tenant", None),
    }}


def _invalid_body(exc):
    return {"error": {"type": type(exc).__name__,
                      "reason": "invalid_request",
                      "message": str(exc)}}


def _drain_rejection(fe):
    wait = fe._drain_estimate()
    return {"error": {
        "type": "ShedError", "reason": "draining",
        "message": "frontend is draining: not accepting new requests",
        "queue_depth": None, "active_slots": None,
        "retry_after_s": wait, "priority": None, "tenant": None,
    }}


class _FrontendServer(_tserver.HttpServerThread):
    handler_class = _Handler
    name_prefix = "mx-serving-http"

    def __init__(self, frontend, port=0, host="127.0.0.1"):
        self.frontend = frontend
        super().__init__(port, host)


class _Box:
    """One submit command's result slot, handed between the handler
    thread and the serving loop."""
    __slots__ = ("outcome", "error", "event")

    def __init__(self):
        self.outcome = None
        self.error = None
        self.event = threading.Event()


class ServingFrontend:
    """The HTTP ingress plus the serving loop that owns the backend.

    `backend` is a ServingEngine or a ServingRouter (duck-typed:
    submit/cancel/step/has_work). The constructor starts both the
    listener and the serving-loop thread; `close()` is deterministic
    and idempotent (loop joined, port released) and the instance is a
    context manager. `shutdown()` is the graceful path: admission
    flips to 503 + Retry-After (and the registered /readyz probe flips
    not-ready), open streams drain, then everything closes."""

    #: The listener class — subclasses (serving/fleet/worker.py) swap
    #: in a server whose handler speaks extra control-plane routes on
    #: the same port.
    server_class = _FrontendServer

    def __init__(self, backend, port=0, host="127.0.0.1", *,
                 stream_buffer=256, keepalive_s=0.25,
                 step_idle_s=0.01, submit_timeout_s=30.0):
        self._backend = backend
        self._fid = next(_frontend_ids)
        self.stream_buffer = int(stream_buffer)
        self.keepalive_s = float(keepalive_s)
        self.step_idle_s = float(step_idle_s)
        self.submit_timeout_s = float(submit_timeout_s)
        self._metrics = _frontend_metrics(self._fid)
        self._codes_family = _code_family()
        self._lock = threading.Lock()
        self._codes = {}            # status code -> count (host mirror)
        self._disconnects = 0
        self._overflows = 0
        self._cancels_issued = 0
        self._cancels_noop = 0
        self._live = {}             # request id -> (Request, TokenStream)
        self._rid_counter = itertools.count()
        self._cmd_q = queue.Queue()
        self._wake = threading.Event()
        self._stop_evt = threading.Event()
        self._draining = False
        self._closed = False
        self._probe_name = f"frontend{self._fid}"
        _tserver.register_ready_probe(self._probe_name,
                                      self._ready_probe)
        telemetry.register_status_provider(self._probe_name,
                                           self._statusz)
        self._loop_thread = threading.Thread(
            target=self._serving_loop,
            name=f"mx-serving-loop:{self._fid}", daemon=True)
        self._server = self.server_class(self, port, host)
        self._loop_thread.start()

    # -- lifecycle ---------------------------------------------------------
    @property
    def url(self):
        return self._server.url

    @property
    def host(self):
        return self._server.host

    @property
    def port(self):
        return self._server.port

    @property
    def draining(self):
        return self._draining

    @thread_safe
    def begin_drain(self):
        """Stop accepting new requests: /v1/generate answers 503 with
        a drain-estimate Retry-After and the registered /readyz probe
        flips not-ready. Admitted requests and open streams keep
        being served. Idempotent."""
        if self._draining:
            return
        self._draining = True
        telemetry.flight.record("frontend_draining", frontend=self._fid)

    def shutdown(self, timeout=30.0):
        """Graceful drain: begin_drain(), let the serving loop finish
        every admitted request and every open stream drain to its
        client, then close deterministically. `timeout` bounds the
        wait — whatever is still open when it expires is force-closed
        by close()."""
        self.begin_drain()
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                busy = bool(self._live)
            if not busy and self._cmd_q.empty() \
                    and not self._backend.has_work:
                break
            time.sleep(0.02)
        self.close()

    def close(self):
        """Deterministic teardown: serving loop joined (pending
        submits failed, not leaked), any still-open streams force-
        closed, listener closed (port released), telemetry
        registrations dropped. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._draining = True
        self._stop_evt.set()
        self._wake.set()
        self._loop_thread.join(timeout=10)
        with self._lock:
            live = list(self._live.values())
        for _req, st in live:
            try:
                st.close("aborted")
            except Exception:       # noqa: BLE001 — teardown
                pass
        self._server.close()
        _tserver.unregister_ready_probe(self._probe_name)
        telemetry.unregister_status_provider(self._probe_name)
        self._metrics["active_streams"].set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __repr__(self):
        return (f"ServingFrontend({self.url}, "
                f"draining={self._draining})")

    # -- serving loop: the ONLY thread that touches the backend ------------
    def _serving_loop(self):
        if assertions_enabled():
            # warm-up ran on the constructing thread; this loop owns
            # the backend (and everything its cascade drives) from here
            claim_ownership(self._backend)
        try:
            while not self._stop_evt.is_set():
                self._drain_cmds()
                try:
                    if self._backend.has_work:
                        self._backend.step()
                        continue
                except Exception as e:  # noqa: BLE001 — keep serving
                    telemetry.flight.record(
                        "frontend_step_error", frontend=self._fid,
                        error=str(e)[:200])
                self._wake.wait(self.step_idle_s)
                self._wake.clear()
        finally:
            self._drain_cmds(fail=True)

    def _drain_cmds(self, fail=False):
        while True:
            try:
                kind, payload = self._cmd_q.get_nowait()
            except queue.Empty:
                return
            if kind == "submit":
                req, box = payload
                if fail:
                    box.outcome = "error"
                    box.error = MXNetError("frontend closed")
                    box.event.set()
                    continue
                self._do_submit(req, box)
            else:
                self._do_cancel(payload)

    def _do_submit(self, req, box):
        try:
            self._backend.submit(req)
            box.outcome = "ok"
        except RejectedError as e:
            box.outcome, box.error = "rejected", e
        except MXNetError as e:
            box.outcome, box.error = "invalid", e
        except Exception as e:      # noqa: BLE001 — surface, don't die
            box.outcome, box.error = "error", e
        box.event.set()

    def _do_cancel(self, request_id):
        try:
            got = self._backend.cancel(request_id)
        except Exception:           # noqa: BLE001 — replica may be dead
            got = None
        with self._lock:
            if got:
                self._cancels_issued += 1
            else:
                self._cancels_noop += 1

    # -- handler-thread entry points ---------------------------------------
    def _build_request(self, body):
        prompt = body.get("prompt")
        if not isinstance(prompt, (list, tuple)) or not prompt:
            raise MXNetError(
                "'prompt' must be a non-empty list of token ids")
        kw = {}
        for k in ("do_sample", "temperature", "top_k", "top_p", "seed",
                  "eos_token_id", "priority", "deadline_ms",
                  "adapter_id", "tenant"):
            if body.get(k) is not None:
                kw[k] = body[k]
        rid = str(body.get("request_id")
                  or f"http{self._fid}-{next(self._rid_counter)}")
        return Request([int(t) for t in prompt],
                       int(body.get("max_new_tokens", 16)),
                       request_id=rid, **kw)

    @thread_safe
    def _submit_via_loop(self, req):
        """Hand the request to the serving thread and wait for the
        admission verdict: ("ok"|"rejected"|"invalid"|"error", exc)."""
        box = _Box()
        self._cmd_q.put(("submit", (req, box)))
        self._wake.set()
        if not box.event.wait(timeout=self.submit_timeout_s):
            return "error", MXNetError("submission timed out")
        return box.outcome, box.error

    @thread_safe
    def cancel(self, request_id):
        """Route a cancel onto the serving thread (handler threads and
        external callers must never call the backend directly)."""
        self._cmd_q.put(("cancel", request_id))
        self._wake.set()

    @thread_safe
    def _on_disconnect(self, req):
        self._metrics["disconnects"].inc()
        with self._lock:
            self._disconnects += 1
        self.cancel(req.id)

    def _note_overflow(self):
        self._metrics["overflows"].inc()
        with self._lock:
            self._overflows += 1

    def _observe_ttfb(self, dt):
        self._metrics["ttfb"].observe(dt)

    def _register(self, req, stream):
        with self._lock:
            self._live[req.id] = (req, stream)
            n = len(self._live)
        self._metrics["active_streams"].set(n)

    def _unregister(self, req):
        with self._lock:
            self._live.pop(req.id, None)
            n = len(self._live)
        self._metrics["active_streams"].set(n)

    def _code_inc(self, code):
        self._codes_family.labels(self._fid, str(code)).inc()
        with self._lock:
            self._codes[str(code)] = self._codes.get(str(code), 0) + 1

    def _drain_estimate(self):
        """Seconds until in-flight work drains — the Retry-After a
        draining frontend attaches. Router backends report their
        slowest up replica (the drain completes when IT does)."""
        reps = getattr(self._backend, "replicas", None)
        if reps is None:
            return self._backend.estimated_drain_wait()
        waits = []
        for rep in reps:
            if rep.state != "up":
                continue
            try:
                w = rep.engine.estimated_drain_wait()
            except Exception:       # noqa: BLE001 — dead replica
                w = None
            if w is not None:
                waits.append(w)
        return max(waits) if waits else None

    # -- observability -----------------------------------------------------
    @thread_safe
    def _ready_probe(self):
        return {"warmed": True, "degraded": False,
                "draining": self._draining or self._closed}

    @property
    def stats(self):
        with self._lock:
            return {
                "requests_by_code": dict(self._codes),
                "active_streams": len(self._live),
                "disconnects": self._disconnects,
                "stream_overflows": self._overflows,
                "cancels_issued": self._cancels_issued,
                "cancels_noop": self._cancels_noop,
                "draining": self._draining,
            }

    @thread_safe
    def _statusz(self):
        return {"url": self.url, "stats": self.stats}
