"""Host-RAM KV spill tier: a byte-budgeted pool of spilled page
payloads.

The serving stack was HBM-only for state: when the page budget ran
out, the radix prefix cache LRU-*discarded* pages and every eviction
became a future full re-prefill. `HostPagePool` is the second tier
under `PagePool`/`PrefixCache` (docs/SERVING.md "Tiered KV cache"):
spilled page payloads — k/v codes plus the int8 dequant scale leaves —
live here as host numpy arrays keyed by what owns them (a radix node's
chunk path, or a preempted request id for a whole-request swap), and a
later radix hit pages them back into freshly allocated device pages
instead of recomputing the prefix.

The pool stores BYTES, not pages: entries are admitted while the
budget holds, evicted LRU when it does not. An entry's lifecycle:

  * ``put(key, payload)``      — admit a payload (dict of numpy
                                 arrays), LRU-evicting unpinned entries
                                 to fit; returns False (payload
                                 dropped) when the budget cannot be
                                 met — spilling is best-effort, the
                                 caller falls back to plain discard.
  * ``checkout(key)``          — take a LEASE on an entry for an
                                 in-flight page-in: the payload is
                                 returned and the entry pinned
                                 (unevictable) until released. Same
                                 release-post-dominance discipline as
                                 device page leases — graftlint's
                                 resource pass checks every checkout
                                 site.
  * ``release(key, drop=...)`` — drop the lease; ``drop=True`` removes
                                 the entry too (the payload now lives
                                 on device again).
  * ``discard(key)``           — remove an unpinned entry outright
                                 (its owner died: node discarded,
                                 request cancelled).

``evict_cb(key) -> bool`` is consulted before the pool LRU-drops an
entry to make room: the owner (the engine, which forwards radix-node
keys to the prefix cache) either detaches its reference and answers
True, or answers False and the entry is skipped — the two tiers can
never disagree about who holds a payload. ``audit()`` checks the
byte accounting and pin invariants the same way ``PagePool.audit()``
checks refcounts.

Payloads are plain host numpy arrays (materialized via
``jax.device_get`` from one jitted fixed-shape gather — see
engine._tier_gather); the pool itself never touches jax.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..base import MXNetError
from ..analysis import loop_only, thread_safe

__all__ = ["HostPagePool"]


def _payload_bytes(payload):
    n = 0
    for v in payload.values():
        if isinstance(v, np.ndarray):
            n += int(v.nbytes)
    return n


class HostPagePool:
    """Byte-budgeted LRU store of spilled KV page payloads (host RAM).

    budget_bytes: total payload bytes the pool may hold. evict_cb:
    optional ``cb(key) -> bool`` asked before an LRU eviction — False
    vetoes (the entry is skipped this round). Counters: ``puts``,
    ``rejected`` (budget could not be met), ``evictions`` (LRU drops).
    """

    def __init__(self, budget_bytes, evict_cb=None):
        if int(budget_bytes) < 1:
            raise MXNetError("HostPagePool needs budget_bytes >= 1")
        self.budget_bytes = int(budget_bytes)
        self.evict_cb = evict_cb
        self._entries = OrderedDict()   # key -> payload dict
        self._bytes = {}                # key -> payload bytes
        self._pins = {}                 # key -> lease count
        self.bytes_used = 0
        self.puts = 0
        self.rejected = 0
        self.evictions = 0

    # -- queries -----------------------------------------------------------
    @property
    def num_entries(self):
        return len(self._entries)

    def __contains__(self, key):
        return key in self._entries

    def keys(self):
        """Snapshot of every key, LRU-oldest first."""
        return list(self._entries)

    def entry_bytes(self, key):
        return int(self._bytes.get(key, 0))

    # -- lifecycle ---------------------------------------------------------
    def _evict_for(self, need):
        """LRU-drop unpinned, owner-approved entries until `need` bytes
        fit. Returns True when the budget can take the new entry."""
        if need > self.budget_bytes:
            return False
        while self.bytes_used + need > self.budget_bytes:
            victim = None
            for key in self._entries:          # oldest first
                if self._pins.get(key, 0):
                    continue
                if self.evict_cb is not None and not self.evict_cb(key):
                    continue
                victim = key
                break
            if victim is None:
                return False
            self._drop(victim)
            self.evictions += 1
        return True

    def _drop(self, key):
        del self._entries[key]
        self.bytes_used -= self._bytes.pop(key)
        self._pins.pop(key, None)

    @loop_only
    def put(self, key, payload):
        """Admit `payload` (a dict of numpy arrays) under `key`,
        LRU-evicting to fit. Returns False — payload NOT stored — when
        the budget cannot be met by dropping unpinned entries; the
        caller falls back to plain discard. Replacing an existing key
        is an error: a spilled page's payload is immutable."""
        if key in self._entries:
            raise MXNetError(f"host tier already holds key {key!r}")
        self.puts += 1
        need = _payload_bytes(payload)
        if not self._evict_for(need):
            self.rejected += 1
            return False
        self._entries[key] = payload
        self._bytes[key] = need
        self.bytes_used += need
        self._entries.move_to_end(key)
        return True

    @loop_only
    def checkout(self, key):
        """Lease an entry for a page-in: returns the payload and pins
        the entry until release(). Raises when the key is absent — the
        caller must treat a missing payload as a plain cache miss
        BEFORE checking out."""
        payload = self._entries.get(key)
        if payload is None:
            raise MXNetError(f"host tier has no entry for key {key!r}")
        self._pins[key] = self._pins.get(key, 0) + 1
        self._entries.move_to_end(key)
        return payload

    @loop_only
    def release(self, key, drop=False):
        """Return a checkout() lease. drop=True removes the entry (the
        payload landed on device; the host copy is dead)."""
        pins = self._pins.get(key, 0)
        if pins < 1:
            raise MXNetError(f"host tier release of unpinned key {key!r}")
        if pins == 1:
            self._pins.pop(key)
        else:
            self._pins[key] = pins - 1
        if drop and not self._pins.get(key, 0):
            self._drop(key)

    @loop_only
    def discard(self, key):
        """Remove an unpinned entry (its owner died). Returns True when
        an entry was dropped, False for an unknown key."""
        if key not in self._entries:
            return False
        if self._pins.get(key, 0):
            raise MXNetError(f"host tier discard of pinned key {key!r}")
        self._drop(key)
        return True

    @thread_safe
    def audit(self, raise_on_error=False):
        """O(entries) invariant check, the host-tier counterpart of
        PagePool.audit(): byte accounting exact, budget respected,
        pins only on live entries. Returns violation strings ([] =
        clean); raise_on_error raises MXNetError instead."""
        v = []
        total = 0
        for key, payload in self._entries.items():
            b = self._bytes.get(key)
            if b is None:
                v.append(f"entry {key!r} has no byte record")
                continue
            real = _payload_bytes(payload)
            if real != b:
                v.append(f"entry {key!r}: recorded {b} bytes, "
                         f"payload holds {real}")
            total += b
        if total != self.bytes_used:
            v.append(f"bytes_used {self.bytes_used} != entry sum {total}")
        if self.bytes_used > self.budget_bytes:
            v.append(f"bytes_used {self.bytes_used} over budget "
                     f"{self.budget_bytes}")
        for key, pins in self._pins.items():
            if key not in self._entries:
                v.append(f"pin on missing entry {key!r}")
            if pins < 1:
                v.append(f"entry {key!r}: non-positive pin count {pins}")
        if v and raise_on_error:
            raise MXNetError("host tier audit failed: " + "; ".join(v))
        return v

    def __repr__(self):
        return (f"HostPagePool(entries={self.num_entries}, "
                f"bytes={self.bytes_used}/{self.budget_bytes}, "
                f"evictions={self.evictions})")
