"""Ref-counted KV page allocator — explicit ownership for the page pool.

PR 1's engine gave every decode slot a fixed, implicit set of physical
pages (slot ``b`` owned pages ``[b*P, (b+1)*P)`` forever). Prefix reuse
(serving/prefix_cache.py) breaks that model: a physical page holding a
cached prompt prefix may be mapped into several slots' page tables at
once and must outlive all of them, so ownership has to be counted, not
assumed. ``PagePool`` is that ledger — a host-side allocator over the
``num_pages`` axis of the device pools in ``PagedKVCache``:

  * ``alloc(n)``      — take n free pages, each born with refcount 1
                        (the caller's lease).
  * ``incref(pages)`` — add a lease (a second slot mapping a shared
                        prefix page, serving/prefix_cache.py match()).
  * ``decref(pages)`` — drop a lease; returns the pages that hit zero.
                        Zero-ref pages are NOT auto-freed: the prefix
                        cache keeps them materialized (and evictable)
                        until its LRU policy says otherwise.
  * ``free(pages)``   — return zero-ref pages to the free list.
  * ``cow(page)``     — copy-on-write split decision: a shared page
                        about to be written must first be re-homed to a
                        fresh exclusive page (the engine performs the
                        device-side copy; the pool only does the
                        accounting).

The pool never touches device memory — it indexes it. All methods are
O(pages) numpy/list work on the host, called between compiled
dispatches. Invariants are enforced loudly (double free, refcount
underflow, incref of a free page all raise MXNetError): a silent
accounting bug here becomes silent KV corruption on device.
"""
from __future__ import annotations

from collections import deque

import numpy as np

from ..base import MXNetError
from ..analysis import loop_only, thread_safe

__all__ = ["PagePool", "PagePoolExhausted"]


class PagePoolExhausted(MXNetError):
    """alloc() could not satisfy the request. A distinct type because
    the engine supervisor treats exhaustion as BACKPRESSURE (requeue
    the admission and retry once pages drain — nobody's fault), not as
    a dispatch fault that blames the request."""


class PagePool:
    """Host-side ref-counted allocator over a pool of physical KV pages."""

    def __init__(self, num_pages, page_bytes=None):
        if num_pages < 1:
            raise MXNetError("PagePool needs at least one page")
        self.num_pages = int(num_pages)
        # optional bytes per page (KV slabs + dequant scales) — set by
        # from_bytes / the engine so capacity introspection can report
        # the pool in HBM terms
        self.page_bytes = int(page_bytes) if page_bytes else None
        self._refcount = np.zeros(self.num_pages, np.int32)
        self._allocated = np.zeros(self.num_pages, bool)
        self._free = deque(range(self.num_pages))

    @classmethod
    def from_bytes(cls, hbm_budget_bytes, page_bytes):
        """Byte-denominated sizing: as many whole pages as the HBM
        budget affords at ``page_bytes`` per page (one page's k+v slabs
        across all layers, plus the per-page dequant scales when the
        pools are quantized). Storing pages at int8 instead of fp32
        shrinks ``page_bytes`` ~4× — the freed budget comes back as
        MORE PAGES, i.e. real admitted-slot capacity, with no caller
        arithmetic."""
        if page_bytes < 1:
            raise MXNetError("from_bytes needs page_bytes >= 1")
        n = int(hbm_budget_bytes) // int(page_bytes)
        if n < 1:
            raise MXNetError(
                f"hbm_budget_bytes {int(hbm_budget_bytes)} below one "
                f"page ({int(page_bytes)} bytes)")
        return cls(n, page_bytes=page_bytes)

    # -- queries -----------------------------------------------------------
    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_allocated(self):
        return self.num_pages - len(self._free)

    def refcount(self, page):
        return int(self._refcount[page])

    def refcounts(self):
        """Copy of the (num_pages,) int32 refcount vector."""
        return self._refcount.copy()

    def shared_mask(self):
        """(num_pages,) bool: pages with more than one lease."""
        return self._refcount > 1

    def exclusive_mask(self):
        """(num_pages,) bool: pages with exactly one lease — the only
        pages a decode write may legally land in."""
        return self._refcount == 1

    # -- lifecycle ---------------------------------------------------------
    def _check(self, pages, allocated):
        for p in pages:
            if not 0 <= p < self.num_pages:
                raise MXNetError(f"page {p} outside pool "
                                 f"[0, {self.num_pages})")
            if bool(self._allocated[p]) != allocated:
                state = "allocated" if allocated else "free"
                raise MXNetError(f"page {p} is not {state}")

    @loop_only
    def alloc(self, n):
        """Take `n` free pages (refcount 1 each). Raises when the pool
        cannot satisfy the request — the caller (prefix cache) evicts
        and retries; the pool itself never reclaims."""
        if n < 0:
            raise MXNetError("alloc(n) needs n >= 0")
        if n > len(self._free):
            raise PagePoolExhausted(
                f"page pool exhausted: want {n} pages, {len(self._free)} "
                f"free of {self.num_pages} (evict cached prefixes or "
                "grow prefix_cache_pages)")
        pages = [self._free.popleft() for _ in range(n)]
        self._refcount[pages] = 1
        self._allocated[pages] = True
        return pages

    @loop_only
    def incref(self, pages):
        """Add one lease per page (pages must be live)."""
        pages = list(pages)
        self._check(pages, allocated=True)
        for p in pages:
            if self._refcount[p] < 1:
                raise MXNetError(f"incref of zero-ref page {p} (only the "
                                 "prefix cache may resurrect idle pages)")
        np.add.at(self._refcount, pages, 1)
        return pages

    @loop_only
    def adopt(self, pages):
        """Add one lease per page where refcount may be 0 (the prefix
        cache re-leasing an idle cached page on a match)."""
        pages = list(pages)
        self._check(pages, allocated=True)
        np.add.at(self._refcount, pages, 1)
        return pages

    @loop_only
    def decref(self, pages):
        """Drop one lease per page; returns the pages that reached zero
        (still allocated — pass them to free() to recycle)."""
        pages = list(pages)
        self._check(pages, allocated=True)
        for p in pages:
            if self._refcount[p] < 1:
                raise MXNetError(f"refcount underflow on page {p}")
        np.subtract.at(self._refcount, pages, 1)
        return [p for p in pages if self._refcount[p] == 0]

    @loop_only
    def free(self, pages):
        """Return zero-ref pages to the free list."""
        pages = list(pages)
        self._check(pages, allocated=True)
        for p in pages:
            if self._refcount[p] != 0:
                raise MXNetError(f"freeing page {p} with live refcount "
                                 f"{int(self._refcount[p])}")
        for p in pages:
            self._allocated[p] = False
            self._free.append(p)
        return pages

    @loop_only
    def cow(self, page):
        """Copy-on-write split: given a page the caller wants to WRITE,
        return (dst_page, needs_copy). Exclusive pages come straight
        back (write in place). Shared pages cost one fresh page — the
        caller must copy the slab on device, then holds dst exclusively;
        the caller's lease on `page` is dropped here."""
        self._check([page], allocated=True)
        if self._refcount[page] == 1:
            return page, False
        (dst,) = self.alloc(1)
        self.decref([page])
        return dst, True

    @thread_safe
    def audit(self, leases=None, members=(), raise_on_error=False,
              scales=None, host_keys=None, spilled_keys=None):
        """O(pages) invariant check — the supervisor runs this after
        every caught dispatch fault, and tests run it at drain.

        leases: optional iterable of page-id rows (one per mapped slot
        table). Tree membership adds no refcount (the prefix cache
        parks idle pages at refcount 0), so when leases are given,
        refcount == slot-lease count must hold exactly for every
        allocated page, and an allocated page with refcount 0 must be
        a tree member — anything else is a leaked page.
        members: page ids the prefix-cache radix tree owns.
        scales: optional (num_pages,) per-page quantization-scale
        summary (max |scale| over layers/heads, host-side) for int8
        pools. Scale leaves must stay lease-consistent: one entry per
        pool page, finite and non-negative everywhere — a NaN/inf or
        negative scale is corrupted quantization state that would
        silently poison every future read of that page.
        host_keys / spilled_keys: the cross-TIER check (give both or
        neither). host_keys = radix-node keys currently held by the
        host spill tier; spilled_keys = keypaths of the radix tree's
        spilled nodes. The sets must match exactly: a host payload
        with no spilled node is a leaked host page (unreachable, yet
        burning budget), a spilled node with no payload is lost state
        a match() would page garbage in for.

        Returns the list of violation strings ([] = clean); with
        raise_on_error=True a non-empty list raises MXNetError instead.
        """
        v = []
        if host_keys is not None or spilled_keys is not None:
            host_keys = set(host_keys or ())
            spilled_keys = set(spilled_keys or ())
            for k in sorted(host_keys - spilled_keys, key=repr):
                v.append(f"host tier holds payload for {k!r} but no "
                         "spilled tree node references it (leaked "
                         "across tiers)")
            for k in sorted(spilled_keys - host_keys, key=repr):
                v.append(f"spilled tree node {k!r} has no host-tier "
                         "payload (lost state)")
        if scales is not None:
            scales = np.asarray(scales)
            if scales.shape != (self.num_pages,):
                v.append(f"scale leaf covers {scales.shape} pages, pool "
                         f"has {self.num_pages}")
            else:
                bad = ~np.isfinite(scales) | (scales < 0)
                for p in np.nonzero(bad)[0]:
                    v.append(f"page {int(p)}: corrupt quant scale "
                             f"{float(scales[p])!r}")
        free = list(self._free)
        free_set = set(free)
        if len(free) != len(free_set):
            v.append(f"free list holds duplicates "
                     f"({len(free) - len(free_set)})")
        members = set(int(p) for p in members)
        for p in free_set:
            if not 0 <= p < self.num_pages:
                v.append(f"free list holds out-of-range page {p}")
        for p in range(self.num_pages):
            alloc = bool(self._allocated[p])
            ref = int(self._refcount[p])
            if alloc == (p in free_set):
                v.append(f"page {p}: allocated={alloc} but "
                         f"{'in' if p in free_set else 'not in'} "
                         "free list")
            if ref < 0:
                v.append(f"page {p}: negative refcount {ref}")
            if ref > 0 and not alloc:
                v.append(f"page {p}: refcount {ref} on free page")
            if p in members and not alloc:
                v.append(f"page {p}: tree member but not allocated")
        if leases is not None:
            lease_count = np.zeros(self.num_pages, np.int64)
            for row in leases:
                for p in row:
                    p = int(p)
                    if not 0 <= p < self.num_pages:
                        v.append(f"slot table references out-of-range "
                                 f"page {p}")
                        continue
                    lease_count[p] += 1
            for p in range(self.num_pages):
                ref = int(self._refcount[p])
                n = int(lease_count[p])
                if n and p in free_set:
                    v.append(f"page {p}: {n} slot lease(s) on a free "
                             "page")
                    continue
                if not self._allocated[p]:
                    continue
                if ref != n:
                    v.append(f"page {p}: refcount {ref} != {n} slot "
                             "lease(s)")
                if ref == 0 and n == 0 and p not in members:
                    v.append(f"page {p}: allocated with no lease and "
                             "no tree membership (leaked)")
        if v and raise_on_error:
            raise MXNetError("page pool audit failed: " + "; ".join(v))
        return v

    def __repr__(self):
        return (f"PagePool(pages={self.num_pages}, free={self.num_free}, "
                f"shared={int((self._refcount > 1).sum())})")
