"""SLO-aware admission and load-shedding policy.

PR 5/6 built the control SIGNALS — queue-depth and TTFT gauges, the
admission-capacity estimate, the flight recorder. This module closes
the loop: a `SheddingPolicy` attached to a `ServingEngine`
(``ServingEngine(..., policy=SheddingPolicy(...))``) reads those live
signals and decides, BEFORE a request queues, whether to admit it,
down-prioritize it, or shed it — and, under sustained overload, flips
the engine into graceful degradation.

Overload levels (assessed from live telemetry on every submit and
every step):

  * 0 OK        — queue below the low watermark, TTFT inside the SLO.
  * 1 ELEVATED  — queue at/above the low watermark, or the recent TTFT
                  p99 is past `ttft_slo_ms`, or requests are queued
                  with zero admission-capacity headroom. New
                  default-priority work is DOWN-PRIORITIZED one class
                  (interactive class-0 traffic is untouched).
  * 2 OVERLOADED — queue at/above the high watermark (or TTFT blown
                  with a backlog). Everything below the protected
                  priority floor is SHED at submit with
                  `ShedError(reason="overload")`; deadline-infeasible
                  requests (the drain-rate estimate says they cannot
                  start in time) are shed with reason="deadline".

Degradation: `degrade_after` consecutive overloaded steps latch the
engine degraded — speculative decoding is suspended (wasted verify
FLOPs are pure loss when demand exceeds capacity; the engine falls
back to the plain decode program and re-enables speculation on
recovery), `serving_degraded`/`/healthz` flip, and a breadcrumb lands
in the flight ring. `recover_after` consecutive non-overloaded steps
clear it. All thresholds default from engine shape (watermarks at
1x/2x num_slots) so `SheddingPolicy()` is usable as-is.

The policy is pure host arithmetic over a handful of counters — its
in-path cost is bounded by the <2% A/B budget the overload bench
(`bench.py gpt2_serving_overload`) measures.
"""
from __future__ import annotations

import math

__all__ = ["SheddingPolicy"]


class SheddingPolicy:
    """Telemetry-driven admission control for one ServingEngine.

    ttft_slo_ms: recent TTFT p99 past this marks the engine elevated
        (None disables the TTFT signal).
    queue_low / queue_high: queued-request watermarks for elevated /
        overloaded (defaults: num_slots / 2*num_slots at attach time).
    shed_priority_floor: classes <= this are never shed by overload
        (deadline-infeasible shedding still applies; default 0 keeps
        only the interactive class protected).
    min_ttft_samples: TTFT observations required before the p99 signal
        is trusted.
    deadline_headroom: shed a request whose deadline budget is below
        headroom x estimated queue wait (drain-rate based; only while
        elevated or worse — the estimate is noise when idle).
    degrade_after / recover_after: consecutive step ticks at/below
        level 2 that latch / clear graceful degradation.
    tenant_queue_share: while elevated or worse, shed a request whose
        tenant already holds more than this fraction of the queue
        (ShedError reason="tenant_share") — one tenant's burst must
        not starve the others of queue capacity. None disables the
        signal; it only ever fires for requests that carry a tenant.
    preempt: while OVERLOADED with every slot busy and more-urgent
        work queued, allow the engine to preempt the least-urgent
        running request — its exclusive KV pages swap to the host
        tier and it resumes bit-identically later (engine
        `_preempt_slot`; needs `host_kv_bytes` on the engine). Off by
        default: preemption beats shedding only when the host tier
        exists to keep the partial work.
    slo: an SLOEngine (default: the process-global
        `telemetry.slo.slo_engine`; pass False to disable). Any
        objective whose FAST window is burning error budget at >=
        `fast_burn` counts toward overload exactly like a blown TTFT
        p99 — the multi-window burn rate reacts in ~1 min where the
        raw p99 needs the histogram to rotate, so shedding starts
        while there is still budget left to protect. Evaluation is
        throttled to `slo_eval_interval_s` (assess runs on every
        submit AND every step; burn rates move on window timescales).
    """

    def __init__(self, ttft_slo_ms=None, queue_low=None, queue_high=None,
                 shed_priority_floor=0, min_ttft_samples=8,
                 deadline_headroom=1.0, degrade_after=3,
                 recover_after=6, tenant_queue_share=None,
                 preempt=False, slo=None, slo_eval_interval_s=0.25):
        self.ttft_slo_ms = ttft_slo_ms
        self.queue_low = queue_low
        self.queue_high = queue_high
        self.shed_priority_floor = int(shed_priority_floor)
        self.min_ttft_samples = int(min_ttft_samples)
        self.deadline_headroom = float(deadline_headroom)
        self.degrade_after = int(degrade_after)
        self.recover_after = int(recover_after)
        self.preempt = bool(preempt)
        self.tenant_queue_share = None if tenant_queue_share is None \
            else float(tenant_queue_share)
        if self.tenant_queue_share is not None \
                and not 0.0 < self.tenant_queue_share <= 1.0:
            raise ValueError("tenant_queue_share must be in (0, 1]")
        self.slo = slo             # None → global engine; False → off
        self.slo_eval_interval_s = float(slo_eval_interval_s)
        self._slo_last = None      # (clock_t, frozenset(burning names))
        self._hot = 0              # consecutive overloaded ticks
        self._cool = 0             # consecutive non-overloaded ticks
        self.level = 0
        self.downgrades = 0

    # -- signals -----------------------------------------------------------
    def _watermarks(self, engine):
        low = self.queue_low if self.queue_low is not None \
            else engine.num_slots
        high = self.queue_high if self.queue_high is not None \
            else 2 * engine.num_slots
        return max(1, int(low)), max(2, int(high))

    def _ttft_blown(self, engine):
        if self.ttft_slo_ms is None:
            return False
        h = engine._metrics["ttft"]
        if h.count < self.min_ttft_samples:
            return False
        p99 = h.percentile(99)
        return (not math.isnan(p99)) and p99 * 1e3 > self.ttft_slo_ms

    def _slo_burning(self, engine):
        """Objective names whose fast window is burning, re-evaluated
        at most every `slo_eval_interval_s` (assess runs per submit
        and per step; burn rates only move on window timescales)."""
        if self.slo is False:
            return ()
        eng = self.slo
        if eng is None:
            from .. import telemetry
            eng = telemetry.slo.slo_engine
        if not eng.objectives:
            return ()
        t = engine._clock()
        if self._slo_last is not None \
                and t - self._slo_last[0] < self.slo_eval_interval_s:
            return self._slo_last[1]
        burning = tuple(eng.fast_burning())
        self._slo_last = (t, burning)
        return burning

    def assess(self, engine):
        """Current overload level from live telemetry (also stored on
        `.level` and published as serving_overload_level)."""
        q = engine.scheduler.num_queued
        low, high = self._watermarks(engine)
        ttft_blown = self._ttft_blown(engine)
        burning = bool(self._slo_burning(engine))
        if q >= high or ((ttft_blown or burning) and q >= low):
            level = 2
        elif q >= low or ttft_blown or burning or (
                q > 0 and engine.admission_capacity_estimate()
                <= engine.scheduler.num_active):
            level = 1
        else:
            level = 0
        self.level = level
        engine._metrics["overload_level"].set(level)
        return level

    # -- hooks the engine calls --------------------------------------------
    def on_submit(self, engine, request, now):
        """Admission decision for one request, BEFORE it queues.
        Returns (action, reason): ("admit", None), ("downgrade", ...)
        — request.priority already bumped — or ("shed", reason)."""
        level = self.assess(engine)
        if level >= 2 and request.priority > self.shed_priority_floor:
            return "shed", "overload"
        if level >= 1 and request.deadline_ms is not None:
            wait = engine.estimated_queue_wait()
            if wait is not None and request.deadline_ms / 1e3 \
                    < self.deadline_headroom * wait:
                return "shed", "deadline"
        if level >= 1 and self.tenant_queue_share is not None \
                and request.tenant is not None:
            q = engine.scheduler.num_queued
            mine = engine.scheduler.tenant_queued(request.tenant)
            if q and mine / q > self.tenant_queue_share:
                return "shed", "tenant_share"
        if level >= 1 and request.priority >= 1 \
                and request.priority < engine.scheduler.num_priorities - 1:
            request.priority += 1
            self.downgrades += 1
            return "downgrade", "elevated"
        return "admit", None

    def preempt_victim(self, engine):
        """Pick one running slot to swap out for more-urgent queued
        work, or None. Fires only when `preempt` is on, the engine is
        OVERLOADED (uses the level from this step's assess — call
        after on_step), every slot is busy, and some queued request is
        STRICTLY more urgent than some running one. The victim is the
        least-urgent running request (largest priority number, then
        fewest generated tokens — minimal swapped state); requests
        below the shed floor, mid-replay, or already carrying a
        pending restart plan are never preempted."""
        if not self.preempt or self.level < 2:
            return None
        sched = engine.scheduler
        if sched.num_free > 0:
            return None
        queued = [r.priority for r in sched.queued_requests()]
        if not queued:
            return None
        best_queued = min(queued)
        victim = None
        for slot in sched.active_slots:
            req = sched.request_at(slot)
            if req is None or engine._pending[slot] is not None:
                continue             # mid-prefill/replay: let it land
            if req.priority <= self.shed_priority_floor:
                continue
            if req.priority <= best_queued:
                continue             # only yield to strictly more urgent
            if victim is None or (req.priority, -len(req.output_tokens)) \
                    > (victim[1].priority, -len(victim[1].output_tokens)):
                victim = (slot, req)
        return None if victim is None else victim[0]

    def on_step(self, engine, now):
        """Per-step degradation tick: latch after `degrade_after`
        consecutive overloaded assessments, clear after
        `recover_after` calm ones."""
        level = self.assess(engine)
        if level >= 2:
            self._hot += 1
            self._cool = 0
            if self._hot >= self.degrade_after:
                engine._set_degraded(True, "overload")
        else:
            self._cool += 1
            self._hot = 0
            if self._cool >= self.recover_after:
                engine._set_degraded(False)
        return level

    def snapshot(self):
        """JSON-able config+state for /statusz and flight dumps."""
        return {
            "ttft_slo_ms": self.ttft_slo_ms,
            "queue_low": self.queue_low,
            "queue_high": self.queue_high,
            "shed_priority_floor": self.shed_priority_floor,
            "deadline_headroom": self.deadline_headroom,
            "degrade_after": self.degrade_after,
            "recover_after": self.recover_after,
            "tenant_queue_share": self.tenant_queue_share,
            "preempt": self.preempt,
            "slo_eval_interval_s": self.slo_eval_interval_s,
            "slo_burning": list(self._slo_last[1])
            if self._slo_last else [],
            "level": self.level,
            "downgrades": self.downgrades,
        }
