"""Radix-tree prompt prefix cache over ref-counted KV pages.

Production traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn history (the Gemma-on-TPU
serving study calls the workload prefill-bound, PAPERS.md). Because
every attention read in the serving path already goes through a
per-slot page table (PagedKVCache + the ragged paged-attention kernel),
a cached prefix can be attached to a new request by *page-table
surgery* alone: map the shared physical pages into the slot's table,
set the cache length past them, and prefill only the uncached suffix.
Zero recompute, zero copy — repeated-prefix prefill cost drops from
O(prompt) to O(suffix).

Structure: a radix tree at PAGE granularity. Each edge is one full
page's worth of token ids (``page_size`` tokens, as a tuple key); each
node owns exactly one physical page in the PagePool holding that
chunk's K/V for every layer. Partial trailing pages are never cached —
a node's page is always complete and therefore read-only forever,
which is what makes sharing safe (see the CoW rule in engine._admit
for the one exception: a fully-cached prompt whose last token must be
re-run for logits).

Ownership protocol (see page_pool.py):
  * ``match(tokens)`` walks the tree and takes one lease per matched
    page for the caller; the engine maps those pages into the slot.
  * ``insert(tokens, pages)`` adopts the slot's freshly prefilled full
    prompt pages as tree nodes — membership, not a lease: when the
    slot later releases, the page's refcount drops to zero but the
    page stays materialized in the tree, instantly re-attachable.
  * ``release(pages)`` drops the slot's leases; zero-ref pages NOT in
    the tree are freed, zero-ref tree pages become EVICTABLE.
  * Eviction is LRU-by-leaf: only leaves (no children — an interior
    node's chunk is a prefix of live entries) with zero leases are
    candidates, oldest touch first. ``budget_pages`` bounds the
    tree's page footprint so churn can never OOM the pool.

Tiered mode (docs/SERVING.md "Tiered KV cache"): with ``evict_hook``
installed, ``_evict_one`` offers the victim's payload to the host
tier before freeing the device page. A hook that answers True took
the payload — the node survives as a SPILLED node (``page=None``,
out of ``_by_page``), and a later ``match`` that walks onto it
allocates a fresh device page and asks ``pagein_hook`` to restore
the payload, so a radix hit on spilled state costs one copy instead
of a full suffix re-prefill. Two invariants keep the tiers honest:

  * the RESIDENT node set is prefix-closed along every root path
    (spill only strips from the bottom up; insert never grows a
    resident node under a spilled ancestor), so a match walk is
    always "resident prefix, then spilled run";
  * a spilled node in the tree always has a live host payload — the
    host pool's LRU may only drop one through ``drop_spilled``,
    which detaches the node (marking it ``dead`` for anyone holding
    a reference, e.g. a preempted request's swap record).
"""
from __future__ import annotations

import itertools

from ..base import MXNetError
from .page_pool import PagePool, PagePoolExhausted

__all__ = ["PrefixCache"]


class _Node:
    __slots__ = ("parent", "key", "page", "children", "stamp",
                 "spilled", "dead")

    def __init__(self, parent=None, key=None, page=None):
        self.parent = parent
        self.key = key          # tuple of page_size token ids (edge label)
        self.page = page        # physical page id, None while spilled
        self.children = {}      # chunk tuple -> _Node
        self.stamp = 0          # LRU touch stamp (monotonic)
        self.spilled = False    # payload lives in the host tier
        self.dead = False       # detached from the tree (evicted/dropped)


class PrefixCache:
    """Radix tree over token-id prefixes; nodes own full KV pages."""

    def __init__(self, pool, page_size, budget_pages=None):
        if not isinstance(pool, PagePool):
            raise MXNetError("PrefixCache needs a PagePool")
        if page_size < 1:
            raise MXNetError("page_size must be >= 1")
        self.pool = pool
        self.page_size = int(page_size)
        self.budget_pages = None if budget_pages is None \
            else int(budget_pages)
        self._root = _Node()
        self._by_page = {}               # page id -> RESIDENT node
        self._clock = itertools.count(1)
        # tier seams (engine-installed; None = single-tier behaviour)
        self.evict_hook = None           # (keypath, page) -> bool (spilled?)
        self.pagein_hook = None          # [(keypath, page)] -> None
        # counters (the engine mirrors these into mx.telemetry)
        self.hits = 0                    # match() calls returning >= 1 page
        self.misses = 0
        self.tokens_matched = 0
        self.evicted_pages = 0           # discarded outright (both modes)
        self.spilled_pages = 0           # cumulative spills to host
        self.paged_in_pages = 0          # cumulative host -> device restores
        self.num_spilled = 0             # spilled nodes currently in-tree

    # -- introspection -----------------------------------------------------
    @property
    def num_pages(self):
        """Device pages currently owned by tree nodes (leased or idle).
        Spilled nodes hold no device page and are not counted — this is
        what ``budget_pages`` bounds."""
        return len(self._by_page)

    @property
    def num_resident(self):
        """Alias of num_pages, paired with num_spilled for the
        prefix_resident_pages / prefix_spilled_pages gauges."""
        return len(self._by_page)

    def _keypath(self, node):
        """Root-to-node tuple of chunk keys — the host-tier key."""
        path = []
        while node.parent is not None:
            path.append(node.key)
            node = node.parent
        return tuple(reversed(path))

    def spilled_keypaths(self):
        """Keypaths of every spilled node in the tree — the audit's
        ground truth for the cross-tier check (PagePool.audit
        host_keys/spilled_keys): these must match the host tier's
        node keys exactly."""
        out = []
        stack = [(self._root, ())]
        while stack:
            node, path = stack.pop()
            for key, child in node.children.items():
                cp = path + (key,)
                if child.spilled:
                    out.append(cp)
                stack.append((child, cp))
        return out

    def member_mask(self):
        """(num_pages,) bool over the pool: True for tree-owned pages.
        The engine ORs this into the decode program's page_lock so a
        cached page can never be clobbered by a stray write."""
        import numpy as np
        mask = np.zeros(self.pool.num_pages, bool)
        if self._by_page:
            mask[list(self._by_page)] = True
        return mask

    def contains(self, tokens):
        """True when every full page of `tokens` is cached."""
        node = self._root
        for chunk in self._chunks(tokens):
            node = node.children.get(chunk)
            if node is None:
                return False
        return True

    def _chunks(self, tokens):
        S = self.page_size
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + S])
                for i in range(0, len(toks) - len(toks) % S, S)]

    # -- the hot path ------------------------------------------------------
    def match(self, tokens):
        """Longest-prefix match at page granularity. Returns the matched
        physical pages in prefix order, each carrying ONE new lease for
        the caller (release() them when the slot frees). Spilled nodes
        on the matched path are paged back in from the host tier (a
        fresh page per node; its birth refcount IS the caller's lease) —
        on pool exhaustion the walk stops there and the match is the
        restorable prefix. Touches the matched path's LRU stamps."""
        stamp = next(self._clock)
        node, pages, path = self._root, [], []
        pending = []                 # spilled (keypath, node) tail run
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            path.append(chunk)
            if child.spilled:
                pending.append((tuple(path), child))
            elif pending:
                break                # resident under spilled ancestor:
                                     # cannot happen (prefix-closure),
                                     # stop rather than corrupt order
            else:
                child.stamp = stamp
                pages.append(child.page)
            node = child
        if pages:
            self.pool.adopt(pages)       # lease, even if the page was idle
        pages += self._pagein(pending, stamp)
        if pages:
            self.hits += 1
            self.tokens_matched += len(pages) * self.page_size
        else:
            self.misses += 1
        return pages

    def _pagein(self, pending, stamp):
        """Restore a run of spilled nodes: allocate a device page per
        node (evicting idle residents if needed), hand the batch to
        pagein_hook, and re-register the nodes as resident. Returns the
        restored pages in prefix order; stops early (prefix kept) on
        pool exhaustion or when reclaim's own spill traffic drops a
        pending node's payload from the host LRU."""
        if not pending or self.pagein_hook is None:
            return []
        staged = []                  # (keypath, node, page)
        for keypath, child in pending:
            if child.dead:
                break
            try:
                page = self.pool.alloc(1)[0]
            except PagePoolExhausted:
                if not self.reclaim(1):
                    break
                page = self.pool.alloc(1)[0]
            if child.dead:           # dropped while we reclaimed
                self.pool.free([page])
                break
            staged.append((keypath, child, page))
        if not staged:
            return []
        try:
            self.pagein_hook([(kp, pg) for kp, _, pg in staged])
        except BaseException:
            self.pool.free([pg for _, _, pg in staged])
            raise
        pages = []
        for keypath, child, page in staged:
            child.page = int(page)
            child.spilled = False
            child.stamp = stamp
            self._by_page[child.page] = child
            self.num_spilled -= 1
            self.paged_in_pages += 1
            pages.append(child.page)
        return pages

    def insert(self, tokens, pages):
        """Adopt the slot's prompt pages into the tree. ``pages`` maps
        1:1 onto the full-page chunks of ``tokens`` (the slot's table
        prefix after prefill). Chunks already cached keep their existing
        node/page — the supplied duplicate page stays slot-owned and is
        freed at release. Returns the number of pages adopted."""
        chunks = self._chunks(tokens)
        if len(pages) < len(chunks):
            raise MXNetError(f"insert: {len(chunks)} full pages of tokens "
                             f"but only {len(pages)} pages supplied")
        stamp = next(self._clock)
        node, adopted = self._root, 0
        for chunk, page in zip(chunks, pages):
            child = node.children.get(chunk)
            if child is None:
                if node.spilled:
                    # never grow a resident node under a spilled
                    # ancestor — the resident set must stay
                    # prefix-closed for match()'s walk order
                    break
                if page in self._by_page:
                    raise MXNetError(f"page {page} already owned by "
                                     "another tree node")
                child = _Node(parent=node, key=chunk, page=int(page))
                node.children[chunk] = child
                self._by_page[child.page] = child
                adopted += 1
            child.stamp = stamp
            node = child
        self.enforce_budget()
        return adopted

    def release(self, pages):
        """Drop one lease per page (a slot freeing its table). Zero-ref
        pages outside the tree go back to the free list; zero-ref tree
        pages stay cached (evictable)."""
        zeroed = self.pool.decref(pages)
        stray = [p for p in zeroed if p not in self._by_page]
        if stray:
            self.pool.free(stray)
        self.enforce_budget()

    # -- eviction ----------------------------------------------------------
    def _discard(self, node):
        """Detach a childless resident node and free its page."""
        del node.parent.children[node.key]
        del self._by_page[node.page]
        node.dead = True
        self.pool.free([node.page])
        self.evicted_pages += 1

    def _evict_one(self):
        """Reclaim the least-recently-touched idle page. With an
        evict_hook installed the victim's payload is offered to the
        host tier first: True from the hook spills (the node survives,
        pageless), False falls back to plain discard for childless
        nodes — an interior node the hook declines is skipped, because
        discarding it would orphan its spilled subtree. Without a
        hook: original LRU-by-leaf discard. Returns True when a device
        page was reclaimed."""
        if self.evict_hook is None:
            best = None
            for page, node in self._by_page.items():
                if node.children or self.pool.refcount(page) != 0:
                    continue
                if best is None or node.stamp < best.stamp:
                    best = node
            if best is None:
                return False
            self._discard(best)
            return True
        # tiered: any idle node with no RESIDENT children is a victim
        # (spilled descendants are fine — stripping bottom-up keeps the
        # resident set prefix-closed), oldest touch first
        cands = [node for page, node in self._by_page.items()
                 if self.pool.refcount(page) == 0
                 and not any(not c.spilled
                             for c in node.children.values())]
        cands.sort(key=lambda n: n.stamp)
        for node in cands:
            # the hook gathers the device payload BEFORE we free it
            if self.evict_hook(self._keypath(node), node.page):
                page = node.page
                del self._by_page[page]
                node.page = None
                node.spilled = True
                self.pool.free([page])
                self.num_spilled += 1
                self.spilled_pages += 1
                return True
            if not node.children:
                self._discard(node)
                return True
        return False

    def drop_spilled(self, keypath):
        """Host-LRU callback: detach the childless spilled node at
        `keypath` so its host payload may be dropped. Returns False —
        vetoing the host eviction — when the node is absent, resident,
        or still has children (its subtree's keys embed this path);
        the dropped node is marked ``dead`` for any swap record still
        holding it."""
        node = self._root
        for chunk in keypath:
            node = node.children.get(chunk)
            if node is None:
                return False
        if not node.spilled or node.children:
            return False
        del node.parent.children[node.key]
        node.dead = True
        self.num_spilled -= 1
        return True

    def enforce_budget(self):
        """Evict idle leaves until the tree fits its page budget (leased
        pages can push past it transiently — they are pinned)."""
        if self.budget_pages is None:
            return
        while len(self._by_page) > self.budget_pages:
            if not self._evict_one():
                break

    def reclaim(self, n_free):
        """Evict idle leaves until the POOL has `n_free` free pages (an
        admission that needs pages the free list cannot cover). Returns
        True when the target was reached."""
        while self.pool.num_free < n_free:
            if not self._evict_one():
                return False
        return True

    def clear(self):
        """Drop every idle page (leased pages survive — they belong to
        live slots)."""
        while self._evict_one():
            pass

    def __repr__(self):
        return (f"PrefixCache(pages={self.num_pages}, "
                f"spilled={self.num_spilled}, "
                f"budget={self.budget_pages}, hits={self.hits}, "
                f"misses={self.misses}, evicted={self.evicted_pages})")
