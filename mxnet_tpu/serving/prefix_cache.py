"""Radix-tree prompt prefix cache over ref-counted KV pages.

Production traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn history (the Gemma-on-TPU
serving study calls the workload prefill-bound, PAPERS.md). Because
every attention read in the serving path already goes through a
per-slot page table (PagedKVCache + the ragged paged-attention kernel),
a cached prefix can be attached to a new request by *page-table
surgery* alone: map the shared physical pages into the slot's table,
set the cache length past them, and prefill only the uncached suffix.
Zero recompute, zero copy — repeated-prefix prefill cost drops from
O(prompt) to O(suffix).

Structure: a radix tree at PAGE granularity. Each edge is one full
page's worth of token ids (``page_size`` tokens, as a tuple key); each
node owns exactly one physical page in the PagePool holding that
chunk's K/V for every layer. Partial trailing pages are never cached —
a node's page is always complete and therefore read-only forever,
which is what makes sharing safe (see the CoW rule in engine._admit
for the one exception: a fully-cached prompt whose last token must be
re-run for logits).

Ownership protocol (see page_pool.py):
  * ``match(tokens)`` walks the tree and takes one lease per matched
    page for the caller; the engine maps those pages into the slot.
  * ``insert(tokens, pages)`` adopts the slot's freshly prefilled full
    prompt pages as tree nodes — membership, not a lease: when the
    slot later releases, the page's refcount drops to zero but the
    page stays materialized in the tree, instantly re-attachable.
  * ``release(pages)`` drops the slot's leases; zero-ref pages NOT in
    the tree are freed, zero-ref tree pages become EVICTABLE.
  * Eviction is LRU-by-leaf: only leaves (no children — an interior
    node's chunk is a prefix of live entries) with zero leases are
    candidates, oldest touch first. ``budget_pages`` bounds the
    tree's page footprint so churn can never OOM the pool.
"""
from __future__ import annotations

import itertools

from ..base import MXNetError
from .page_pool import PagePool

__all__ = ["PrefixCache"]


class _Node:
    __slots__ = ("parent", "key", "page", "children", "stamp")

    def __init__(self, parent=None, key=None, page=None):
        self.parent = parent
        self.key = key          # tuple of page_size token ids (edge label)
        self.page = page        # physical page id in the pool
        self.children = {}      # chunk tuple -> _Node
        self.stamp = 0          # LRU touch stamp (monotonic)


class PrefixCache:
    """Radix tree over token-id prefixes; nodes own full KV pages."""

    def __init__(self, pool, page_size, budget_pages=None):
        if not isinstance(pool, PagePool):
            raise MXNetError("PrefixCache needs a PagePool")
        if page_size < 1:
            raise MXNetError("page_size must be >= 1")
        self.pool = pool
        self.page_size = int(page_size)
        self.budget_pages = None if budget_pages is None \
            else int(budget_pages)
        self._root = _Node()
        self._by_page = {}               # page id -> node
        self._clock = itertools.count(1)
        # counters (the engine mirrors these into mx.telemetry)
        self.hits = 0                    # match() calls returning >= 1 page
        self.misses = 0
        self.tokens_matched = 0
        self.evicted_pages = 0

    # -- introspection -----------------------------------------------------
    @property
    def num_pages(self):
        """Pages currently owned by tree nodes (leased or idle)."""
        return len(self._by_page)

    def member_mask(self):
        """(num_pages,) bool over the pool: True for tree-owned pages.
        The engine ORs this into the decode program's page_lock so a
        cached page can never be clobbered by a stray write."""
        import numpy as np
        mask = np.zeros(self.pool.num_pages, bool)
        if self._by_page:
            mask[list(self._by_page)] = True
        return mask

    def contains(self, tokens):
        """True when every full page of `tokens` is cached."""
        node = self._root
        for chunk in self._chunks(tokens):
            node = node.children.get(chunk)
            if node is None:
                return False
        return True

    def _chunks(self, tokens):
        S = self.page_size
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + S])
                for i in range(0, len(toks) - len(toks) % S, S)]

    # -- the hot path ------------------------------------------------------
    def match(self, tokens):
        """Longest-prefix match at page granularity. Returns the matched
        physical pages in prefix order, each carrying ONE new lease for
        the caller (release() them when the slot frees). Touches the
        matched path's LRU stamps."""
        stamp = next(self._clock)
        node, pages = self._root, []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.stamp = stamp
            pages.append(child.page)
            node = child
        if pages:
            self.pool.adopt(pages)       # lease, even if the page was idle
            self.hits += 1
            self.tokens_matched += len(pages) * self.page_size
        else:
            self.misses += 1
        return pages

    def insert(self, tokens, pages):
        """Adopt the slot's prompt pages into the tree. ``pages`` maps
        1:1 onto the full-page chunks of ``tokens`` (the slot's table
        prefix after prefill). Chunks already cached keep their existing
        node/page — the supplied duplicate page stays slot-owned and is
        freed at release. Returns the number of pages adopted."""
        chunks = self._chunks(tokens)
        if len(pages) < len(chunks):
            raise MXNetError(f"insert: {len(chunks)} full pages of tokens "
                             f"but only {len(pages)} pages supplied")
        stamp = next(self._clock)
        node, adopted = self._root, 0
        for chunk, page in zip(chunks, pages):
            child = node.children.get(chunk)
            if child is None:
                if page in self._by_page:
                    raise MXNetError(f"page {page} already owned by "
                                     "another tree node")
                child = _Node(parent=node, key=chunk, page=int(page))
                node.children[chunk] = child
                self._by_page[child.page] = child
                adopted += 1
            child.stamp = stamp
            node = child
        self.enforce_budget()
        return adopted

    def release(self, pages):
        """Drop one lease per page (a slot freeing its table). Zero-ref
        pages outside the tree go back to the free list; zero-ref tree
        pages stay cached (evictable)."""
        zeroed = self.pool.decref(pages)
        stray = [p for p in zeroed if p not in self._by_page]
        if stray:
            self.pool.free(stray)
        self.enforce_budget()

    # -- eviction ----------------------------------------------------------
    def _evict_one(self):
        """Free the least-recently-touched idle leaf. Returns True when
        a page was reclaimed."""
        best = None
        for page, node in self._by_page.items():
            if node.children or self.pool.refcount(page) != 0:
                continue
            if best is None or node.stamp < best.stamp:
                best = node
        if best is None:
            return False
        del best.parent.children[best.key]
        del self._by_page[best.page]
        self.pool.free([best.page])
        self.evicted_pages += 1
        return True

    def enforce_budget(self):
        """Evict idle leaves until the tree fits its page budget (leased
        pages can push past it transiently — they are pinned)."""
        if self.budget_pages is None:
            return
        while len(self._by_page) > self.budget_pages:
            if not self._evict_one():
                break

    def reclaim(self, n_free):
        """Evict idle leaves until the POOL has `n_free` free pages (an
        admission that needs pages the free list cannot cover). Returns
        True when the target was reached."""
        while self.pool.num_free < n_free:
            if not self._evict_one():
                return False
        return True

    def clear(self):
        """Drop every idle page (leased pages survive — they belong to
        live slots)."""
        while self._evict_one():
            pass

    def __repr__(self):
        return (f"PrefixCache(pages={self.num_pages}, "
                f"budget={self.budget_pages}, hits={self.hits}, "
                f"misses={self.misses}, evicted={self.evicted_pages})")
