"""Front-of-house router over N in-process ServingEngine replicas.

One engine is one point of failure: a wedged or killed replica takes
every queued and in-flight request with it. `ServingRouter` fronts a
fleet (docs/SERVING.md "Multi-replica serving & failover"):

  * PLACEMENT — radix-prefix affinity: the first page of prompt tokens
    is rendezvous-hashed over the routable replicas, so requests that
    share a prompt prefix land on the replica that already holds its
    pages (multiplying the prefix cache's hit rate under multi-user
    traffic), with load-aware SPILL to the least-loaded ready replica
    when the affinity target's queue is deep. Routable = up, not
    draining, not degraded (and warmed, when require_warm=True) — the
    same conjunction /readyz serves.
  * SUPERVISION — a replica whose step() raises is declared dead
    ("kill"); a busy replica whose dispatch-progress counters freeze
    for `watchdog_ticks` consecutive router steps is declared wedged
    ("stall"; the same progress probe the flight-recorder watchdog
    uses). Either way the router latches ONE flight dump per failure
    (`replica_down:engine<id>`), exports every queued and in-flight
    request off the corpse host-side, and MIGRATES them to survivors.
    A migrated request re-prefills prompt+emitted with its RNG counter
    resumed (ServingEngine.adopt — the restart continuation), so its
    output is bit-identical to a fault-free run: a replica failure
    loses zero accepted requests while a survivor exists.
  * HEDGING — a request still unfinished after a p99-derived delay is
    duplicated to a second replica. Identical RNG streams mean both
    copies emit identical tokens, so the first finisher simply wins
    and the loser is cancelled (ServingEngine.cancel). Hedges won /
    wasted are counted separately: a wasted hedge is the price of the
    tail-latency insurance.
  * ROLLING RESTART — drain(i) closes one replica's admission
    (ShedError(reason="draining") with a drain-time retry estimate),
    optionally migrates its backlog, and rejoin(i) returns it to the
    rotation after mark_warm().

Shed accounting is two-level by construction: a replica that rejects
counts its own serving_shed_total; the router counts router_shed_total
ONLY when no replica accepted — candidate replicas are pre-screened
(queue bounds, overload level) before submit is attempted, so one
rejected request never lands in both families. The aggregated
rejection carries retry_after_s = min over the replicas' estimates.

Everything is single-threaded and deterministic: step() drives each
replica in order, the watchdog counts router steps, and the chaos
harness (serving/faults.py ReplicaFaultPlan) injects kill/hang/degrade
through the `replica_hook` seam — the fleet-level analogue of the
engine's dispatch_hook.
"""
from __future__ import annotations

import itertools
import time
import zlib
from collections import deque

import numpy as np

from .. import telemetry
from ..base import MXNetError
from ..analysis import loop_only
from ..telemetry import server as _tserver
from .scheduler import (QueueFullError, RejectedError, Request,
                        ShedError)

__all__ = ["ServingRouter"]

_router_ids = itertools.count()

# Router metrics are per-router labeled children (router=<ordinal>) of
# process-global families, mirroring the per-engine convention.
# docs/OBSERVABILITY.md catalogs each one.
_R = ("router",)


def _router_metrics(rid):
    c, g = telemetry.counter, telemetry.gauge
    m = {
        "requests": c("router_requests_total",
                      "requests the router accepted and placed on a "
                      "replica", _R),
        "affinity": c("router_routed_affinity_total",
                      "placements on the prefix-affinity replica", _R),
        "spill": c("router_routed_spill_total",
                   "placements spilled off the affinity replica "
                   "(not routable, or load-aware spill)", _R),
        "migrated": c("router_migrated_requests_total",
                      "queued/in-flight requests moved to a survivor "
                      "after a replica failure or drain", _R),
        "hedges": c("router_hedges_total",
                    "straggler requests duplicated to a second "
                    "replica", _R),
        "hedges_won": c("router_hedges_won_total",
                        "hedges that finished first (primary copy "
                        "cancelled)", _R),
        "hedges_wasted": c("router_hedges_wasted_total",
                           "hedges the primary beat (duplicate "
                           "cancelled — the insurance premium)", _R),
        "drains": c("router_drains_total",
                    "replica drains initiated (rolling restarts)", _R),
        "replicas": g("router_replicas",
                      "replicas fronted by this router", _R),
        "replicas_ready": g("router_replicas_ready",
                            "replicas currently routable (up, not "
                            "draining, not degraded, warmed when "
                            "required)", _R),
    }
    _down_family()
    _router_shed_family()
    return {k: inst.labels(rid) for k, inst in m.items()}


def _down_family():
    return telemetry.counter(
        "router_replica_down_total",
        "replicas declared failed, by reason (kill = step() raised "
        "out of the replica; stall = the watchdog saw a busy replica "
        "make no dispatch progress for watchdog_ticks router steps)",
        ("router", "reason"))


def _router_shed_family():
    return telemetry.counter(
        "router_shed_total",
        "requests the ROUTER shed because no replica could accept "
        "them (replica-level sheds count in serving_shed_total; a "
        "request never lands in both families)", ("router", "reason"))


class _Replica:
    """Router-side state for one fronted engine."""

    __slots__ = ("engine", "state", "down_reason", "last_progress",
                 "stall_ticks")

    def __init__(self, engine):
        self.engine = engine
        self.state = "up"            # "up" | "down"
        self.down_reason = None
        self.last_progress = None
        self.stall_ticks = 0


class ServingRouter:
    """Health-supervising, prefix-affinity router over ServingEngine
    replicas (module docstring).

    replicas: the engines to front (they should share one model and
        one injectable clock with the router for coherent deadlines).
    hedge_after_s: fixed hedge delay; None derives it from the p99 of
        observed request latencies (x hedge_factor) once
        hedge_min_samples finishes landed — no hedging before that.
    spill_queue: affinity-replica queue depth that triggers load-aware
        spill (default: that replica's num_slots).
    watchdog_ticks: consecutive no-progress-while-busy router steps
        before a replica is declared stalled.
    require_warm: when True, only warmed (mark_warm()) replicas are
        routable — production fleets warm before joining; tests and
        benches that compile lazily leave it False.
    """

    def __init__(self, replicas, *, hedge_after_s=None, hedge_factor=1.0,
                 hedge_min_samples=16, spill_queue=None,
                 watchdog_ticks=25, require_warm=False, clock=None):
        replicas = list(replicas)
        if not replicas:
            raise MXNetError("ServingRouter needs at least one replica")
        if len({id(e) for e in replicas}) != len(replicas):
            raise MXNetError("each replica must be a distinct engine")
        self.replicas = [_Replica(e) for e in replicas]
        self.hedge_after_s = hedge_after_s
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_samples = int(hedge_min_samples)
        self.spill_queue = spill_queue
        self.watchdog_ticks = int(watchdog_ticks)
        if self.watchdog_ticks < 2:
            raise MXNetError("watchdog_ticks must be >= 2")
        self.require_warm = bool(require_warm)
        self._clock = clock if clock is not None else time.perf_counter
        # affinity key: the first page of prompt tokens — requests
        # sharing at least one full page share their hash key
        self._affinity_tokens = min(e.page_size for e in replicas)
        self._rid = str(next(_router_ids))
        self._metrics = _router_metrics(self._rid)
        self._down = _down_family()
        self._rshed = _router_shed_family()
        self._down_counts = {}       # reason -> n (host-side)
        self._shed_counts = {}       # reason -> n (host-side)
        self._metrics["replicas"].set(len(self.replicas))
        self._owner = {}             # request id -> (replica idx, Request)
        self._t_submit = {}          # request id -> router-clock submit
        self._hedges = {}            # original id -> (replica idx, clone)
        self._clone_to_orig = {}     # clone id -> original id
        self._lat = deque(maxlen=256)   # finished-request latencies
        self._pending = []           # terminals minted outside step order
        # chaos seam (serving/faults.py ReplicaFaultPlan): called once
        # per step with (router, None, None) — the fleet tick — and
        # once per up replica with (router, idx, engine) right before
        # its step(). May raise (the router treats it as the replica
        # dying) or return "skip" (the replica makes no progress this
        # tick — a wedged dispatch the watchdog must catch).
        self.replica_hook = None
        telemetry.register_status_provider(
            f"router/{self._rid}", self._statusz)
        self._set_gauges()

    # -- telemetry ---------------------------------------------------------
    @property
    def stats(self):
        m = self._metrics
        return {
            "requests": int(m["requests"].value),
            "affinity": int(m["affinity"].value),
            "spill": int(m["spill"].value),
            "migrated": int(m["migrated"].value),
            "hedges": int(m["hedges"].value),
            "hedges_won": int(m["hedges_won"].value),
            "hedges_wasted": int(m["hedges_wasted"].value),
            "drains": int(m["drains"].value),
            "replicas": len(self.replicas),
            "replicas_ready": len(self._routable()),
            "replica_down": dict(self._down_counts),
            "shed": dict(self._shed_counts),
        }

    def _statusz(self):
        reps = []
        for idx, rep in enumerate(self.replicas):
            eng = rep.engine
            reps.append({
                "engine": eng._eid,
                "state": rep.state,
                "down_reason": rep.down_reason,
                "routable": self._is_routable(idx),
                "warmed": eng.warmed,
                "degraded": eng._degraded,
                "draining": eng.draining,
                "queued": eng.scheduler.num_queued,
                "active": eng.scheduler.num_active,
                "stall_ticks": rep.stall_ticks,
            })
        return {
            "config": {
                "num_replicas": len(self.replicas),
                "hedge_after_s": self.hedge_after_s,
                "hedge_factor": self.hedge_factor,
                "hedge_min_samples": self.hedge_min_samples,
                "spill_queue": self.spill_queue,
                "watchdog_ticks": self.watchdog_ticks,
                "require_warm": self.require_warm,
                "affinity_tokens": self._affinity_tokens,
            },
            "hedge_delay_s": self._hedge_delay(),
            "in_flight": len(self._owner),
            "hedges_in_flight": len(self._hedges),
            "replicas": reps,
            "stats": self.stats,
        }

    def _set_gauges(self):
        self._metrics["replicas_ready"].set(len(self._routable()))

    def _shed_inc(self, reason):
        self._rshed.labels(self._rid, reason).inc()
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1

    # -- placement ---------------------------------------------------------
    def _is_routable(self, idx):
        rep = self.replicas[idx]
        eng = rep.engine
        return (rep.state == "up" and not eng.draining
                and not eng._degraded
                and (eng.warmed or not self.require_warm))

    def _routable(self):
        return [i for i in range(len(self.replicas))
                if self._is_routable(i)]

    def _load(self, idx):
        s = self.replicas[idx].engine.scheduler
        return s.num_queued + s.num_active

    def _affinity_idx(self, request, candidates):
        """Rendezvous (highest-random-weight) hash of the prompt's
        first page of tokens over the candidate replicas: deterministic
        for a given prefix, and stable — a replica leaving the set only
        moves the keys it owned. The adapter id folds into the key so
        same-adapter traffic co-locates and replicas don't each page in
        every adapter; null-adapter requests hash exactly as before."""
        key = np.asarray(request.prompt[:self._affinity_tokens],
                         np.int32).tobytes()
        if request.adapter_id not in (None, 0):
            key += b"|adapter:" + repr(request.adapter_id).encode()
        best, best_w = None, -1
        for i in candidates:
            w = zlib.crc32(key + b"/%d" % i)
            if w > best_w:
                best, best_w = i, w
        return best

    def _placement_order(self, request, candidates):
        """(ordered candidate list, affinity idx): affinity target
        first unless load-aware spill kicks in — its queue at/over
        spill_queue AND a strictly less-loaded alternative exists."""
        aff = self._affinity_idx(request, candidates)
        others = sorted((i for i in candidates if i != aff),
                        key=lambda i: (self._load(i), i))
        eng = self.replicas[aff].engine
        spill_at = self.spill_queue if self.spill_queue is not None \
            else eng.num_slots
        if others and eng.scheduler.num_queued >= spill_at \
                and self._load(others[0]) < self._load(aff):
            return others + [aff], aff
        return [aff] + others, aff

    def _wait_of(self, idx):
        eng = self.replicas[idx].engine
        return eng.estimated_drain_wait() if eng.draining \
            else eng.estimated_queue_wait()

    def _can_accept(self, idx, request):
        """Pre-screen one replica without side effects: the predicted
        rejection reason, or None when submit should succeed. Screening
        keeps a doomed submit from counting a replica-level shed for a
        request the router is still trying to place elsewhere."""
        eng = self.replicas[idx].engine
        sched = eng.scheduler
        pr = min(max(int(request.priority), 0),
                 sched.num_priorities - 1)
        bound = sched._bounds[pr]
        if bound is not None and len(sched._queues[pr]) >= bound:
            return "queue_full"
        pol = eng.policy
        if pol is not None and pol.assess(eng) >= 2 \
                and pr > pol.shed_priority_floor:
            return "overload"
        return None

    def _reject_all(self, request, fails):
        """Router-level rejection: every replica refused (or none was
        routable). retry_after_s is the MIN over the replicas'
        estimates — the earliest any of them could accept — and the
        shed counts ONLY in router_shed_total (replica-level sheds,
        when a submit was actually attempted, already counted
        theirs)."""
        waits = [w for _, _, w in fails if w is not None]
        wait = min(waits) if waits else None
        reasons = [r for _, r, _ in fails]
        if not reasons:
            reason = "no_ready_replica"
        elif all(r == "queue_full" for r in reasons):
            reason = "queue_full"
        else:
            reason = next(r for r in reasons if r != "queue_full")
        depth = sum(r.engine.scheduler.num_queued
                    for r in self.replicas)
        active = sum(r.engine.scheduler.num_active
                     for r in self.replicas)
        request.status = "shed"
        if request.t_submit is None:
            request.t_submit = self._clock()
        self._shed_inc(reason)
        telemetry.flight.note_shed(f"router{self._rid}")
        telemetry.request_log.terminal(
            request.id, f"router{self._rid}", "rejected",
            reason=reason, priority=request.priority,
            queue_depth=depth, active_slots=active,
            retry_after_s=None if wait is None else round(wait, 4))
        msg = (f"request {request.id} rejected by all "
               f"{len(self.replicas)} replicas ({reason}) "
               f"[queue_depth={depth}, active_slots={active}"
               + (f", retry_after~{wait:.3f}s" if wait is not None
                  else "") + "]")
        cls = QueueFullError if reason == "queue_full" else ShedError
        raise cls(msg, reason=reason, queue_depth=depth,
                  active_slots=active, retry_after_s=wait,
                  priority=request.priority)

    # -- public API --------------------------------------------------------
    @loop_only
    def submit(self, request):
        """Place one request: prefix-affinity target first (load-aware
        spill and pre-screening may reorder), remaining routable
        replicas by load. Raises the aggregated QueueFullError/
        ShedError when nobody accepts."""
        candidates = self._routable()
        if not candidates:
            self._reject_all(request, [])
        order, aff = self._placement_order(request, candidates)
        fails = []
        for idx in order:
            why = self._can_accept(idx, request)
            if why is not None:
                fails.append((idx, why, self._wait_of(idx)))
                continue
            eng = self.replicas[idx].engine
            try:
                eng.submit(request)
            except RejectedError as e:
                fails.append((idx, e.reason or "rejected",
                              e.retry_after_s
                              if e.retry_after_s is not None
                              else self._wait_of(idx)))
                continue
            self._owner[request.id] = (idx, request)
            self._t_submit[request.id] = self._clock()
            m = self._metrics
            m["requests"].inc()
            (m["affinity"] if idx == aff else m["spill"]).inc()
            return request
        self._reject_all(request, fails)

    @loop_only
    def cancel(self, request_id):
        """Cancel a routed request (and any hedge duplicate of it)
        wherever it lives. Returns the Request, or None."""
        h = self._hedges.pop(request_id, None)
        if h is not None:
            hidx, clone = h
            self._clone_to_orig.pop(clone.id, None)
            try:
                self.replicas[hidx].engine.cancel(clone.id)
            except Exception:     # noqa: BLE001 — replica may be dead
                pass
        owner = self._owner.pop(request_id, None)
        self._t_submit.pop(request_id, None)
        if owner is None:
            return None
        idx, req = owner
        try:
            return self.replicas[idx].engine.cancel(request_id) or req
        except Exception:         # noqa: BLE001 — replica may be dead
            return req

    @property
    def has_work(self):
        return bool(self._pending) or any(
            rep.state == "up" and rep.engine.has_work
            for rep in self.replicas)

    @loop_only
    def step(self):
        """One fleet scheduling round: fire the chaos tick, step every
        up replica (its exceptions mean the REPLICA died — requests
        are exported and migrated), advance the stall watchdog, then
        launch any due hedges. Returns this round's terminal
        requests (originals only — hedge clones resolve into their
        originals)."""
        now = self._clock()
        out = list(self._pending)
        self._pending = []
        self._fire_hook(None, None)
        for idx, rep in enumerate(self.replicas):
            if rep.state != "up":
                continue
            eng = rep.engine
            try:
                act = self._fire_hook(idx, eng)
                if act != "skip":
                    for req in eng.step():
                        out.extend(self._resolve(idx, req))
            except Exception as e:   # noqa: BLE001 — fleet supervisor
                self._replica_down(idx, "kill", e)
                continue
            progress, busy = eng._flight_probe()
            if busy and rep.last_progress is not None \
                    and progress == rep.last_progress:
                rep.stall_ticks += 1
            else:
                rep.stall_ticks = 0
            rep.last_progress = progress
            if rep.stall_ticks >= self.watchdog_ticks:
                self._replica_down(idx, "stall")
        self._maybe_hedge(now)
        out.extend(self._pending)
        self._pending = []
        self._set_gauges()
        return out

    @loop_only
    def serve(self, requests=()):
        """Submit `requests` (router-rejected ones come back with
        status "shed"), run the fleet until it drains, and return
        every terminal request in submission order."""
        done = []
        for r in requests:
            try:
                self.submit(r)
            except (QueueFullError, ShedError):
                done.append(r)
        while self.has_work:
            done.extend(self.step())
        done.sort(key=lambda r: (r.t_submit is None, r.t_submit))
        return done

    @loop_only
    def drain(self, replica, migrate=False):
        """Begin a rolling restart of one replica: admission closes
        (new submits route around it; direct submits shed with
        reason="draining"), in-flight work finishes — or, with
        migrate=True, is exported and adopted by survivors
        immediately. Rejoin with rejoin() after mark_warm()."""
        rep = self.replicas[int(replica)]
        rep.engine.drain()
        self._metrics["drains"].inc()
        if migrate:
            moved = rep.engine.export_requests()
            self._migrate(moved, from_eid=rep.engine._eid)
        self._set_gauges()

    @loop_only
    def rejoin(self, replica):
        """Return a drained (or previously failed) replica to the
        rotation: admission reopens and the watchdog re-arms. The
        caller is responsible for the replica actually being servable
        (warmed via mark_warm() when require_warm is set)."""
        rep = self.replicas[int(replica)]
        rep.engine.undrain()
        rep.state = "up"
        rep.down_reason = None
        rep.stall_ticks = 0
        rep.last_progress = None
        self._set_gauges()

    # -- failover ----------------------------------------------------------
    def _replica_down(self, idx, reason, exc=None):
        """Declare one replica failed: latch ONE flight dump
        (replica_down:engine<id>), close its admission, export its
        queued + in-flight requests host-side, and migrate them."""
        rep = self.replicas[idx]
        if rep.state == "down":
            return
        rep.state = "down"
        rep.down_reason = reason
        eng = rep.engine
        self._down.labels(self._rid, reason).inc()
        self._down_counts[reason] = \
            self._down_counts.get(reason, 0) + 1
        detail = (f"router{self._rid}: replica engine{eng._eid} "
                  f"declared down ({reason})")
        if exc is not None:
            detail += f": {type(exc).__name__}: {exc}"
        telemetry.flight.record("replica_down", router=self._rid,
                                engine=eng._eid, reason=reason)
        telemetry.flight.trigger(f"replica_down:engine{eng._eid}",
                                 detail)
        try:
            eng.drain()           # a dead replica must read not-ready
        except Exception:         # noqa: BLE001
            pass
        try:
            moved = eng.export_requests()
        except Exception:         # noqa: BLE001 — wedged beyond export
            moved = []
        self._migrate(moved, from_eid=eng._eid)
        self._set_gauges()

    def _migrate(self, moved, from_eid):
        """Re-home exported requests onto survivors (affinity first —
        the survivor holding the prefix pages — then by load). adopt()
        preserves emitted tokens, so migrated outputs stay
        bit-identical. With no adoptive survivor the request ends
        status "shed" with a structured ShedError on `.error`."""
        for req in moved:
            oid = self._clone_to_orig.pop(req.id, None)
            if oid is not None:
                # a hedge clone died with its replica: the original is
                # still running — the hedge is simply lost
                self._hedges.pop(oid, None)
                continue
            candidates = self._routable()
            order = []
            if candidates:
                order, _ = self._placement_order(req, candidates)
            placed = False
            for idx in order:
                try:
                    self.replicas[idx].engine.adopt(
                        req, migrated_from=f"engine{from_eid}")
                except Exception:   # noqa: BLE001 — try the next one
                    continue
                self._owner[req.id] = (idx, req)
                self._metrics["migrated"].inc()
                placed = True
                break
            if not placed:
                waits = [self._wait_of(i)
                         for i in range(len(self.replicas))]
                waits = [w for w in waits if w is not None]
                req.status = "shed"
                req.error = ShedError(
                    f"request {req.id} lost its replica and no "
                    f"survivor could adopt it",
                    reason="no_ready_replica",
                    retry_after_s=min(waits) if waits else None,
                    priority=req.priority)
                self._shed_inc("no_ready_replica")
                telemetry.flight.note_shed(f"router{self._rid}")
                self._owner.pop(req.id, None)
                self._t_submit.pop(req.id, None)
                if req.stream is not None:
                    # export detached the request from its engine, so
                    # no terminal transition will close the subscriber
                    # stream — this is the end of the line, wake the
                    # front-end reader
                    try:
                        req.stream.close("shed")
                    except Exception:   # noqa: BLE001 — subscriber
                        pass
                self._pending.append(req)

    # -- hedging -----------------------------------------------------------
    def _hedge_delay(self):
        if self.hedge_after_s is not None:
            return float(self.hedge_after_s)
        n = len(self._lat)
        if n < self.hedge_min_samples:
            return None
        lat = sorted(self._lat)
        return lat[min(n - 1, int(0.99 * n))] * self.hedge_factor

    def _maybe_hedge(self, now):
        delay = self._hedge_delay()
        if delay is None:
            return
        for oid, (idx, req) in list(self._owner.items()):
            if oid in self._hedges:
                continue
            t0 = self._t_submit.get(oid)
            if t0 is None or now - t0 < delay:
                continue
            if req.status not in ("queued", "running"):
                continue
            cands = [i for i in self._routable() if i != idx]
            if not cands:
                continue
            tgt = min(cands, key=lambda i: (self._load(i), i))
            clone = Request(
                req.prompt, req.max_new_tokens,
                request_id=f"hedge:{oid}", do_sample=req.do_sample,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, seed=req.seed,
                eos_token_id=req.eos_token_id, priority=req.priority,
                deadline_ms=req.deadline_ms, adapter_id=req.adapter_id,
                tenant=req.tenant,
                # the clone races the SAME logical request — it shares
                # the original's trace id so both attempts correlate
                # to one distributed trace
                trace=dict(req.trace) if req.trace else None)
            try:
                self.replicas[tgt].engine.submit(clone)
            except RejectedError:
                continue
            self._hedges[oid] = (tgt, clone)
            self._clone_to_orig[clone.id] = oid
            self._metrics["hedges"].inc()
            telemetry.request_log.event(
                oid, self.replicas[idx].engine._eid, "hedged",
                to=f"engine{self.replicas[tgt].engine._eid}",
                after_s=round(now - t0, 4))

    def _resolve(self, idx, req):
        """Fold one replica-terminal request into router state.
        Returns the user-visible terminals it produced ([] when a
        hedge clone lost or resolved into its original)."""
        oid = self._clone_to_orig.pop(req.id, None)
        if oid is not None:
            h = self._hedges.pop(oid, None)
            owner = self._owner.get(oid)
            if h is None or owner is None:
                return []            # original already resolved
            if req.status != "finished":
                return []            # clone shed/failed — primary runs on
            # the hedge WON: identical RNG streams mean its tokens are
            # exactly what the primary would have emitted — graft them,
            # cancel the primary copy. The subscriber stream is
            # detached first so the primary's cancel can't close it
            # "cancelled"; the front-end reconciles the grafted token
            # tail from the Request, then sees the "finished" close.
            pidx, orig = owner
            st, orig.stream = orig.stream, None
            try:
                self.replicas[pidx].engine.cancel(oid)
            except Exception:        # noqa: BLE001 — replica may be dead
                pass
            orig.output_tokens = list(req.output_tokens)
            orig.status = "finished"
            orig.t_finish = req.t_finish
            if st is not None:
                orig.stream = st
                try:
                    st.close("finished")
                except Exception:    # noqa: BLE001 — subscriber
                    pass
            self._metrics["hedges_won"].inc()
            self._owner.pop(oid, None)
            self._note_done(orig)
            return [orig]
        h = self._hedges.pop(req.id, None)
        if h is not None:
            hidx, clone = h
            self._clone_to_orig.pop(clone.id, None)
            try:
                self.replicas[hidx].engine.cancel(clone.id)
            except Exception:        # noqa: BLE001 — replica may be dead
                pass
            self._metrics["hedges_wasted"].inc()
        self._owner.pop(req.id, None)
        self._note_done(req)
        return [req]

    def _note_done(self, req):
        t0 = self._t_submit.pop(req.id, None)
        if t0 is not None and req.status == "finished":
            self._lat.append(self._clock() - t0)

    # -- chaos seam --------------------------------------------------------
    def _fire_hook(self, idx, engine):
        hook = self.replica_hook
        if hook is None:
            return None
        return hook(self, idx, engine)

    def __repr__(self):
        up = sum(r.state == "up" for r in self.replicas)
        return (f"ServingRouter(replicas={len(self.replicas)}, up={up}, "
                f"in_flight={len(self._owner)})")
