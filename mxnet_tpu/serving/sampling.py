"""Per-slot token sampling for the continuous-batching engine.

Every sampling knob is a PER-SLOT ARRAY, not a compile-time constant, so
one compiled decode program serves any mix of greedy and sampled
requests at any temperature/top-k/top-p — admission never recompiles.

RNG contract (the reproducibility satellite): token i of a request with
seed s is drawn with key fold_in(PRNGKey(s), i). The stream depends ONLY
on the request's own (seed, token index) — never on the slot it landed
in, the admission order, or which other requests share the batch — so
sampled output is bit-reproducible across schedules. This is the same
counter-derivation discipline the Pallas dropout kernels apply per
(batch, head) grid cell, keyed here by the logical request instead of
the physical slot.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["slot_keys", "sample_tokens"]


def slot_keys(seeds, counters):
    """(B,) int32 request seeds × (B,) int32 per-request token indices →
    (B,) PRNG keys, one independent stream element per slot."""
    def one(seed, counter):
        return jax.random.fold_in(jax.random.PRNGKey(seed), counter)
    return jax.vmap(one)(seeds, counters)


def sample_tokens(logits, keys, do_sample, temperature, top_k, top_p):
    """Select one token per slot from (B, V) logits.

    keys: (B,) PRNG keys (slot_keys). do_sample: (B,) bool — False rows
    take argmax. temperature: (B,) f32 (> 0; greedy rows ignore it).
    top_k: (B,) int32, <= 0 disables. top_p: (B,) f32, >= 1 disables
    (the full distribution must be a true no-op: f32 cumsum rounding
    above 1.0 would otherwise cut tail tokens — same guard as
    GPT2.generate). Returns (B,) int32.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # ONE descending sort serves both filters (per decode step inside the
    # compiled block — don't sort twice)
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    cut_sorted = jnp.zeros((B, V), bool)
    ranks = jnp.arange(V)[None, :]
    cut_sorted |= (ranks >= top_k[:, None]) & (top_k > 0)[:, None]
    # nucleus: cut token i only if the mass STRICTLY before it already
    # exceeds top_p — the top-1 token always survives (even top_p=0)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cut_sorted |= ((cum - probs) > top_p[:, None]) & (top_p < 1.0)[:, None]
    cut = jnp.zeros_like(cut_sorted).at[
        jnp.arange(B)[:, None], sort_idx].set(cut_sorted)
    filtered = jnp.where(cut, -jnp.inf, scaled)

    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(keys, filtered)
    return jnp.where(do_sample, sampled.astype(jnp.int32), greedy)
