"""Per-slot token sampling for the continuous-batching engine.

Every sampling knob is a PER-SLOT ARRAY, not a compile-time constant, so
one compiled decode program serves any mix of greedy and sampled
requests at any temperature/top-k/top-p — admission never recompiles.

RNG contract (the reproducibility satellite): token i of a request with
seed s is drawn with key fold_in(PRNGKey(s), i). The stream depends ONLY
on the request's own (seed, token index) — never on the slot it landed
in, the admission order, or which other requests share the batch — so
sampled output is bit-reproducible across schedules. This is the same
counter-derivation discipline the Pallas dropout kernels apply per
(batch, head) grid cell, keyed here by the logical request instead of
the physical slot.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["slot_keys", "filtered_logits", "sample_tokens"]


def slot_keys(seeds, counters):
    """(B,) int32 request seeds × (B,) int32 per-request token indices →
    (B,) PRNG keys, one independent stream element per slot."""
    def one(seed, counter):
        return jax.random.fold_in(jax.random.PRNGKey(seed), counter)
    return jax.vmap(one)(seeds, counters)


def filtered_logits(logits, temperature, top_k, top_p):
    """Temperature-scaled (B, V) logits with the top-k/top-p filter
    applied: cut tokens are -inf, surviving tokens keep their scaled
    value (softmax over the result IS the sampling distribution). This
    is the single definition of the filtered distribution — the per-step
    sampler and the speculative-decoding rejection sampler
    (serving/speculative.py) must agree on it exactly, or acceptance
    would not preserve the sampling distribution.

    temperature: (B,) f32 (> 0). top_k: (B,) int32, <= 0 disables.
    top_p: (B,) f32, >= 1 disables (a true no-op: f32 cumsum rounding
    above 1.0 would otherwise cut tail tokens — same guard as
    GPT2.generate). The top-1 token always survives (even top_p=0 /
    top_k=1 leave exactly the argmax).
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # ONE descending sort serves both filters (per decode step inside the
    # compiled block — don't sort twice)
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    cut_sorted = jnp.zeros((B, V), bool)
    ranks = jnp.arange(V)[None, :]
    cut_sorted |= (ranks >= top_k[:, None]) & (top_k > 0)[:, None]
    # nucleus: cut token i only if the mass STRICTLY before it already
    # exceeds top_p — the top-1 token always survives (even top_p=0)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cut_sorted |= ((cum - probs) > top_p[:, None]) & (top_p < 1.0)[:, None]
    cut = jnp.zeros_like(cut_sorted).at[
        jnp.arange(B)[:, None], sort_idx].set(cut_sorted)
    return jnp.where(cut, -jnp.inf, scaled)


def sample_tokens(logits, keys, do_sample, temperature, top_k, top_p):
    """Select one token per slot from (B, V) logits.

    keys: (B,) PRNG keys (slot_keys). do_sample: (B,) bool — False rows
    take argmax of the RAW logits (temperature/filters ignored).
    Sampled rows draw categorically from filtered_logits. Returns (B,)
    int32.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = filtered_logits(logits, temperature, top_k, top_p)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(keys, filtered)
    return jnp.where(do_sample, sampled.astype(jnp.int32), greedy)
