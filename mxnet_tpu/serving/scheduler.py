"""Slot scheduler for continuous batching.

The engine decodes a FIXED batch of B slots (one compiled program, no
shape churn); the scheduler owns which request occupies which slot.
Admission is strict FIFO — the oldest queued request always gets the
next free slot, so a steady stream of new arrivals can never starve an
earlier one. Slots free the moment their request finishes (eos or token
budget), and a freed slot is re-admittable between two compiled decode
dispatches — the continuous-batching property: a finished sequence
never burns its slot waiting for the slowest member of its batch.
"""
from __future__ import annotations

import itertools
from collections import deque

import numpy as np

from ..base import MXNetError

__all__ = ["Request", "SlotScheduler", "QueueFullError"]

_req_counter = itertools.count()


class Request:
    """One generation request.

    prompt: 1-D int sequence. max_new_tokens: generation budget
    (including the first token sampled at prefill). Sampling knobs are
    per-request and dynamic — they never recompile the engine. seed
    drives this request's private RNG stream (see serving.sampling).
    eos_token_id=None disables eos stopping for this request.
    """

    def __init__(self, prompt, max_new_tokens, request_id=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=0, eos_token_id=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise MXNetError("Request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if temperature <= 0:
            raise MXNetError("temperature must be > 0 (use "
                             "do_sample=False for greedy)")
        self.max_new_tokens = int(max_new_tokens)
        self.id = request_id if request_id is not None \
            else next(_req_counter)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p if top_p is not None else 1.0)
        self.seed = int(seed)
        self.eos_token_id = eos_token_id
        # filled in by the engine
        self.output_tokens = []
        self.t_submit = None
        self.t_admit = None
        self.t_finish = None

    @property
    def prompt_len(self):
        return int(self.prompt.size)

    def __repr__(self):
        return (f"Request(id={self.id}, prompt_len={self.prompt_len}, "
                f"max_new={self.max_new_tokens}, "
                f"generated={len(self.output_tokens)})")


class QueueFullError(MXNetError):
    """Raised by SlotScheduler.submit when the bounded admission queue is
    at capacity — the engine counts these as rejected submissions
    (serving_requests_rejected_total) before re-raising."""


class SlotScheduler:
    """Fixed-pool slot allocator + FIFO admission queue.

    max_queue bounds the admission queue (None = unbounded): a serving
    front-end needs backpressure it can see — an unbounded queue turns
    overload into silent tail-latency collapse instead of a countable
    rejection."""

    def __init__(self, num_slots, max_queue=None):
        if num_slots < 1:
            raise MXNetError("need at least one decode slot")
        self.num_slots = int(num_slots)
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise MXNetError("max_queue must be >= 1 (or None)")
        self._free = deque(range(self.num_slots))
        self._queue = deque()
        self._active = {}          # slot -> Request

    # -- queue -------------------------------------------------------------
    def submit(self, request):
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise QueueFullError(
                f"admission queue full ({self.max_queue} waiting); "
                "rejecting request — retry after the queue drains")
        self._queue.append(request)
        return request

    def admit(self):
        """Pair queued requests with free slots, oldest request first.
        Returns the [(slot, request), ...] admitted this round."""
        admitted = []
        while self._free and self._queue:
            slot = self._free.popleft()
            req = self._queue.popleft()
            self._active[slot] = req
            admitted.append((slot, req))
        return admitted

    def release(self, slot):
        """Free a slot whose request finished (or was evicted)."""
        if slot not in self._active:
            raise MXNetError(f"slot {slot} is not active")
        req = self._active.pop(slot)
        self._free.append(slot)
        return req

    def cancel_queued(self, request_id):
        """Remove a not-yet-admitted request from the queue by id.
        Returns the Request, or None when no queued request matches
        (it may already be running — see slot_of)."""
        for i, req in enumerate(self._queue):
            if req.id == request_id:
                del self._queue[i]
                return req
        return None

    def slot_of(self, request_id):
        """Slot currently decoding `request_id`, or None."""
        for slot, req in self._active.items():
            if req.id == request_id:
                return slot
        return None

    # -- introspection -----------------------------------------------------
    def request_at(self, slot):
        return self._active.get(slot)

    @property
    def queued_ids(self):
        """Request ids waiting for a slot, admission order."""
        return [r.id for r in self._queue]

    def snapshot(self):
        """JSON-able view of the scheduler's state — what /statusz and
        the flight recorder's state.json embed: the slot map (slot →
        request id + progress), the waiting queue, and capacity."""
        return {
            "num_slots": self.num_slots,
            "max_queue": self.max_queue,
            "free_slots": sorted(self._free),
            "queued_ids": self.queued_ids,
            "active": {
                str(slot): {
                    "request_id": req.id,
                    "prompt_len": req.prompt_len,
                    "generated": len(req.output_tokens),
                    "max_new_tokens": req.max_new_tokens,
                } for slot, req in sorted(self._active.items())},
        }

    @property
    def active_slots(self):
        return sorted(self._active)

    @property
    def num_active(self):
        return len(self._active)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_queued(self):
        return len(self._queue)

    @property
    def has_work(self):
        return bool(self._queue or self._active)
