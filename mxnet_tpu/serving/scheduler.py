"""Slot scheduler for continuous batching.

The engine decodes a FIXED batch of B slots (one compiled program, no
shape churn); the scheduler owns which request occupies which slot.

Admission order (docs/SERVING.md "Robustness"):

  * Requests carry a PRIORITY CLASS (0 = most urgent). Each class has
    its own FIFO queue with an independent bound, so bulk traffic can
    never push interactive traffic out of the admission queue.
  * Within the pick loop the highest class goes first, but every
    `aging_every`-th admission takes the globally OLDEST eligible
    request regardless of class — deterministic aging, so a steady
    stream of high-priority arrivals can never starve a queued
    low-priority request (starvation-freedom is tested, not assumed).
  * Requests re-queued by the engine supervisor after a dispatch fault
    sit out their backoff window (`t_not_before`) and then re-enter at
    the FRONT of their class (they are older than anything queued
    behind them). A request with a failure history is on PROBATION:
    at most one probationer is in flight at a time, so a poison request
    gets re-tried alone and can never take innocents down twice.

Slots free the moment their request finishes (eos or token budget), and
a freed slot is re-admittable between two compiled decode dispatches —
the continuous-batching property: a finished sequence never burns its
slot waiting for the slowest member of its batch.
"""
from __future__ import annotations

import itertools
from collections import deque

import numpy as np

from ..base import MXNetError
from ..analysis import loop_only, thread_safe

__all__ = ["Request", "SlotScheduler", "TenantQuota", "RejectedError",
           "QueueFullError", "TenantQuotaError", "ShedError",
           "TERMINAL_STATUSES"]

# The statuses a Request can END in. "exported" is NOT terminal — a
# migrating request is between replicas and will be adopted (or shed)
# by the router; front-ends and the idempotent-cancel check both key
# off this set.
TERMINAL_STATUSES = frozenset(
    {"finished", "cancelled", "deadline", "failed", "shed"})

_req_counter = itertools.count()
_seq_counter = itertools.count()


class Request:
    """One generation request.

    prompt: 1-D int sequence. max_new_tokens: generation budget
    (including the first token sampled at prefill). Sampling knobs are
    per-request and dynamic — they never recompile the engine. seed
    drives this request's private RNG stream (see serving.sampling).
    eos_token_id=None disables eos stopping for this request.

    priority: admission class, 0 = most urgent (clamped by the
    scheduler to its configured class count; default 1 = normal).
    deadline_ms: end-to-end budget relative to submit(). A queued
    request past its deadline is shed before admission (terminal
    `rejected(deadline)`); a running one is cancelled at the next
    dispatch boundary (terminal `finished(deadline)`, partial output
    kept). None = no deadline.

    adapter_id: LoRA adapter this request decodes through (must be
    registered in the engine's AdapterPool); None/0 = the base model
    (null adapter, bit-identical to an adapter-free engine).
    tenant: accounting/quota label for multi-tenant admission; None =
    the anonymous default tenant. Both ride along through migration
    (export/adopt) and restart continuations.

    trace: distributed trace context (dict with "trace_id" and
    optionally "parent_span"/"t_begin") — the HTTP edge seeds it from
    an incoming `traceparent` header, hedged clones copy it, and
    export/adopt migration packs the accumulated timeline into it, so
    one request is ONE trace wherever it runs (docs/OBSERVABILITY.md
    "Trace propagation"). None = the engine mints an id at submit.
    """

    def __init__(self, prompt, max_new_tokens, request_id=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=0, eos_token_id=None, priority=1, deadline_ms=None,
                 adapter_id=None, tenant=None, trace=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise MXNetError("Request needs a non-empty prompt")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if temperature <= 0:
            raise MXNetError("temperature must be > 0 (use "
                             "do_sample=False for greedy)")
        if deadline_ms is not None and deadline_ms <= 0:
            raise MXNetError("deadline_ms must be > 0 (or None)")
        self.max_new_tokens = int(max_new_tokens)
        self.id = request_id if request_id is not None \
            else next(_req_counter)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p if top_p is not None else 1.0)
        self.seed = int(seed)
        self.eos_token_id = eos_token_id
        self.priority = int(priority)
        if self.priority < 0:
            raise MXNetError("priority must be >= 0 (0 = most urgent)")
        self.deadline_ms = None if deadline_ms is None \
            else float(deadline_ms)
        self.adapter_id = adapter_id
        self.tenant = tenant
        self.trace = dict(trace) if trace else None
        # filled in by the engine
        self.status = "new"
        self.output_tokens = []
        # TTFT phase budget (engine `_phase`): phase name -> seconds;
        # rides the Request through export/adopt so a migrated
        # request's decomposition stays continuous
        self.phases = {}
        self.t_submit = None
        self.t_enqueue = None        # last queue entry, engine clock
        self.t_admit = None
        self.t_finish = None
        self.t_deadline = None       # absolute, engine clock domain
        # supervisor bookkeeping (serving/engine.py): consecutive
        # dispatch failures blamed on this request, and the earliest
        # clock time it may be re-admitted after a faulted dispatch
        self.dispatch_failures = 0
        self.t_not_before = 0.0
        self._seq = None             # global submit order, set by submit()
        # quantized-KV write schedule (serving/engine.py): the prefill
        # chunk sizes actually fed, plus any prefix-cache tokens
        # attached instead of computed. Per-page dequant scales make
        # deep-layer KV codes depend on chunk boundaries, so a restart
        # or migration can only continue bit-identically by REPLAYING
        # this schedule; it rides the Request through export/adopt.
        self.kv_history = []
        self.kv_attach = 0
        # subscriber slot (serving/frontend.py): anything with
        # emit(tokens)->bool / close(status). The engine feeds it as
        # tokens land and closes it at every terminal transition; it
        # rides the Request through export/adopt migration, which is
        # how a mid-stream failover re-attaches the live stream.
        self.stream = None
        # cross-process KV handoff payload (serving/engine.py
        # export_handoff / serving/fleet): the request's used KV pages
        # (codes + int8 scale leaves) and decode-cursor scalars, packed
        # when a finished prefill ships to a decode worker. _admit
        # scatters it into fresh pages instead of re-prefilling; a
        # missing/stale payload falls back to the replay restart, which
        # is bit-identical anyway. None = nothing in flight.
        self.kv_payload = None
        # whole-request swap record (serving/engine.py _preempt_slot):
        # while a PREEMPTED request waits in queue, its exclusive KV
        # pages live in the host tier under ("req", id) and this dict
        # carries what _try_resume needs to splice them back (shared
        # prefix nodes, decode cursor, counters). None = not swapped;
        # resume-or-restart both clear it.
        self.swap = None

    @property
    def prompt_len(self):
        return int(self.prompt.size)

    def __repr__(self):
        return (f"Request(id={self.id}, prompt_len={self.prompt_len}, "
                f"max_new={self.max_new_tokens}, "
                f"priority={self.priority}, "
                f"generated={len(self.output_tokens)})")


class RejectedError(MXNetError):
    """A submission the serving stack refused. Carries structured
    context so a front-end can do better than parse the message:
    `reason`, `queue_depth`, `active_slots`, `priority`, and
    `retry_after_s` (drain-rate estimate of when retrying could
    succeed; None when the engine has no recent finishes to rate)."""

    def __init__(self, msg, reason=None, queue_depth=None,
                 active_slots=None, retry_after_s=None, priority=None):
        super().__init__(msg)
        self.reason = reason
        self.queue_depth = queue_depth
        self.active_slots = active_slots
        self.retry_after_s = retry_after_s
        self.priority = priority


class QueueFullError(RejectedError):
    """Raised by SlotScheduler.submit when the request's priority-class
    queue is at capacity — the engine counts these as rejected
    submissions (serving_requests_rejected_total and
    serving_shed_total{reason="queue_full"}) before re-raising with a
    retry-after estimate attached."""


class TenantQuotaError(QueueFullError):
    """Raised by SlotScheduler.submit when the request's TENANT is at
    its max_queue quota (its priority-class queue may have room) — a
    subclass of QueueFullError so front-ends that only know the class
    bound still see backpressure, but the engine counts it under its
    own shed reason (serving_shed_total{reason="tenant_quota"})."""

    def __init__(self, msg, tenant=None, **kw):
        super().__init__(msg, **kw)
        self.tenant = tenant


class ShedError(RejectedError):
    """Raised by the engine when the shedding policy refuses a request
    before it queues (overload, infeasible deadline) — counted in
    serving_shed_total{reason,priority}."""


class TenantQuota:
    """Per-tenant admission limits + fair-share weight.

    max_active: concurrent decode slots the tenant may hold (None =
    no cap — the tenant competes for everything). A tenant at its cap
    keeps its requests QUEUED (not shed): the cap bounds slot
    occupancy, the queue bound sheds.
    max_queue: queued requests across all priority classes (None =
    only the per-class bounds apply). Submissions past it raise
    TenantQuotaError — countable backpressure, the multi-tenant
    analogue of queue_full.
    weight: deficit-weighted fair-share weight inside the pick loop;
    a weight-2 tenant is owed twice the admissions of a weight-1
    tenant when both have eligible queued work.
    """

    def __init__(self, max_active=None, max_queue=None, weight=1.0):
        if max_active is not None and max_active < 1:
            raise MXNetError("max_active must be >= 1 (or None)")
        if max_queue is not None and max_queue < 1:
            raise MXNetError("max_queue must be >= 1 (or None)")
        if weight <= 0:
            raise MXNetError("weight must be > 0")
        self.max_active = None if max_active is None else int(max_active)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.weight = float(weight)

    def __repr__(self):
        return (f"TenantQuota(max_active={self.max_active}, "
                f"max_queue={self.max_queue}, weight={self.weight})")


class SlotScheduler:
    """Fixed-pool slot allocator + priority-class admission queues.

    max_queue bounds each class's queue (None = unbounded; a sequence
    gives per-class bounds): a serving front-end needs backpressure it
    can see — an unbounded queue turns overload into silent tail-latency
    collapse instead of a countable rejection. num_priorities is the
    class count (requests clamp into it); aging_every sets the
    starvation-freedom cadence (every Nth admission is oldest-first)."""

    def __init__(self, num_slots, max_queue=None, num_priorities=3,
                 aging_every=4, tenant_quotas=None):
        if num_slots < 1:
            raise MXNetError("need at least one decode slot")
        self.num_slots = int(num_slots)
        self.num_priorities = int(num_priorities)
        if self.num_priorities < 1:
            raise MXNetError("num_priorities must be >= 1")
        self.aging_every = int(aging_every)
        if self.aging_every < 2:
            raise MXNetError("aging_every must be >= 2")
        if max_queue is None or np.isscalar(max_queue):
            bound = None if max_queue is None else int(max_queue)
            if bound is not None and bound < 1:
                raise MXNetError("max_queue must be >= 1 (or None)")
            self._bounds = [bound] * self.num_priorities
        else:
            self._bounds = [None if b is None else int(b)
                            for b in max_queue]
            if len(self._bounds) != self.num_priorities:
                raise MXNetError(
                    f"per-class max_queue needs {self.num_priorities} "
                    f"entries, got {len(self._bounds)}")
            if any(b is not None and b < 1 for b in self._bounds):
                raise MXNetError("per-class max_queue bounds must be "
                                 ">= 1 (or None)")
        self._free = deque(range(self.num_slots))
        self._queues = [deque() for _ in range(self.num_priorities)]
        self._active = {}          # slot -> Request
        self._admitted = 0         # total admissions, drives aging
        # multi-tenant admission: {tenant: TenantQuota}. Tenants
        # without an entry (and tenant=None traffic) are unquoted with
        # weight 1 — single-tenant behaviour is unchanged.
        quotas = tenant_quotas or {}
        for t, q in quotas.items():
            if not isinstance(q, TenantQuota):
                raise MXNetError(f"tenant_quotas[{t!r}] must be a "
                                 "TenantQuota")
        self.tenant_quotas = dict(quotas)
        self._tenant_service = {}  # tenant -> weighted admissions
        self._tenant_admitted = {}  # tenant -> raw admissions (stats)

    @property
    def max_queue(self):
        """The scalar bound when all classes share one (the common,
        back-compatible configuration), else the per-class list."""
        first = self._bounds[0]
        if all(b == first for b in self._bounds):
            return first
        return list(self._bounds)

    # -- tenants -----------------------------------------------------------
    def quota_of(self, tenant):
        return self.tenant_quotas.get(tenant)

    def tenant_queued(self, tenant):
        return sum(r.tenant == tenant for q in self._queues for r in q)

    def tenant_active(self, tenant):
        return sum(r.tenant == tenant for r in self._active.values())

    def _weight(self, tenant):
        q = self.tenant_quotas.get(tenant)
        return q.weight if q is not None else 1.0

    # -- queue -------------------------------------------------------------
    @loop_only
    def submit(self, request):
        pr = min(max(int(getattr(request, "priority", 1)), 0),
                 self.num_priorities - 1)
        request.priority = pr
        bound = self._bounds[pr]
        if bound is not None and len(self._queues[pr]) >= bound:
            raise QueueFullError(
                f"admission queue full for priority class {pr} "
                f"({bound} waiting); rejecting request — retry after "
                "the queue drains",
                reason="queue_full", queue_depth=self.num_queued,
                active_slots=self.num_active, priority=pr)
        tenant = getattr(request, "tenant", None)
        quota = self.tenant_quotas.get(tenant)
        if quota is not None and quota.max_queue is not None \
                and self.tenant_queued(tenant) >= quota.max_queue:
            raise TenantQuotaError(
                f"tenant {tenant!r} is at its queue quota "
                f"({quota.max_queue} waiting); rejecting request — "
                "this tenant must drain before submitting more",
                reason="tenant_quota", tenant=tenant,
                queue_depth=self.num_queued,
                active_slots=self.num_active, priority=pr)
        request._seq = next(_seq_counter)
        self._queues[pr].append(request)
        return request

    @loop_only
    def requeue(self, request):
        """Put a request the engine rolled back (faulted dispatch,
        transient allocation failure) back at the FRONT of its class —
        it is older than everything queued behind it. Class bounds do
        not apply: the request was already admitted once."""
        self._queues[request.priority].appendleft(request)
        return request

    @loop_only
    def pop_expired(self, now):
        """Remove and return every queued request whose deadline has
        passed — the engine sheds these before admission."""
        out = []
        for q in self._queues:
            survivors = [r for r in q
                         if r.t_deadline is None or now < r.t_deadline]
            if len(survivors) != len(q):
                out.extend(r for r in q
                           if r.t_deadline is not None
                           and now >= r.t_deadline)
                q.clear()
                q.extend(survivors)
        return out

    def _eligible(self, req, now, probe_ok):
        if req.dispatch_failures > 0 and not probe_ok:
            return False             # one probationer in flight at a time
        if now is not None and req.t_not_before > now:
            return False             # still backing off
        quota = self.tenant_quotas.get(req.tenant)
        if quota is not None and quota.max_active is not None \
                and self.tenant_active(req.tenant) >= quota.max_active:
            return False             # tenant at its slot cap: stays
            # queued (the cap bounds occupancy; the queue bound sheds)
        return True

    def _pick(self, now):
        probe_ok = not any(r.dispatch_failures > 0
                           for r in self._active.values())
        if (self._admitted + 1) % self.aging_every == 0:
            # aging turn: globally oldest eligible request wins,
            # whatever its class or tenant — starvation-freedom
            # outranks fair share
            best = None
            for ci, q in enumerate(self._queues):
                for pos, req in enumerate(q):
                    if self._eligible(req, now, probe_ok) and (
                            best is None or req._seq < best[0]):
                        best = (req._seq, ci, pos)
            if best is not None:
                _, ci, pos = best
                req = self._queues[ci][pos]
                del self._queues[ci][pos]
                return req
            return None
        for q in self._queues:
            # deficit-weighted fair pick inside the class: each
            # contending tenant's oldest eligible request is a
            # candidate; the tenant with the least weighted service
            # wins (ties → FIFO by _seq). With one tenant (or none
            # configured) every candidate is the queue head — plain
            # FIFO, the pre-tenant behaviour.
            heads = {}               # tenant -> (pos, req), oldest
            for pos, req in enumerate(q):
                if req.tenant not in heads \
                        and self._eligible(req, now, probe_ok):
                    heads[req.tenant] = (pos, req)
            if not heads:
                continue
            pos, req = min(
                heads.values(),
                key=lambda pr: (
                    self._tenant_service.get(pr[1].tenant, 0.0)
                    / self._weight(pr[1].tenant),
                    pr[1]._seq))
            del q[pos]
            return req
        return None

    @loop_only
    def admit(self, now=None):
        """Pair queued requests with free slots: highest priority class
        first, FIFO within a class, with the aging and probation rules
        described in the module docstring. `now` (the engine's clock)
        activates backoff windows; None admits regardless of backoff.
        Returns the [(slot, request), ...] admitted this round."""
        admitted = []
        while self._free:
            req = self._pick(now)
            if req is None:
                break
            slot = self._free.popleft()
            self._active[slot] = req
            self._admitted += 1
            self._tenant_service[req.tenant] = \
                self._tenant_service.get(req.tenant, 0.0) + 1.0
            self._tenant_admitted[req.tenant] = \
                self._tenant_admitted.get(req.tenant, 0) + 1
            admitted.append((slot, req))
        return admitted

    @loop_only
    def release(self, slot):
        """Free a slot whose request finished (or was evicted)."""
        if slot not in self._active:
            raise MXNetError(f"slot {slot} is not active")
        req = self._active.pop(slot)
        self._free.append(slot)
        return req

    @loop_only
    def cancel_queued(self, request_id):
        """Remove a not-yet-admitted request from its queue by id.
        Returns the Request, or None when no queued request matches
        (it may already be running — see slot_of)."""
        for q in self._queues:
            for i, req in enumerate(q):
                if req.id == request_id:
                    del q[i]
                    return req
        return None

    def slot_of(self, request_id):
        """Slot currently decoding `request_id`, or None."""
        for slot, req in self._active.items():
            if req.id == request_id:
                return slot
        return None

    # -- introspection -----------------------------------------------------
    def request_at(self, slot):
        return self._active.get(slot)

    def queued_requests(self):
        """Queued requests, admission-priority order (class, then FIFO)."""
        return [r for q in self._queues for r in q]

    @property
    def queued_ids(self):
        """Request ids waiting for a slot, admission-priority order."""
        return [r.id for r in self.queued_requests()]

    def snapshot(self):
        """JSON-able view of the scheduler's state — what /statusz and
        the flight recorder's state.json embed: the slot map (slot →
        request id + progress), the waiting queues, and capacity."""
        return {
            "num_slots": self.num_slots,
            "max_queue": self.max_queue,
            "num_priorities": self.num_priorities,
            "aging_every": self.aging_every,
            "free_slots": sorted(self._free),
            "queued_ids": self.queued_ids,
            "queued_by_class": [len(q) for q in self._queues],
            "active": {
                str(slot): {
                    "request_id": req.id,
                    "prompt_len": req.prompt_len,
                    "priority": req.priority,
                    "tenant": req.tenant,
                    "adapter_id": req.adapter_id,
                    "generated": len(req.output_tokens),
                    "max_new_tokens": req.max_new_tokens,
                    "dispatch_failures": req.dispatch_failures,
                } for slot, req in sorted(self._active.items())},
            "tenants": self.tenants_snapshot(),
        }

    def tenants_snapshot(self):
        """Per-tenant quota occupancy — the /statusz tenants block.
        Covers every tenant with a configured quota plus any tenant
        that currently has queued/active work or has ever been
        admitted."""
        tenants = set(self.tenant_quotas)
        tenants.update(r.tenant for q in self._queues for r in q)
        tenants.update(r.tenant for r in self._active.values())
        tenants.update(self._tenant_admitted)
        out = {}
        for t in sorted(tenants, key=lambda x: (x is None, str(x))):
            quota = self.tenant_quotas.get(t)
            out[str(t)] = {
                "queued": self.tenant_queued(t),
                "active": self.tenant_active(t),
                "admitted": self._tenant_admitted.get(t, 0),
                "max_active": quota.max_active if quota else None,
                "max_queue": quota.max_queue if quota else None,
                "weight": self._weight(t),
            }
        return out

    @property
    def active_slots(self):
        return sorted(self._active)

    @property
    def num_active(self):
        return len(self._active)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_queued(self):
        return sum(len(q) for q in self._queues)

    @property
    def has_work(self):
        return bool(self._active or any(self._queues))
