"""Speculative decoding without a draft model: prompt-lookup drafting
plus distribution-preserving in-program verification.

Decode throughput is bounded by one model forward per emitted token per
slot; speculative decoding amortizes that forward over several candidate
tokens verified at once (the largest decode lever in the TPU serving
literature — see docs/SERVING.md "Speculative decoding"). No draft model
runs here: the PROPOSER is a host-side n-gram lookup over the request's
own prompt + emitted history (prompt-lookup decoding), which is free,
and pays off exactly on the workloads production decode is full of —
code, templated JSON, multi-turn chat, retrieval-augmented answers that
quote their context.

The two halves:

  * PromptLookupProposer (host): match the last n-gram of a request's
    history against earlier occurrences and draft the continuation of
    the match. Pure function of the request's own history — drafts
    never depend on the slot, the schedule, or co-batched requests, so
    the reproducibility contract of serving/sampling.py survives.
  * verify_tokens (in-program): one multi-query forward has produced
    logits for positions [current token, draft_1 .. draft_{S-1}];
    acceptance walks the drafts left to right.
      - greedy slots accept draft j+1 iff it equals argmax(logits_j) —
        the emitted tokens are EXACTLY the spec-off greedy stream, bit
        for bit.
      - sampled slots run standard speculative rejection sampling
        against the filtered distribution p_j (sampling.filtered_logits,
        the same definition the plain sampler uses). The prompt-lookup
        proposal is a point mass, so draft d is accepted with
        probability p_j(d), and a rejection samples from the residual
        p_j with d removed — the emitted marginal is exactly p_j
        (distribution-preserving, the Leviathan/Chen speculative
        sampling identity specialized to a deterministic proposer).

RNG contract: the token at request-stream index i derives every random
decision from fold_in(PRNGKey(seed), i) — fold_in(key, 1) for the accept
uniform, fold_in(key, 2) for the residual draw, and the UNSPLIT key for
a position with no draft (so a dispatch with zero drafts is
bit-identical to the spec-off sampler). Output therefore depends only on
(seed, token index, the request's own history) — reproducible across
schedules, slot counts, and acceptance histories.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .sampling import filtered_logits

__all__ = ["PromptLookupProposer", "verify_tokens"]


class PromptLookupProposer:
    """Draft up to `max_draft` tokens by n-gram lookup over a history.

    Tries n-gram sizes from `max_ngram` down to `min_ngram`: take the
    last n tokens, find their EARLIEST earlier occurrence (the earliest
    match leaves the longest continuation — on cyclic text the recent
    matches sit too close to the end to extrapolate), and draft the
    tokens that followed it. Stateless: propose() is a pure function of
    the history it is handed, which is what keeps drafting schedule-
    independent.
    """

    def __init__(self, max_draft, max_ngram=3, min_ngram=1):
        if max_draft < 1:
            raise ValueError("max_draft must be >= 1")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_draft = int(max_draft)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history):
        """history: 1-D int sequence (prompt + emitted so far). Returns
        an int32 array of 0..max_draft draft tokens (empty = no match;
        the dispatch then degenerates to plain one-token decode)."""
        h = np.asarray(history, np.int32)
        n = h.size
        for k in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            pat = h[n - k:]
            windows = np.lib.stride_tricks.sliding_window_view(h[:-1], k)
            hits = np.nonzero((windows == pat).all(axis=1))[0]
            if hits.size:
                start = int(hits[0]) + k
                return h[start:start + self.max_draft].copy()
        return np.zeros((0,), np.int32)


def _block_keys(seeds, counters, S):
    """(B,) seeds × (B,) stream offsets → (B, S) keys; the key at
    [b, j] is the request's stream element for token index
    counters[b] + j (serving/sampling.py slot_keys, widened per
    in-dispatch position)."""
    def one(seed, c0):
        return jax.vmap(
            lambda j: jax.random.fold_in(jax.random.PRNGKey(seed),
                                         c0 + j))(jnp.arange(S))
    return jax.vmap(one)(seeds, counters)


def verify_tokens(logits, drafts, n_draft, seeds, counters, do_sample,
                  temperature, top_k, top_p, greedy_only=False):
    """Verify one speculative dispatch. Inputs:

    logits:   (B, S, V) — position j conditions on [current token,
              draft_1..draft_j]; logits_j is the distribution of the
              token AFTER that prefix.
    drafts:   (B, S-1) int32 draft tokens (padding past n_draft ignored).
    n_draft:  (B,) int32 — live drafts per slot, 0..S-1.
    seeds/counters/do_sample/temperature/top_k/top_p: per-slot arrays
    (counters = the request-stream index of the FIRST token this
    dispatch emits).
    greedy_only: STATIC — when the caller knows no slot in the dispatch
    samples (the dominant greedy-serving shape), skip the filtered
    distribution, the stream keys, and the rejection draws entirely;
    greedy rows are bit-identical either way.

    Returns (emitted, n_acc): emitted (B, S) int32 — the token the slot
    would emit at each position (valid through position n_acc);
    n_acc (B,) int32 — leading drafts accepted. The caller emits
    emitted[:, :n_acc+1] (its own eos/budget truncation on top).
    """
    B, S, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # (B, S)
    cand_g = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1)
    if greedy_only:
        pos = jnp.arange(S)[None, :]
        is_draft = pos < n_draft[:, None]
        chain = jnp.cumprod(
            ((cand_g == greedy) & is_draft).astype(jnp.int32), axis=1)
        return greedy, chain.sum(axis=1)
    filt = filtered_logits(
        logits.reshape(B * S, V), jnp.repeat(temperature, S),
        jnp.repeat(top_k, S), jnp.repeat(top_p, S)).reshape(B, S, V)
    probs = jax.nn.softmax(filt, axis=-1)
    # position j's candidate is drafts[:, j]; the last position never
    # has one (it is the bonus sample when every draft was accepted)
    cand = cand_g
    p_cand = jnp.take_along_axis(probs, cand[..., None], axis=-1)[..., 0]
    keys = _block_keys(seeds, counters, S)
    # point-mass proposal => accept prob is the target mass of the draft
    u = jax.vmap(jax.vmap(
        lambda k: jax.random.uniform(jax.random.fold_in(k, 1))))(keys)
    accept = jnp.where(do_sample[:, None], u < p_cand, cand == greedy)
    pos = jnp.arange(S)[None, :]
    is_draft = pos < n_draft[:, None]
    chain = jnp.cumprod((accept & is_draft).astype(jnp.int32), axis=1)
    n_acc = chain.sum(axis=1)
    # rejection at j: sample the residual — p_j with the draft removed
    # (renormalization is categorical's job); a reject implies
    # p_j(draft) < 1, so the row keeps at least one finite entry
    resid_logits = jnp.where(
        jax.nn.one_hot(cand, V, dtype=bool), -jnp.inf, filt)
    resid = jax.vmap(jax.vmap(
        lambda k, row: jax.random.categorical(
            jax.random.fold_in(k, 2), row)))(keys, resid_logits)
    # no draft at j: a plain sample with the UNSPLIT stream key — the
    # zero-draft dispatch is bit-identical to the spec-off sampler
    full = jax.vmap(jax.vmap(jax.random.categorical))(keys, filt)
    sampled = jnp.where(
        pos < n_acc[:, None], cand,
        jnp.where(is_draft, resid, full)).astype(jnp.int32)
    emitted = jnp.where(do_sample[:, None], sampled, greedy)
    return emitted, n_acc
