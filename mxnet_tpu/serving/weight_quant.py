"""w8 weight serving: int8 weight codes on the sharded megatron split
(ISSUE 19).

`ServingEngine(weight_dtype="int8")` quantizes the megatron col/row
dense weights ONCE at construction — symmetric int8 with per-out-tile
f32 scales — and serves from the code arrays: the codes ride the same
dispatch operand positions (and the same PartitionSpecs) the fp32
weights did, the scales travel as extra replicated-or-sharded operands,
and the dequant is fused into the matmul as an output epilogue inside
`ops.nn.FullyConnected` (see `register_w8_weight` there). Everything
else — embeddings, the tied LM head, norms, biases — stays fp32.

Scale layout on the tp mesh:

- **column-parallel** (qkv / fc1, out-dim sharded): the default out
  tile divides the per-shard out dim at the FINEST legal split — the
  head count (`max_shards`), since tp must divide num_heads — so every
  tile lives inside one shard for EVERY shard count and the codes and
  scales are byte-identical across tp. The (n_tiles,) scale vector
  shards with the weight (`PartitionSpec(AXIS_TP)`) — literally
  per-(layer, shard, out-tile) scales, each shard's slice quantized
  against only its own rows.
- **row-parallel** (proj / fc2, in-dim sharded): scales are computed
  over the FULL in dim and replicated. Each shard applies its scales
  to its partial product BEFORE the psum (the scale depends only on
  the out index, so scaling the partials equals scaling the sum) —
  the per-shard dequant stays inside the one-psum-per-projection
  discipline. Shard-LOCAL row scales would make the served numerics a
  function of the shard count; shard-invariant scales keep the PR 15
  contract that greedy token streams are bit-identical tp=1 vs tp=N.

The quantized weights are pure construction-time data: no monotone
scale updates, no write schedules — w8 outputs are a deterministic
function of the tokens, unlike int8 KV pages (docs/SERVING.md "Weight
quantization").
"""
from __future__ import annotations

import re
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ops.nn import deregister_w8_weight, register_w8_weight
from ..parallel.mesh import PartitionSpec
from ..parallel.rules import megatron_kind

__all__ = ["QuantizedWeight", "pick_out_tile", "quantize_weight",
           "build_weight_plan", "dequantize", "quantize_dense_weights",
           "register_w8_weight", "deregister_w8_weight"]

# per-out-tile scale granularity cap: tiles are the largest divisor of
# the (per-shard) out dim <= this. 128 matches the MXU lane width, so
# the epilogue multiply broadcasts along full vector registers.
DEFAULT_TILE_CAP = 128


class QuantizedWeight(NamedTuple):
    """One quantized serving weight: `codes` replaces the fp32 array at
    the weight's dispatch operand position (same PartitionSpec), `scale`
    travels as an extra operand with `scale_spec`."""
    index: int              # position in the engine's param list
    name: str               # parameter path
    kind: str               # 'col' | 'row' (megatron split)
    codes: object           # int8 (out, in)
    scale: object           # f32 (out // tile,)
    tile: int               # out rows per scale entry
    scale_spec: object      # PartitionSpec for the scale operand


def pick_out_tile(n, cap=DEFAULT_TILE_CAP):
    """Largest divisor of `n` that is <= cap (>= 1)."""
    for d in range(min(int(n), int(cap)), 0, -1):
        if n % d == 0:
            return d
    return 1


def quantize_weight(w, kind, tp=1, tp_axis=None, tile=None,
                    max_shards=None):
    """Symmetric int8 quantization of a (out, in) dense weight with
    per-out-tile f32 scales. Returns a (codes, scale, tile, scale_spec)
    tuple; see the module docstring for the col/row layout contract.

    `max_shards` (column-parallel only) is the finest shard count the
    serving mesh could legally run — the engine passes num_heads — and
    pins the DEFAULT tile to divide out_dim // max_shards, so the
    quantization is a pure function of the weights, independent of the
    tp this engine happens to use (greedy streams stay bit-identical
    tp=1 vs tp=N, the PR 15 contract)."""
    w = jnp.asarray(w, jnp.float32)
    if w.ndim != 2:
        raise MXNetError(f"w8 quantizes 2-D dense weights, got {w.shape}")
    out_dim = int(w.shape[0])
    if kind == "col":
        shards = int(max_shards or tp)
        if shards % tp or out_dim % shards:
            raise MXNetError(
                f"column-parallel out dim {out_dim} / max_shards "
                f"{shards} not compatible with tp={tp}")
        tile = int(tile) if tile else pick_out_tile(out_dim // shards)
        if (out_dim // tp) % tile:
            raise MXNetError(
                f"out tile {tile} does not divide per-shard out dim "
                f"{out_dim // tp}")
        scale_spec = PartitionSpec(tp_axis) if tp > 1 else PartitionSpec()
    elif kind == "row":
        tile = int(tile) if tile else pick_out_tile(out_dim)
        if out_dim % tile:
            raise MXNetError(
                f"out tile {tile} does not divide out dim {out_dim}")
        scale_spec = PartitionSpec()
    else:
        raise MXNetError(f"unknown w8 weight kind {kind!r}")
    n_tiles = out_dim // tile
    grouped = jnp.reshape(w, (n_tiles, tile, w.shape[1]))
    amax = jnp.max(jnp.abs(grouped), axis=(1, 2))
    scale = jnp.maximum(amax, 1e-8).astype(jnp.float32) / 127.0
    codes = jnp.clip(jnp.round(grouped / scale[:, None, None]),
                     -127, 127).astype(jnp.int8)
    return (jnp.reshape(codes, w.shape), scale, tile, scale_spec)


def dequantize(q):
    """Merged dequantized fp32 weight for a QuantizedWeight (or any
    (codes, scale) pair with the per-out-tile layout) — the oracle the
    w8 tolerance tests serve against."""
    codes, scale = q.codes, q.scale
    c = np.asarray(codes, np.float32)
    s = np.repeat(np.asarray(scale, np.float32), c.shape[0] // scale.shape[0])
    return c * s[:, None]


def build_weight_plan(named_params, tp=1, tp_axis=None, tile=None,
                      max_shards=None):
    """Classify and quantize a model's serving weights.

    named_params: iterable of (name, Parameter) in the engine's param
    order. Every 2-D weight matching the megatron column/row split
    (parallel.rules.COL/ROW_WEIGHT_PATTERN) is quantized; embeddings,
    norms and biases are left untouched. `max_shards` pins the col tile
    to the finest legal split (see quantize_weight). Returns a list of
    QuantizedWeight entries (possibly empty)."""
    plan = []
    for index, (name, p) in enumerate(named_params):
        kind = megatron_kind(name)
        if kind is None:
            continue
        d = p.data()._data
        if d.ndim != 2:
            continue
        codes, scale, t, spec = quantize_weight(
            d, kind, tp=tp, tp_axis=tp_axis, tile=tile,
            max_shards=max_shards)
        plan.append(QuantizedWeight(index, name, kind, codes, scale, t,
                                    spec))
    return plan


def quantize_dense_weights(block, pattern=r"\.weight$", tile=None,
                           cap=DEFAULT_TILE_CAP):
    """Eager w8 for non-engine models (vision classifier heads etc.):
    quantize every matching 2-D Dense weight of `block` IN PLACE to int8
    codes and register persistent fused-dequant scales, so a plain
    forward runs the same one-byte-per-element weight read the serving
    engine uses. The block becomes inference-only (grad_req is forced to
    'null' on converted weights). Returns [(name, QuantizedWeight)]."""
    pat = re.compile(pattern)
    done = []
    for index, (name, p) in enumerate(block.collect_params().items()):
        if not pat.search(name) or p.shape is None or len(p.shape) != 2:
            continue
        d = p.data()._data
        codes, scale, t, spec = quantize_weight(
            d, megatron_kind(name) or "row", tile=tile or pick_out_tile(
                int(d.shape[0]), cap))
        register_w8_weight(codes, scale)
        arr = NDArray(codes)
        arr._grad_req = "null"
        p._grad_req = "null"
        p._data = arr
        done.append((name, QuantizedWeight(index, name, "row", codes,
                                           scale, t, spec)))
    return done
