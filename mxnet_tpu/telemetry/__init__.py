"""mx.telemetry — the framework-wide metrics + tracing subsystem.

Unified observability for serving and training (docs/OBSERVABILITY.md):
a process-global registry of named Counter/Gauge/Histogram instruments
(exponential-bucket histograms for latencies, prometheus-style labeled
children), `span(name)` tracing that nests, logs JSONL, and lines up
with the XLA device trace, and on-demand device-memory watermark
sampling.

Instrumented call sites:
  * serving/engine.py + serving/scheduler.py — queue depth, admission
    wait, TTFT, per-token decode latency, slot occupancy,
    prefill/decode dispatch counts + wall time, drain time, rejected
    submissions;
  * gluon/trainer.py — eager step wall time and count;
  * kvstore.py — out-of-program allreduce/broadcast bytes + wall time;
  * parallel/comm.py — the static per-step collective wire budget of a
    compiled program (comm_report publishes gauges);
  * gluon/block.py — jit trace-cache retrace/eviction counters
    (mx.runtime.jit_cache_stats() is now a view over these).

Zero dependencies: importing this package touches only the stdlib —
never jax — so it is safe anywhere, including backend-free processes.

Live introspection (docs/OBSERVABILITY.md):
  * `serve(port)` — stdlib HTTP server on a daemon thread exposing
    /metrics, /healthz, /statusz, /requests, /trace;
  * `request_log` — per-request lifecycle timelines (bounded ring),
    `chrome_trace()` exports them (plus spans) as Chrome/Perfetto
    trace_event JSON;
  * `flight` — anomaly-triggered flight recorder: event ring +
    stall/queue-full/NaN/retrace watchdog, atomic once-per-trigger
    dumps;
  * `cost` — device-cost accounting: per-program cost_analysis
    registry (FLOPs, bytes), live MFU/roofline gauges, compile
    attribution (`/compilez`);
  * `ledger` — HBM ledger: per-subsystem byte accounting reconciled
    against live-array watermarks (`/memz`).

Quick use:
    import mxnet_tpu as mx
    mx.telemetry.snapshot()                    # nested dict
    print(mx.telemetry.render_prometheus())    # text exposition
    mx.telemetry.dump("telemetry.json")
    with mx.telemetry.span("my.phase"):
        ...
    mx.telemetry.serve(9100)                   # live introspection
    mx.telemetry.flight.install(out_dir="flight_dumps")
    mx.telemetry.reset()                       # tests / bench rounds
"""
from __future__ import annotations

from .instruments import (  # noqa: F401
    Counter, Gauge, Histogram, Registry,
    DEFAULT_LATENCY_BUCKETS, exponential_buckets,
)
from .tracing import (  # noqa: F401
    span, events, clear_events, enable_jsonl, disable_jsonl,
    add_event_hook, remove_event_hook,
)
from .request_trace import (  # noqa: F401
    RequestTrace, RequestTraceLog, request_log, chrome_trace,
    PHASES, new_trace_id, new_span_id, parse_traceparent,
    format_traceparent, now,
)
from .server import (  # noqa: F401
    IntrospectionServer, serve, stop_server, get_server,
    register_status_provider, unregister_status_provider,
    collect_status, register_ready_probe, unregister_ready_probe,
    readiness, component_ready,
)
from . import cost  # noqa: F401
from . import flight  # noqa: F401
from . import ledger  # noqa: F401
from . import memory  # noqa: F401
from . import slo  # noqa: F401
from .slo import SLO, slo_engine  # noqa: F401

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "DEFAULT_LATENCY_BUCKETS", "exponential_buckets",
           "default_registry", "counter", "gauge", "histogram", "get",
           "snapshot", "render_prometheus", "dump", "reset",
           "span", "events", "clear_events", "enable_jsonl",
           "disable_jsonl", "add_event_hook", "remove_event_hook",
           "RequestTrace", "RequestTraceLog", "request_log",
           "chrome_trace", "PHASES", "new_trace_id", "new_span_id",
           "parse_traceparent", "format_traceparent", "now",
           "SLO", "slo_engine", "slo",
           "IntrospectionServer", "serve",
           "stop_server", "get_server", "register_status_provider",
           "unregister_status_provider", "collect_status",
           "register_ready_probe", "unregister_ready_probe",
           "readiness", "component_ready",
           "cost", "flight", "ledger", "memory"]

#: The process-global registry every framework instrument lives in.
default_registry = Registry()


def counter(name, help="", labelnames=()):
    """Get-or-create a Counter in the default registry."""
    return default_registry.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    """Get-or-create a Gauge in the default registry."""
    return default_registry.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    """Get-or-create a Histogram in the default registry."""
    return default_registry.histogram(name, help, labelnames, buckets)


def get(name):
    """Look up an instrument by name (None when absent)."""
    return default_registry.get(name)


def snapshot():
    """Nested dict of every instrument's current state."""
    return default_registry.snapshot()


def render_prometheus():
    """Prometheus text exposition of the default registry."""
    return default_registry.render_prometheus()


def dump(path):
    """Write snapshot() as JSON to `path`; returns the path."""
    return default_registry.dump(path)


def reset():
    """Zero every instrument in place and clear the span + request
    rings (instrument/child identities survive — safe with live
    engines)."""
    default_registry.reset()
    clear_events()
    request_log.clear()
    slo.slo_engine.clear()
