"""Device-cost accounting: program cost registry, MFU/roofline gauges,
and compile attribution.

PRs 2 and 5 made the host side observable; this module makes the
*device economics* observable (docs/OBSERVABILITY.md "Device-cost
accounting"). Three pieces, one process-global program table:

  * **Program cost registry** — every jitted program the framework
    dispatches registers its XLA ``cost_analysis()`` (FLOPs, bytes
    accessed) keyed by a program signature string (``engine0/prefill/64``,
    ``engine0/decode/greedy``, ``train_step``). Combined with the
    measured per-dispatch wall time it publishes live MFU
    (``cost_mfu{program}``), achieved bandwidth, arithmetic intensity,
    and a compute-vs-memory-bound roofline classification per program.
  * **Compile attribution** — ``CostedFunction`` wraps a ``jax.jit``
    callable for one fixed signature: the first call times the full
    trace+lower+compile explicitly (AOT), extracts the cost analysis,
    and counts ``compiles_total{program}`` / ``compile_seconds_total
    {program}``; later calls run the compiled executable directly.
    Compile events feed registered hooks — the flight recorder
    subscribes so a *steady-state* retrace (shape churn after warmup)
    latches a dump with the offending program key.
  * **Peaks** — per-device peak FLOP/s and HBM bandwidth by device
    kind (public Google Cloud TPU system-architecture numbers), env-
    overridable with ``MXNET_TPU_PEAK_FLOPS`` / ``MXNET_TPU_PEAK_
    BANDWIDTH``. The ridge point (peak_flops / peak_bw) classifies
    each program: arithmetic intensity above the ridge is compute
    bound, below is memory bound.

In-path cost per dispatch is a handful of instrument updates (~µs
against multi-ms dispatches); ``set_enabled(False)`` turns the in-path
accounting into a no-op for A/B runs (the AOT wrapping itself stays —
it is structural, not per-dispatch work).

Stdlib-only at import: jax is imported lazily inside ``peaks()`` (and
only when a device has necessarily been initialized by the caller).
"""
from __future__ import annotations

import math
import os
import threading
import time

__all__ = ["CostedFunction", "register_program", "record_compile",
           "note_dispatch", "get", "report", "peaks", "set_enabled",
           "enabled", "add_compile_hook", "remove_compile_hook",
           "reset_programs"]

_lock = threading.Lock()
_programs = {}             # program key -> _ProgramRecord
_compile_hooks = []
_enabled = True
_device_peaks = None       # cached (flops, bw, kind) from the backend
_peaks_published = None    # last (flops, bw) written to the gauges


# (device-kind substring, (peak bf16 FLOP/s, peak HBM bytes/s)).
# Sources: public Google Cloud TPU system-architecture pages (checked
# 2025) — same flops table as bench.py's peak_flops(); bandwidth from
# the per-generation spec tables (v2 700 GB/s, v3 900 GB/s, v4
# 1228 GB/s, v5e 819 GB/s, v5p 2765 GB/s, v6e/Trillium 1640 GB/s).
# Ordered: more specific substrings first ("v5 lite" before "v5").
_PEAK_TABLE = (
    ("v5 lite", (197e12, 819e9)), ("v5litepod", (197e12, 819e9)),
    ("v5e", (197e12, 819e9)),
    ("v6 lite", (918e12, 1640e9)), ("v6e", (918e12, 1640e9)),
    ("v5p", (459e12, 2765e9)),
    ("v4", (275e12, 1228e9)),
    ("v5", (459e12, 2765e9)),
    ("v3", (123e12, 900e9)),
    ("v2", (45e12, 700e9)),
)
# nominal single-core numbers so CPU smoke runs produce finite ratios
_FALLBACK_PEAKS = (1e12, 100e9)


class _ProgramRecord:
    """One program's registered cost + accumulated compile/dispatch
    totals (mirrored onto labeled instruments; this object is the
    /compilez + report() source of truth)."""

    __slots__ = ("program", "flops", "bytes_accessed", "source",
                 "compiles", "compile_seconds", "dispatches",
                 "dispatch_seconds", "last_seconds", "last_compile_ts",
                 "shards")

    def __init__(self, program):
        self.program = program
        self.flops = None
        self.bytes_accessed = None
        self.source = None
        self.compiles = 0
        self.compile_seconds = 0.0
        self.dispatches = 0
        self.dispatch_seconds = 0.0
        self.last_seconds = None
        self.last_compile_ts = None
        self.shards = 1


_P = ("program",)
_metrics_cache = None


def _metrics():
    """Get-or-create the cost instrument family (lazy so importing
    telemetry stays declaration-free until cost accounting is used)."""
    global _metrics_cache
    if _metrics_cache is None:
        from . import counter, gauge
        _metrics_cache = {
            "compiles": counter(
                "compiles_total",
                "trace+lower+compile events per program signature", _P),
            "compile_seconds": counter(
                "compile_seconds_total",
                "wall seconds spent compiling, per program signature",
                _P),
            "dispatches": counter(
                "cost_dispatches_total",
                "cost-accounted dispatches per program", _P),
            "dispatch_seconds": counter(
                "cost_dispatch_seconds_total",
                "accumulated dispatch wall seconds per program", _P),
            "program_flops": gauge(
                "cost_program_flops",
                "XLA cost_analysis FLOPs of one dispatch of the "
                "program", _P),
            "program_bytes": gauge(
                "cost_program_bytes_accessed",
                "XLA cost_analysis bytes accessed by one dispatch", _P),
            "ai": gauge(
                "cost_arithmetic_intensity",
                "program FLOPs / bytes accessed (roofline x-axis)", _P),
            "compute_bound": gauge(
                "cost_compute_bound",
                "1 = arithmetic intensity above the device ridge point "
                "(compute bound), 0 = below (memory bound)", _P),
            "mfu": gauge(
                "cost_mfu",
                "model FLOPs utilization of the last dispatch "
                "(flops / wall / peak_flops)", _P),
            "achieved_flops": gauge(
                "cost_achieved_flops_per_sec",
                "program FLOPs / last dispatch wall", _P),
            "achieved_bw": gauge(
                "cost_achieved_bandwidth_bytes_per_sec",
                "program bytes accessed / last dispatch wall", _P),
            "peak_flops": gauge(
                "cost_peak_flops",
                "assumed per-chip peak FLOP/s (device table or "
                "MXNET_TPU_PEAK_FLOPS)"),
            "peak_bw": gauge(
                "cost_peak_bandwidth_bytes_per_sec",
                "assumed per-chip peak HBM bytes/s (device table or "
                "MXNET_TPU_PEAK_BANDWIDTH)"),
            "ridge": gauge(
                "cost_ridge_intensity",
                "device ridge point: peak_flops / peak_bandwidth "
                "(FLOPs per byte)"),
        }
    return _metrics_cache


# -- peaks ------------------------------------------------------------------

def peaks():
    """(peak_flops, peak_bandwidth_bytes_per_sec, device_kind).

    Env overrides are read every call (tests, odd hardware); the
    device-kind lookup hits the backend once and is cached. Safe
    without jax: falls back to nominal CPU numbers."""
    global _device_peaks
    if _device_peaks is None:
        kind, table = "unknown", _FALLBACK_PEAKS
        try:
            import jax
            dev = jax.devices()[0]
            kind = str(getattr(dev, "device_kind", "") or dev.platform)
            low = kind.lower()
            for sub, vals in _PEAK_TABLE:
                if sub in low:
                    table = vals
                    break
        except Exception:
            pass
        _device_peaks = (table[0], table[1], kind)
    flops = float(os.environ.get("MXNET_TPU_PEAK_FLOPS", 0) or 0) \
        or _device_peaks[0]
    bw = float(os.environ.get("MXNET_TPU_PEAK_BANDWIDTH", 0) or 0) \
        or _device_peaks[1]
    global _peaks_published
    if _peaks_published != (flops, bw):     # hot path: publish on change
        m = _metrics()
        m["peak_flops"].set(flops)
        m["peak_bw"].set(bw)
        m["ridge"].set(flops / bw)
        _peaks_published = (flops, bw)
    return flops, bw, _device_peaks[2]


# -- enable/disable the in-path accounting ----------------------------------

def set_enabled(flag):
    """Gate the per-dispatch accounting (note_dispatch becomes a no-op
    returning None). Compile attribution and program registration are
    one-time events and stay on."""
    global _enabled
    _enabled = bool(flag)


def enabled():
    return _enabled


# -- the program table ------------------------------------------------------

def _record(program):
    rec = _programs.get(program)
    if rec is None:
        rec = _programs.setdefault(program, _ProgramRecord(program))
    return rec


def register_program(program, flops=None, bytes_accessed=None,
                     source="xla", shards=1):
    """Register (or refresh) a program's static cost. `flops`/`bytes_
    accessed` of ONE dispatch — the WHOLE-MODEL figures, summed over
    partitions for an SPMD program (callers extracting from a sharded
    executable multiply the per-partition cost_analysis() up before
    registering; CostedFunction(shards=N) does this). `shards` is the
    partition count: note_dispatch divides by it so the per-chip MFU /
    bandwidth gauges stay honest under tp>1 while `.flops` keeps
    feeding whole-model goodput counters. Non-finite / non-positive
    values are treated as unknown (backends that don't report costs).
    Returns the record."""
    def _clean(v):
        if v is None:
            return None
        v = float(v)
        return v if math.isfinite(v) and v > 0 else None

    flops, bytes_accessed = _clean(flops), _clean(bytes_accessed)
    with _lock:
        rec = _record(program)
        if flops is not None:
            rec.flops = flops
        if bytes_accessed is not None:
            rec.bytes_accessed = bytes_accessed
        rec.source = source
        rec.shards = max(int(shards), 1)
        flops, bytes_accessed = rec.flops, rec.bytes_accessed
    m = _metrics()
    if flops is not None:
        m["program_flops"].labels(program).set(flops)
    if bytes_accessed is not None:
        m["program_bytes"].labels(program).set(bytes_accessed)
    if flops is not None and bytes_accessed is not None:
        ai = flops / bytes_accessed
        pf, pb, _ = peaks()
        m["ai"].labels(program).set(ai)
        m["compute_bound"].labels(program).set(
            1.0 if ai >= pf / pb else 0.0)
    return get(program)


def record_compile(program, seconds, steady=False):
    """Count one trace+lower+compile of `program` and fan the event out
    to the compile hooks (the flight recorder's retrace-storm detector
    rides here). `steady=True` marks a compile AFTER the owner declared
    steady state — shape churn that should not happen."""
    seconds = float(seconds)
    with _lock:
        rec = _record(program)
        rec.compiles += 1
        rec.compile_seconds += seconds
        rec.last_compile_ts = time.time()
        hooks = list(_compile_hooks)
    m = _metrics()
    m["compiles"].labels(program).inc()
    m["compile_seconds"].labels(program).inc(seconds)
    ev = {"program": program, "seconds": seconds, "steady": bool(steady),
          "ts": time.time()}
    for fn in hooks:
        try:
            fn(ev)
        except Exception:
            pass               # a broken subscriber must not break dispatch
    return ev


def note_dispatch(program, seconds):
    """Attribute one measured dispatch wall to `program`; publishes the
    live MFU / achieved-bandwidth gauges when the program has a
    registered cost. Returns the program record (None when accounting
    is disabled) — callers use `.flops` for goodput counters."""
    if not _enabled:
        return None
    seconds = max(float(seconds), 1e-9)
    with _lock:
        rec = _record(program)
        rec.dispatches += 1
        rec.dispatch_seconds += seconds
        rec.last_seconds = seconds
        flops, nbytes = rec.flops, rec.bytes_accessed
        sh = rec.shards or 1
    m = _metrics()
    m["dispatches"].labels(program).inc()
    m["dispatch_seconds"].labels(program).inc(seconds)
    # registered cost is whole-model; the gauges compare against ONE
    # chip's peak, so a tp=N program's achieved figures divide by the
    # shard count (each chip only did 1/N of the FLOPs in that wall)
    if flops is not None:
        pf, _, _ = peaks()
        m["mfu"].labels(program).set(flops / seconds / pf / sh)
        m["achieved_flops"].labels(program).set(flops / seconds / sh)
        # re-assert the static gauge so a telemetry.reset() between
        # bench rounds heals on the next dispatch (set only on change
        # would read a lock anyway; one blind set is the same cost)
        m["program_flops"].labels(program).set(flops)
    if nbytes is not None:
        m["achieved_bw"].labels(program).set(nbytes / seconds / sh)
        m["program_bytes"].labels(program).set(nbytes)
    return rec


def get(program):
    """Snapshot dict of one program's record (None when unknown)."""
    with _lock:
        rec = _programs.get(program)
        if rec is None:
            return None
        return _snap(rec)


def _snap(rec):
    out = {k: getattr(rec, k) for k in _ProgramRecord.__slots__}
    sh = rec.shards or 1
    if rec.flops and rec.bytes_accessed:
        out["arithmetic_intensity"] = rec.flops / rec.bytes_accessed
    if rec.flops and rec.last_seconds:
        pf, pb, _ = peaks()
        out["mfu"] = rec.flops / rec.last_seconds / pf / sh
        if rec.bytes_accessed:
            out["bandwidth_util"] = (rec.bytes_accessed
                                     / rec.last_seconds / pb / sh)
    return out


def report():
    """The /compilez + `dump_telemetry --cost` view: every program's
    registered cost, roofline placement, compile attribution and
    dispatch totals, plus the assumed device peaks."""
    pf, pb, kind = peaks()
    with _lock:
        progs = {p: _snap(r) for p, r in sorted(_programs.items())}
    ridge = pf / pb
    for snap in progs.values():
        ai = snap.get("arithmetic_intensity")
        if ai is not None:
            snap["bound"] = "compute" if ai >= ridge else "memory"
    return {"device_kind": kind, "peak_flops": pf,
            "peak_bandwidth_bytes_per_sec": pb,
            "ridge_intensity": ridge, "programs": progs}


def reset_programs():
    """Forget every program record (tests / between bench rounds that
    rebuild their engines). Instruments are left to telemetry.reset()."""
    with _lock:
        _programs.clear()


# -- compile hooks ----------------------------------------------------------

def add_compile_hook(fn):
    """fn(event_dict) runs on every record_compile (the flight recorder
    subscribes for steady-state retrace detection)."""
    with _lock:
        if fn not in _compile_hooks:
            _compile_hooks.append(fn)


def remove_compile_hook(fn):
    with _lock:
        try:
            _compile_hooks.remove(fn)
        except ValueError:
            pass


# -- the AOT wrapper --------------------------------------------------------

def _cost_from_compiled(compiled):
    """(flops, bytes_accessed) from an XLA Compiled, None-safe across
    backend/version variations (list-of-dicts vs dict, missing keys,
    sentinel -1 values)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not ca:
        return None, None
    d = dict(ca)
    return d.get("flops"), d.get("bytes accessed")


class CostedFunction:
    """AOT wrapper around a ``jax.jit`` function for ONE fixed call
    signature: the first call explicitly lowers + compiles (timed into
    ``compiles_total{program}`` / ``compile_seconds_total{program}``),
    registers the program's ``cost_analysis()`` FLOPs and bytes, and
    caches the compiled executable; every later call runs the
    executable directly — same arguments, same donation semantics.

    ``steady_fn`` (optional, ``() -> bool``): when it returns True at
    compile time the compile event is flagged *steady* — the flight
    recorder treats a steady compile as a retrace storm and latches a
    dump. Owners flip it after warmup (``ServingEngine.mark_warm()``).

    ``cost_scale``: multiplier applied to the extracted FLOPs/bytes
    before registration. XLA's HloCostAnalysis counts a while/scan body
    ONCE regardless of trip count, so a program that runs K chained
    steps per dispatch (the serving engine's K-step decode scan) must
    pass its trip count here for the per-dispatch cost to be honest.

    ``shards``: SPMD partition count of the program. ``cost_analysis()``
    on a sharded executable reports PER-PARTITION figures, so they are
    multiplied by `shards` before registration (the registry holds
    whole-model cost) and `note_dispatch` divides its per-chip gauges
    back down — `cost_mfu{program}` stays an honest fraction of ONE
    chip's peak at any tp.

    If AOT lowering fails (exotic backend), the wrapper falls back to
    calling the jitted function directly — the compile is then timed
    inside the first dispatch, and the program registers without cost
    figures (MFU gauges simply stay absent)."""

    __slots__ = ("_fn", "program", "_steady_fn", "_call", "_cost_scale",
                 "_shards")

    def __init__(self, fn, program, steady_fn=None, cost_scale=1.0,
                 shards=1):
        self._fn = fn
        self.program = str(program)
        self._steady_fn = steady_fn
        self._call = None
        self._cost_scale = float(cost_scale)
        self._shards = max(int(shards), 1)

    def __call__(self, *args):
        call = self._call
        if call is None:
            t0 = time.perf_counter()
            flops = nbytes = None
            try:
                compiled = self._fn.lower(*args).compile()
                flops, nbytes = _cost_from_compiled(compiled)
                call = compiled
            except Exception:
                call = self._fn        # jit compiles inside call #1
            dt = time.perf_counter() - t0
            self._call = call
            s = self._cost_scale * self._shards
            register_program(self.program,
                             flops * s if flops else flops,
                             nbytes * s if nbytes else nbytes,
                             shards=self._shards)
            steady = False
            if self._steady_fn is not None:
                try:
                    steady = bool(self._steady_fn())
                except Exception:
                    steady = False
            record_compile(self.program, dt, steady=steady)
        return call(*args)
