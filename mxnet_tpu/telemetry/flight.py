"""Anomaly-triggered flight recorder.

A black box for the serving/training process: a fixed-size ring of
recent telemetry events (span exits + request lifecycle events + any
`record()`ed breadcrumbs) plus a watchdog thread, and on an anomaly an
**atomic, once-per-trigger dump** of everything an offline triage
needs (docs/OBSERVABILITY.md "Flight recorder"):

    <out_dir>/<reason>-<timestamp>/
        events.jsonl     the ring, oldest first
        metrics.json     full registry snapshot
        state.json       trigger reason/detail, component status
                         (engine config, slot map, queue), recent
                         request timelines

Dumps are staged in a `.tmp` sibling and os.rename()d into place, so
a reader never sees a half-written directory. Each trigger *reason*
latches after its first dump — a stalled loop or a NaN storm fires
once, not once per watchdog tick — until `rearm()`.

Built-in detectors (all opt-in via `install()`):
  * **stall** — a watched component reports (progress, busy); busy
    with frozen progress past `stall_timeout` seconds trips
    `stall:<name>`. ServingEngine registers itself: progress is its
    dispatch/finish counter sum, busy is `scheduler.has_work`.
  * **queue-full storm** — `note_queue_full()` timestamps (the engine
    calls it on every QueueFullError); more than
    `queue_full_threshold` within `queue_full_window` seconds trips
    `queue_full:<name>`.
  * **non-finite grads** — `gluon.trainer` (sentinel armed by
    `install(watch_trainer=True)`) checks the global gradient norm
    each step and trips `trainer_nonfinite` on NaN/Inf (a NaN loss
    backpropagates NaN into every gradient, so this catches NaN loss
    without seeing the loss).
  * **retrace storms** — the recorder subscribes to `telemetry.cost`'s
    compile hook: every compile becomes a ring breadcrumb, and a
    compile flagged *steady* (the owning engine declared warmup over
    via `mark_warm()` yet a program still compiled inside the dispatch
    loop) trips `retrace_storm:<program key>` with the offending
    program signature in the dump detail.

Stdlib only; never imports jax.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque

__all__ = ["FlightRecorder", "install", "uninstall", "get", "record",
           "trigger", "note_queue_full", "note_shed",
           "trainer_sentinel_enabled", "latched_reasons", "watch",
           "unwatch"]

_recorder = None
_lock = threading.Lock()

# Stall-watch probes live at MODULE level so a component can register
# at construction time and a recorder installed later still sees it
# (and an uninstall/reinstall keeps the probes). Values are weak for
# bound methods — a collected engine drops out silently.
_watches = {}              # name -> weak ref / thunk returning probe


def watch(name, probe):
    """Register `probe() -> (progress, busy)` for stall detection:
    `progress` must move while `busy` is True, else an armed recorder
    trips `stall:<name>` after its stall_timeout. Bound methods are
    weakly held."""
    if hasattr(probe, "__self__"):
        ref = weakref.WeakMethod(probe)
    else:
        ref = lambda p=probe: p                           # noqa: E731
    _watches[str(name)] = ref


def unwatch(name):
    _watches.pop(str(name), None)


class FlightRecorder:
    def __init__(self, out_dir="flight_dumps", capacity=4096,
                 stall_timeout=30.0, poll_interval=None,
                 queue_full_threshold=64, queue_full_window=1.0,
                 watch_trainer=False):
        self.out_dir = str(out_dir)
        self.stall_timeout = float(stall_timeout)
        self.queue_full_threshold = int(queue_full_threshold)
        self.queue_full_window = float(queue_full_window)
        self.watch_trainer = bool(watch_trainer)
        self._ring = deque(maxlen=int(capacity))
        self._ring_lock = threading.Lock()
        self._fired = set()            # latched reasons
        self._fired_lock = threading.Lock()
        self._watch_state = {}         # name -> {progress, since}
        self._queue_full = {}          # name -> deque of timestamps
        self._dumps = []               # paths written, oldest first
        from . import counter
        self._dump_counter = counter(
            "flight_dumps_total",
            "flight-recorder dumps written", labelnames=("reason",))
        self._event_counter = counter(
            "flight_ring_events_total",
            "events captured into the flight ring")
        # subscribe to both telemetry event streams + compile events
        from . import cost, tracing
        from .request_trace import request_log
        self._span_hook = lambda ev: self.record("span", **ev)
        self._req_hook = lambda tr, ev: self.record(
            "request", request_id=tr.request_id, engine=tr.engine, **ev)
        tracing.add_event_hook(self._span_hook)
        request_log.add_hook(self._req_hook)
        self._compile_hook = self._on_compile
        cost.add_compile_hook(self._compile_hook)
        self._poll = float(poll_interval if poll_interval is not None
                           else max(min(self.stall_timeout / 4, 1.0), 0.01))
        self._stop = threading.Event()
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, name="mx-flight-watchdog",
            daemon=True)
        self._watchdog.start()

    # -- the ring ----------------------------------------------------------
    def record(self, kind, **attrs):
        """Append one breadcrumb to the ring (cheap: one lock + append)."""
        ev = dict(kind=kind, t=time.time(), **attrs)
        with self._ring_lock:
            self._ring.append(ev)
        self._event_counter.inc()

    def events(self):
        with self._ring_lock:
            return list(self._ring)

    # -- stall watch -------------------------------------------------------
    def _watchdog_loop(self):
        while not self._stop.wait(self._poll):
            now = time.monotonic()
            for name, ref in list(_watches.items()):
                st = self._watch_state.setdefault(
                    name, {"progress": None, "since": None})
                probe = ref()
                if probe is None:
                    _watches.pop(name, None)
                    continue
                try:
                    progress, busy = probe()
                except Exception:
                    continue
                if not busy or progress != st["progress"]:
                    st["progress"], st["since"] = progress, now
                    continue
                if st["since"] is not None and \
                        now - st["since"] > self.stall_timeout:
                    self.trigger(
                        f"stall:{name}",
                        {"stalled_for_s": round(now - st["since"], 3),
                         "progress": progress,
                         "stall_timeout_s": self.stall_timeout})

    # -- retrace storm (compile-after-warmup) ------------------------------
    def _on_compile(self, ev):
        """telemetry.cost compile hook: breadcrumb every compile; a
        compile the owner flagged as steady-state (shape churn inside
        the dispatch loop after warmup) latches `retrace_storm:<key>`
        with the offending program signature."""
        self.record("compile", program=ev.get("program"),
                    seconds=ev.get("seconds"),
                    steady=ev.get("steady", False))
        if ev.get("steady"):
            self.trigger(
                f"retrace_storm:{ev.get('program')}",
                {"program": ev.get("program"),
                 "compile_seconds": ev.get("seconds"),
                 "note": "a program compiled inside the dispatch loop "
                         "after its owner declared steady state — "
                         "unexpected shape churn"})

    # -- queue-full / shed storms ------------------------------------------
    def _note_storm(self, kind, name):
        """Shared rejection-storm detector: timestamp one event of
        `kind` for component `name`; trips `<kind>:<name>` when the
        trailing window fills past the threshold."""
        dq = self._queue_full.setdefault(
            (kind, name), deque(maxlen=self.queue_full_threshold))
        now = time.monotonic()
        dq.append(now)
        self.record(kind, component=name)
        if len(dq) == self.queue_full_threshold and \
                now - dq[0] <= self.queue_full_window:
            self.trigger(
                f"{kind}:{name}",
                {"rejections": len(dq),
                 "window_s": round(now - dq[0], 4),
                 "threshold": self.queue_full_threshold})

    def note_queue_full(self, name="engine"):
        """Timestamp one QueueFullError; trips `queue_full:<name>` when
        the trailing window fills past the threshold."""
        self._note_storm("queue_full", str(name))

    def note_shed(self, name="engine"):
        """Timestamp one policy shed (the engine calls it on every
        ShedError); trips `shed_storm:<name>` when the trailing window
        fills past the queue-full threshold — sustained shedding is the
        same anomaly class as a queue-full storm."""
        self._note_storm("shed_storm", str(name))

    # -- trigger + dump ----------------------------------------------------
    def trigger(self, reason, detail=None):
        """Dump ring + metrics + component state for `reason`. Latched:
        the first call per reason writes the dump and returns its path;
        repeats return None until `rearm(reason)`."""
        reason = str(reason)
        with self._fired_lock:
            if reason in self._fired:
                return None
            self._fired.add(reason)
        path = self._dump(reason, detail)
        self._dumps.append(path)
        self._dump_counter.labels(reason).inc()
        return path

    def rearm(self, reason=None):
        """Un-latch one reason (or all) so it can trigger again."""
        with self._fired_lock:
            if reason is None:
                self._fired.clear()
            else:
                self._fired.discard(str(reason))

    @property
    def dumps(self):
        return list(self._dumps)

    @property
    def latched(self):
        """Trigger reasons that have fired and not been rearm()ed —
        /healthz reports `degraded` while this is non-empty."""
        with self._fired_lock:
            return sorted(self._fired)

    def _dump(self, reason, detail):
        from . import snapshot
        from .request_trace import request_log
        from .server import collect_status

        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime())
        final = os.path.join(self.out_dir,
                             f"{safe}-{stamp}-{os.getpid()}")
        n = 0
        while os.path.exists(final):           # same reason+second
            n += 1
            final = f"{final}.{n}"
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "events.jsonl"), "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev, default=str) + "\n")
        with open(os.path.join(tmp, "metrics.json"), "w") as f:
            json.dump({"ts": time.time(), "instruments": snapshot()},
                      f, indent=1, sort_keys=True, default=str)
        state = {"reason": reason, "detail": detail, "ts": time.time(),
                 "pid": os.getpid(),
                 "components": collect_status(),
                 "requests": request_log.recent(64)}
        with open(os.path.join(tmp, "state.json"), "w") as f:
            json.dump(state, f, indent=1, sort_keys=True, default=str)
        os.rename(tmp, final)                  # atomic publish
        return final

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        self._stop.set()
        self._watchdog.join(timeout=5)
        from . import cost, tracing
        from .request_trace import request_log
        tracing.remove_event_hook(self._span_hook)
        request_log.remove_hook(self._req_hook)
        cost.remove_compile_hook(self._compile_hook)


# -- module-level singleton (what the engine/trainer hooks talk to) --------

def install(**kw):
    """Create and arm the process flight recorder (replaces any prior
    one). See FlightRecorder for the knobs."""
    global _recorder
    with _lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = FlightRecorder(**kw)
        return _recorder


def uninstall():
    global _recorder
    with _lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.close()


def get():
    return _recorder


def record(kind, **attrs):
    """Breadcrumb into the ring; no-op when no recorder is armed."""
    rec = _recorder
    if rec is not None:
        rec.record(kind, **attrs)


def trigger(reason, detail=None):
    rec = _recorder
    return rec.trigger(reason, detail) if rec is not None else None


def note_queue_full(name="engine"):
    rec = _recorder
    if rec is not None:
        rec.note_queue_full(name)


def note_shed(name="engine"):
    rec = _recorder
    if rec is not None:
        rec.note_shed(name)


def latched_reasons():
    """Latched trigger reasons of the armed recorder ([] when none) —
    the /healthz degraded probe."""
    rec = _recorder
    return rec.latched if rec is not None else []


def trainer_sentinel_enabled():
    """True when an armed recorder asked for trainer NaN/Inf checks —
    the per-step gradient-norm fetch only happens then."""
    rec = _recorder
    return rec is not None and rec.watch_trainer
