"""Zero-dependency, thread-safe metric instruments + registry.

The framework-wide observability core (docs/OBSERVABILITY.md): named
Counter/Gauge/Histogram instruments live in a process-global Registry and
are cheap enough for hot paths — one lock acquire and a few float ops per
record (~1 µs), against multi-millisecond compiled dispatches. Pure
stdlib: importing this module never touches jax, so `import
mxnet_tpu.telemetry` is safe in processes that must not initialize a
backend (tier-1 guarantee, tests/test_telemetry.py).

Design notes:

  * Histograms are fixed-boundary with exponential buckets (default
    100 µs · 2^i — latency-shaped), so recording is O(log n_buckets) and
    memory is constant regardless of sample count; percentiles are
    estimated by linear interpolation inside the covering bucket
    (the prometheus histogram_quantile estimator), exact to one bucket's
    resolution.
  * Labels follow the prometheus child model: an instrument declared
    with `labelnames` is a parent; `.labels(v)` interns a child per
    label-value tuple. Serving uses this for per-engine children so
    `ServingEngine.stats` stays engine-local while the registry view
    aggregates.
  * `Registry.reset()` zeroes values IN PLACE (children keep their
    identity) — call sites may hold child references across a reset.
"""
from __future__ import annotations

import json
import math
import threading
import time

from ..base import MXNetError

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "exponential_buckets", "DEFAULT_LATENCY_BUCKETS"]


def exponential_buckets(start, factor, count):
    """`count` ascending upper bounds: start, start·factor, …"""
    if start <= 0 or factor <= 1 or count < 1:
        raise MXNetError("exponential_buckets needs start>0, factor>1, "
                         "count>=1")
    return tuple(start * factor ** i for i in range(count))


# 100 µs .. ~105 s in ×2 steps — covers admission waits through drains
DEFAULT_LATENCY_BUCKETS = exponential_buckets(1e-4, 2.0, 21)


class _Instrument:
    """Base: name/help/labels bookkeeping shared by all three kinds."""

    kind = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}        # label-value tuple -> child instrument

    # -- labels ------------------------------------------------------------
    def labels(self, *values, **kw):
        """Child instrument for one label-value combination (interned)."""
        if not self.labelnames:
            raise MXNetError(f"instrument {self.name!r} declared no "
                             "labelnames")
        if kw:
            if values or set(kw) != set(self.labelnames):
                raise MXNetError(f"labels() for {self.name!r} needs exactly "
                                 f"{self.labelnames}")
            values = tuple(str(kw[k]) for k in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise MXNetError(f"{self.name!r} takes {len(self.labelnames)} "
                             f"label values, got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
            return child

    def _make_child(self):
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        with self._lock:
            children = list(self._children.values())
            self._reset_self()
        for c in children:
            c.reset()

    def _reset_self(self):
        raise NotImplementedError

    # -- snapshots ---------------------------------------------------------
    def snapshot(self):
        """JSON-able dict: own value and/or per-child values."""
        out = {"type": self.kind}
        if self.help:
            out["help"] = self.help
        if self.labelnames:
            out["labelnames"] = list(self.labelnames)
            with self._lock:
                items = list(self._children.items())
            out["children"] = [
                dict(zip(self.labelnames, vals), **child._value_snapshot())
                for vals, child in items]
        else:
            out.update(self._value_snapshot())
        return out

    def _value_snapshot(self):
        raise NotImplementedError

    def _samples(self):
        """[(label_values, child)] for exposition — self when unlabeled."""
        if self.labelnames:
            with self._lock:
                return list(self._children.items())
        return [((), self)]


class Counter(_Instrument):
    """Monotonic count. `inc()` only accepts non-negative deltas."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def _make_child(self):
        return Counter(self.name, self.help)

    def inc(self, amount=1):
        if amount < 0:
            raise MXNetError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset_self(self):
        self._value = 0.0

    def _value_snapshot(self):
        return {"value": self.value}


class Gauge(_Instrument):
    """Point-in-time value; optionally backed by a callback evaluated at
    read time (`set_function`) — used for device-memory sampling."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0
        self._fn = None

    def _make_child(self):
        return Gauge(self.name, self.help)

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        self.inc(-amount)

    def set_function(self, fn):
        """Evaluate fn() at every read — keeps sampling cost out of hot
        paths and inside snapshot()/render time."""
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        return float(fn())

    def _reset_self(self):
        self._value = 0.0

    def _value_snapshot(self):
        return {"value": self.value}


class Histogram(_Instrument):
    """Fixed-boundary histogram with an implicit +Inf overflow bucket.

    Records count/sum/min/max plus per-bucket counts; `observe(v, n)`
    folds n identical observations in one lock acquire (the serving
    engine uses this to attribute one decode dispatch's wall time to
    every token it emitted)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))
        if not self.buckets:
            raise MXNetError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def _make_child(self):
        return Histogram(self.name, self.help, buckets=self.buckets)

    def _bucket_index(self, v):
        lo, hi = 0, len(self.buckets)
        while lo < hi:                    # first bound >= v
            mid = (lo + hi) // 2
            if v <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, value, count=1):
        if count < 1:
            return
        value = float(value)
        i = self._bucket_index(value)
        with self._lock:
            self._counts[i] += count
            self._sum += value * count
            self._count += count
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    # -- derived stats -----------------------------------------------------
    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def percentile(self, q):
        """Estimate the q-th percentile (0..100) by linear interpolation
        inside the covering bucket (histogram_quantile estimator). The
        result is exact to one bucket's width; min/max clamp the open
        first/last buckets.

        An EMPTY histogram returns `float("nan")` — the defined "no
        data" value (docs/OBSERVABILITY.md "Percentiles"): NaN
        propagates visibly through arithmetic instead of forging a
        plausible 0.0 latency, and `math.isnan` is the idiomatic probe.
        Snapshots and dashboards must therefore guard on `count` before
        formatting. q outside [0, 100] raises."""
        if not 0 <= q <= 100:
            raise MXNetError(
                f"percentile q must be in [0, 100], got {q!r}")
        with self._lock:
            counts = list(self._counts)
            total, mn, mx = self._count, self._min, self._max
        if total == 0:
            return math.nan
        target = q / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else min(mn, self.buckets[0])
                hi = self.buckets[i] if i < len(self.buckets) else mx
                lo, hi = max(lo, mn), min(hi, mx)
                if hi <= lo:
                    return lo
                frac = (target - cum) / c
                return lo + frac * (hi - lo)
            cum += c
        return mx

    # -- merging -----------------------------------------------------------
    def merge(self, other):
        """Fold `other`'s observations into self, BUCKET-WISE: per-bucket
        counts add, sum/count add, min/max widen. This is the only
        correct way to combine histograms from different processes —
        averaging per-process percentiles is wrong the moment the
        processes saw different loads (docs/OBSERVABILITY.md "Fleet
        observability"; tests/test_telemetry.py proves it against a
        numpy oracle). Requires identical bucket boundaries."""
        if not isinstance(other, Histogram):
            raise MXNetError(f"cannot merge {type(other).__name__} into "
                             f"histogram {self.name!r}")
        if other.buckets != self.buckets:
            raise MXNetError(
                f"histogram merge for {self.name!r} needs identical "
                f"buckets: {len(self.buckets)} bounds vs "
                f"{len(other.buckets)}")
        with other._lock:
            counts = list(other._counts)
            o_sum, o_count = other._sum, other._count
            o_min, o_max = other._min, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += o_sum
            self._count += o_count
            if o_min < self._min:
                self._min = o_min
            if o_max > self._max:
                self._max = o_max
        return self

    @classmethod
    def from_cumulative(cls, bounds, cumulative, sum, count,
                        name="", help=""):
        """Reconstruct a Histogram from Prometheus exposition samples:
        `bounds` are the finite `le` bucket bounds (ascending, no +Inf)
        and `cumulative` the matching cumulative counts PLUS the final
        +Inf count (len(bounds) + 1 entries). min/max are synthesized
        from the outermost non-empty buckets — the exposition format
        does not carry them — so `percentile()` stays exact to one
        bucket's resolution on the reconstruction."""
        bounds = tuple(float(b) for b in bounds)
        if len(cumulative) != len(bounds) + 1:
            raise MXNetError(
                f"from_cumulative for {name!r}: {len(bounds)} bounds "
                f"need {len(bounds) + 1} cumulative counts, got "
                f"{len(cumulative)}")
        h = cls(name, help, buckets=bounds)
        prev = 0
        for i, cum in enumerate(cumulative):
            c = int(cum) - prev
            if c < 0:
                raise MXNetError(
                    f"from_cumulative for {name!r}: cumulative counts "
                    "must be non-decreasing")
            h._counts[i] = c
            prev = int(cum)
        h._count = int(count)
        h._sum = float(sum)
        if h._count:
            nonzero = [i for i, c in enumerate(h._counts) if c]
            lo_i, hi_i = nonzero[0], nonzero[-1]
            h._min = bounds[lo_i - 1] if lo_i > 0 else min(0.0, bounds[0])
            h._max = bounds[hi_i] if hi_i < len(bounds) else bounds[-1]
        return h

    def _reset_self(self):
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def _value_snapshot(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
            mn, mx = self._min, self._max
        out = {"count": total, "sum": s,
               "buckets": {("%g" % b): c
                           for b, c in zip(self.buckets, counts)},
               "overflow": counts[-1]}
        if total:
            out.update(min=mn, max=mx, avg=s / total,
                       p50=self.percentile(50), p90=self.percentile(90),
                       p99=self.percentile(99))
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Name → instrument map with get-or-create semantics.

    Re-declaring a name returns the existing instrument; a kind or
    labelnames mismatch raises (two subsystems silently sharing one
    name with different meanings is the bug this catches)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments = {}
        self._collect_hooks = []

    # -- declaration -------------------------------------------------------
    def _declare(self, kind, name, help="", labelnames=(), **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if inst.kind != kind or \
                        inst.labelnames != tuple(labelnames):
                    raise MXNetError(
                        f"instrument {name!r} already registered as "
                        f"{inst.kind}{inst.labelnames} — cannot redeclare "
                        f"as {kind}{tuple(labelnames)}")
                return inst
            inst = _KINDS[kind](name, help, labelnames=labelnames, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name, help="", labelnames=()):
        return self._declare("counter", name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._declare("gauge", name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._declare("histogram", name, help, labelnames,
                             buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._instruments.get(name)

    def add_collect_hook(self, fn):
        """fn() runs before every snapshot/render — opt-in samplers
        (device memory) hang here so hot paths never pay for them."""
        with self._lock:
            if fn not in self._collect_hooks:
                self._collect_hooks.append(fn)

    def _collect(self):
        with self._lock:
            hooks = list(self._collect_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass               # a broken sampler must not break reads

    # -- views -------------------------------------------------------------
    def snapshot(self):
        """{name: instrument snapshot} for every registered instrument."""
        self._collect()
        with self._lock:
            items = sorted(self._instruments.items())
        return {name: inst.snapshot() for name, inst in items}

    def render_prometheus(self):
        """Prometheus text exposition format (0.0.4)."""
        self._collect()
        with self._lock:
            items = sorted(self._instruments.items())
        lines = []
        for name, inst in items:
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} {inst.kind}")
            for values, child in inst._samples():
                lab = ",".join(f'{k}="{v}"'
                               for k, v in zip(inst.labelnames, values))
                if inst.kind == "histogram":
                    with child._lock:
                        counts = list(child._counts)
                        total, s = child._count, child._sum
                    cum = 0
                    for b, c in zip(child.buckets + (math.inf,), counts):
                        cum += c
                        le = "+Inf" if b == math.inf else "%g" % b
                        sep = "," if lab else ""
                        lines.append(f'{name}_bucket{{{lab}{sep}le="{le}"}}'
                                     f" {cum}")
                    suffix = f"{{{lab}}}" if lab else ""
                    lines.append(f"{name}_sum{suffix} {s:g}")
                    lines.append(f"{name}_count{suffix} {total}")
                else:
                    suffix = f"{{{lab}}}" if lab else ""
                    lines.append(f"{name}{suffix} {child.value:g}")
        return "\n".join(lines) + "\n"

    def dump(self, path):
        """Write the snapshot as JSON; returns the path."""
        snap = {"ts": time.time(), "instruments": self.snapshot()}
        with open(path, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
        return path

    def reset(self):
        """Zero every instrument in place (tests; between bench rounds).
        Instrument and child identities survive — holders of references
        (e.g. a live ServingEngine) keep recording into the same
        objects."""
        with self._lock:
            insts = list(self._instruments.values())
        for inst in insts:
            inst.reset()
