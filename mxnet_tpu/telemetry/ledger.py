"""HBM ledger: per-subsystem device-byte accounting, reconciled
against the live-array watermarks.

`telemetry/memory.py` answers *how much* HBM is in use; this module
answers *where it went* (docs/OBSERVABILITY.md "HBM ledger"). Each
subsystem registers a **provider** — a callable returning
``{category: value}`` where a value is:

  * an array (anything with ``.nbytes``, or an NDArray wrapping one) or
    an iterable of arrays — counted toward the accounted total with
    **identity dedup** across every provider and category, so two
    engines sharing one set of weights, or a category overlapping
    another, never double-count;
  * an ``int`` — raw bytes, counted as-is (no dedup possible);
  * a ``Detail(int)`` — an *informational* figure published as a gauge
    but excluded from the accounted total (e.g. the prefix-cache-held
    subset of the KV page slab, which is already counted inside
    ``kv_pages``).

``snapshot()`` walks the providers, reconciles the accounted total
against ``jax.live_arrays()`` (the same source as
``memory_live_array_bytes``) and the PjRt allocator limit where the
backend reports one (env override ``MXNET_TPU_HBM_BYTES``), and
publishes:

    ledger_bytes{component="engine/0/kv_pages"}   per category
    ledger_accounted_bytes                        Σ deduped categories
    ledger_unattributed_bytes                     live − accounted
    ledger_headroom_bytes                         limit − live (when a
                                                  limit is known)

The serving engine derives its *admission capacity estimate* (max
concurrent slots at the current page budget) from the same page
accounting — that gauge lives with the engine
(``serving_admission_capacity{engine}``).

Registered call sites: ``ServingEngine`` (weights, KV page slab,
device-resident slot state, prefix-cache detail), ``gluon.Trainer``
(optimizer state), ``parallel.TrainStep`` (params, optimizer state,
pipeline residuals). Providers are weakly held (bound methods) — a
collected owner drops out silently, like /statusz providers.

Stdlib-only at import; jax is touched only inside ``snapshot()`` and
only when the process already initialized it.
"""
from __future__ import annotations

import os
import sys
import threading
import weakref

__all__ = ["Detail", "register", "unregister", "providers", "snapshot",
           "install"]

_lock = threading.Lock()
_providers = {}            # name -> () -> provider callable (weak-aware)


class Detail(int):
    """Informational byte figure: published as a gauge, excluded from
    the accounted total (use for categories that overlap another)."""


def register(name, fn):
    """Publish `fn() -> {category: arrays | int | Detail}` under `name`.
    Bound methods are held via WeakMethod — a dead owner drops the
    provider instead of leaking it."""
    if hasattr(fn, "__self__"):
        ref = weakref.WeakMethod(fn)
        get = lambda r=ref: r()                          # noqa: E731
    else:
        get = lambda f=fn: f                             # noqa: E731
    with _lock:
        _providers[str(name)] = get


def unregister(name):
    with _lock:
        _providers.pop(str(name), None)


def providers():
    with _lock:
        return sorted(_providers)


def _gauges(registry):
    g = registry.gauge
    return {
        "bytes": g("ledger_bytes",
                   "HBM ledger: accounted device bytes per component "
                   "(component = provider/category)",
                   labelnames=("component",)),
        "accounted": g("ledger_accounted_bytes",
                       "HBM ledger: total bytes accounted to a "
                       "subsystem (identity-deduped)"),
        "unattributed": g("ledger_unattributed_bytes",
                          "live jax.Array bytes not claimed by any "
                          "ledger provider (live - accounted)"),
        "headroom": g("ledger_headroom_bytes",
                      "device capacity minus live bytes (0 when no "
                      "capacity limit is known)"),
    }


def _arrays_of(value):
    """Flatten a provider value into raw arrays; returns None when the
    value is a plain byte count instead."""
    if isinstance(value, int) and not isinstance(value, bool):
        return None
    if hasattr(value, "nbytes") or hasattr(value, "_data"):
        value = [value]
    out = []
    for a in value:
        a = getattr(a, "_data", a)         # NDArray -> jnp array
        if a is not None and hasattr(a, "nbytes"):
            out.append(a)
    return out


def snapshot(registry=None):
    """One reconciliation pass: walk the providers, dedupe, compare
    with the live-array total and the allocator limit, update the
    ledger gauges, and return the full /memz dict."""
    from . import default_registry
    gs = _gauges(registry or default_registry)
    with _lock:
        items = sorted(_providers.items())
    components = {}
    seen = set()               # id() of every counted array
    accounted = 0
    dead = []
    for name, get in items:
        fn = get()
        if fn is None:
            dead.append(name)
            continue
        try:
            cats = fn() or {}
        except Exception as e:
            components[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        comp = {}
        for cat, value in cats.items():
            if isinstance(value, Detail):
                comp[str(cat)] = {"bytes": int(value), "detail": True}
                gs["bytes"].labels(f"{name}/{cat}").set(int(value))
                continue
            arrays = _arrays_of(value)
            if arrays is None:             # raw int bytes
                n = int(value)
            else:
                n = 0
                for a in arrays:
                    if id(a) in seen:
                        continue
                    seen.add(id(a))
                    n += int(a.nbytes)
            comp[str(cat)] = {"bytes": n}
            accounted += n
            gs["bytes"].labels(f"{name}/{cat}").set(n)
        components[name] = comp
    if dead:
        with _lock:
            for name in dead:
                _providers.pop(name, None)

    out = {"components": components, "accounted_bytes": accounted}
    live = None
    limit = float(os.environ.get("MXNET_TPU_HBM_BYTES", 0) or 0) or None
    in_use = None
    if "jax" in sys.modules:       # never the thing that boots a backend
        try:
            from . import memory
            mem = memory.sample(registry)
            live = mem.get("live_array_bytes")
            # the first device's allocator view, where reported
            for k, v in mem.items():
                if k.startswith("bytes_limit") and limit is None:
                    limit = float(v)
                if k.startswith("bytes_in_use") and in_use is None:
                    in_use = float(v)
        except Exception as e:
            out["memory_error"] = str(e)
    out["live_array_bytes"] = live
    if live is not None:
        out["unattributed_bytes"] = int(live - accounted)
        gs["unattributed"].set(live - accounted)
        if accounted:
            out["unattributed_fraction"] = round(
                (live - accounted) / max(live, 1), 6)
    out["capacity_bytes"] = limit
    used = in_use if in_use is not None else live
    if limit is not None and used is not None:
        out["headroom_bytes"] = int(limit - used)
        gs["headroom"].set(limit - used)
    gs["accounted"].set(accounted)
    return out


def install(registry=None):
    """Reconcile on every snapshot/render of the registry (opt-in, like
    memory.install — a ledger walk is O(live arrays))."""
    from . import default_registry
    reg = registry or default_registry
    reg.add_collect_hook(lambda: snapshot(reg))
