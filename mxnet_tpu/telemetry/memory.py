"""Device-memory watermark sampling.

Two complementary sources, both read on demand (a collect hook, never a
hot path):

  * `device.memory_stats()` — the PjRt allocator's own counters
    (bytes_in_use, peak_bytes_in_use, bytes_limit) where the backend
    reports them (TPU does; XLA:CPU usually returns {}).
  * `jax.live_arrays()` — the framework-side view: every live
    jax.Array's committed bytes. Works on every backend, catches leaks
    the allocator hides (e.g. host-side buffer pileups), and its
    process-lifetime maximum is tracked as the
    `memory_live_array_bytes_peak` watermark.

`install()` registers sampling as a registry collect hook so every
snapshot()/render_prometheus() carries fresh values; `sample()` takes
one reading immediately and returns it.
"""
from __future__ import annotations

__all__ = ["sample", "install"]

_installed = False
_live_peak = 0.0


def _gauges(registry):
    g = registry.gauge
    return {
        "in_use": g("memory_device_bytes_in_use",
                    "PjRt allocator bytes in use", labelnames=("device",)),
        "peak": g("memory_device_peak_bytes",
                  "PjRt allocator peak bytes in use",
                  labelnames=("device",)),
        "limit": g("memory_device_bytes_limit",
                   "PjRt allocator capacity", labelnames=("device",)),
        "live_bytes": g("memory_live_array_bytes",
                        "total bytes of live jax.Arrays"),
        "live_count": g("memory_live_array_count",
                        "number of live jax.Arrays"),
        "live_peak": g("memory_live_array_bytes_peak",
                       "process-lifetime max of live jax.Array bytes"),
    }


def sample(registry=None):
    """One reading: update the memory gauges, return them as a dict."""
    global _live_peak
    import jax

    from . import default_registry
    gs = _gauges(registry or default_registry)
    out = {}
    for dev in jax.devices():
        stats = dict(getattr(dev, "memory_stats", lambda: None)() or {})
        if not stats:
            continue
        label = str(dev.id)
        for key, stat in (("in_use", "bytes_in_use"),
                          ("peak", "peak_bytes_in_use"),
                          ("limit", "bytes_limit")):
            if stat in stats:
                gs[key].labels(label).set(stats[stat])
                out[f"{stat}[{label}]"] = stats[stat]
    n_bytes = 0
    n = 0
    for arr in jax.live_arrays():
        n += 1
        try:
            n_bytes += arr.nbytes
        except Exception:
            pass                    # deleted/donated buffers race the walk
    _live_peak = max(_live_peak, float(n_bytes))
    gs["live_bytes"].set(n_bytes)
    gs["live_count"].set(n)
    gs["live_peak"].set(_live_peak)
    out.update(live_array_bytes=n_bytes, live_array_count=n,
               live_array_bytes_peak=_live_peak)
    return out


def install(registry=None):
    """Sample on every snapshot/render of the registry (idempotent)."""
    global _installed
    from . import default_registry
    reg = registry or default_registry
    reg.add_collect_hook(lambda: sample(reg))
    _installed = True
