"""Per-request lifecycle tracing with Chrome/Perfetto export.

Every serving request gets a structured event timeline — enqueued →
admitted → prefix_match → prefill → each decode/verify dispatch (with
emitted/drafted/accepted counts) → finished/cancelled/rejected —
recorded by the engine into a bounded in-memory ring
(`telemetry.request_log`, docs/OBSERVABILITY.md "Request timelines").
Recording is a dict append under one lock (~1 µs) against
multi-millisecond compiled dispatches, so it stays on by default; the
live server's `/requests` endpoint serves the ring as JSON and
`/trace` (or `chrome_trace()` here) exports it as Chrome `trace_event`
JSON that loads directly in ui.perfetto.dev or chrome://tracing.

Timestamps come from one process-wide clock: `perf_counter` offsets
re-anchored to the wall clock captured at import. That keeps every
`ts` **monotonic** (perf_counter never steps backwards the way
`time.time` can under NTP) while still reading as wall time, which is
what makes the exported `ts`/`dur` pairs internally consistent — a
child dispatch slice always nests inside its request's lifetime slice.

Distributed context (docs/OBSERVABILITY.md "Trace propagation"): every
trace carries a W3C trace-context id. The HTTP edge parses an incoming
`traceparent` header (or mints a fresh id) and the id rides the
Request through router placement, hedged clones, and
export/adopt migration — `begin(trace_id=..., t_begin=...,
phases=...)` re-opens a migrated request's timeline as a CONTINUATION
(same trace id, preserved start, accumulated phase budget) instead of
an orphan restart.

TTFT phase budget (docs/OBSERVABILITY.md "Phase taxonomy"): `phase()`
records one of the declared `PHASES` with a measured duration;
per-trace accumulation makes a request's time-to-first-token decompose
into queue_wait + prefix_match + host_pagein + prefill_chunks +
first_decode (+ handoff when a disaggregated fleet ships the finished
prefill to a decode worker). Phase names are CLOSED — an undeclared
name raises here and graftlint's `phases` pass flags the literal
statically.

Zero dependencies: stdlib only, like the rest of `mx.telemetry`.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

__all__ = ["RequestTrace", "RequestTraceLog", "request_log",
           "chrome_trace", "now", "PHASES", "new_trace_id",
           "new_span_id", "parse_traceparent", "format_traceparent"]

# one monotonic wall clock for every lifecycle/span timestamp
_EPOCH = time.time() - time.perf_counter()


def now():
    """Monotonic unix-epoch seconds (perf_counter re-anchored once)."""
    return _EPOCH + time.perf_counter()


#: The closed set of TTFT phase names. A request's time-to-first-token
#: decomposes into exactly these (docs/OBSERVABILITY.md "Phase
#: taxonomy"); `RequestTraceLog.phase()` rejects anything else and the
#: graftlint `phases` pass checks recorded literals statically.
#: `handoff` is cross-process only: the export->scatter gap when a
#: finished prefill ships its KV pages to a decode worker
#: (serving/fleet, docs/SERVING.md "Disaggregated prefill/decode").
PHASES = ("queue_wait", "prefix_match", "host_pagein",
          "prefill_chunks", "first_decode", "handoff")

# -- W3C trace-context (traceparent) helpers ----------------------------------
# Header shape: "00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>".
# This is the wire contract the HTTP edge speaks and the cross-process
# split (ROADMAP item 1) will reuse verbatim.


def new_trace_id():
    """Fresh 32-hex-char W3C trace id (never all zeros)."""
    while True:
        t = os.urandom(16).hex()
        if t != "0" * 32:
            return t


def new_span_id():
    """Fresh 16-hex-char W3C span id (never all zeros)."""
    while True:
        s = os.urandom(8).hex()
        if s != "0" * 16:
            return s


def _is_hex(s):
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


def parse_traceparent(header):
    """(trace_id, span_id) from a `traceparent` header value, or None
    when the header is absent/malformed (per spec, an invalid header is
    IGNORED — the edge then starts a fresh trace, never 400s)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) \
            or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) \
            or span_id == "0" * 16:
        return None
    if len(parts[3]) != 2 or not _is_hex(parts[3]):
        return None
    return trace_id, span_id


def format_traceparent(trace_id, span_id=None, sampled=True):
    """Render a `traceparent` header value for `trace_id` (a fresh
    span id is minted when none is given)."""
    flags = "01" if sampled else "00"
    return f"00-{trace_id}-{span_id or new_span_id()}-{flags}"


class RequestTrace:
    """One request's event timeline. Events are dicts with at least
    {"event", "ts"}; dispatch events carry "dur" (seconds) and counts.
    `status` is None while live, then finished/cancelled/rejected.

    `trace_id` is the W3C id correlating this timeline across hops
    (minted here when the caller has none). `phases` accumulates the
    TTFT phase budget (phase name -> total seconds); a migrated
    request's continuation is seeded with both so the stitched trace
    reads as ONE request, not two."""

    __slots__ = ("request_id", "engine", "t_begin", "t_end", "status",
                 "events", "attrs", "trace_id", "phases")

    def __init__(self, request_id, engine="", trace_id=None,
                 t_begin=None, phases=None, **attrs):
        self.request_id = request_id
        self.engine = str(engine)
        self.trace_id = trace_id or new_trace_id()
        self.t_begin = now()
        self.t_end = None
        self.status = None
        self.attrs = attrs
        self.phases = dict(phases) if phases else {}
        self.events = [{"event": "enqueued", "ts": self.t_begin}]
        if t_begin is not None:
            # continuation of a migrated/re-homed timeline: keep the
            # ORIGINAL start so queue->finish reads as one lifetime
            self.t_begin = float(t_begin)
            self.events[0]["ts"] = self.t_begin
            self.events[0]["resumed_at"] = now()

    def to_dict(self):
        out = {"request_id": self.request_id, "engine": self.engine,
               "trace_id": self.trace_id,
               "t_begin": self.t_begin, "t_end": self.t_end,
               "status": self.status, "phases": dict(self.phases),
               "events": list(self.events)}
        if self.attrs:
            out.update(self.attrs)
        return out


class RequestTraceLog:
    """Bounded ring of request timelines (live + most recent finished).

    The engine drives it: begin() at submit, event() per lifecycle
    step, end() at the terminal event. Keys are (engine, request_id) so
    multiple engines (and a request id reused across engines) never
    collide. Thread-safe; disabled() turns every call into a no-op for
    A/B overhead runs."""

    def __init__(self, capacity=512):
        self._lock = threading.Lock()
        self._live = {}                       # (engine, id) -> trace
        self._done = deque(maxlen=int(capacity))
        self._hooks = []
        self.enabled = True

    # -- recording ---------------------------------------------------------
    def begin(self, request_id, engine="", trace_id=None, t_begin=None,
              phases=None, **attrs):
        """Open a timeline. `trace_id`/`t_begin`/`phases` stitch a
        migrated request's continuation onto its original trace
        (export_requests packs them, adopt passes them back)."""
        if not self.enabled:
            return None
        tr = RequestTrace(request_id, engine, trace_id=trace_id,
                          t_begin=t_begin, phases=phases, **attrs)
        with self._lock:
            self._live[(tr.engine, request_id)] = tr
        self._fire(tr, tr.events[0])
        return tr

    def phase(self, request_id, engine="", phase="", dur=0.0, **attrs):
        """Record one TTFT phase span (name MUST be in `PHASES` —
        a typo'd phase would otherwise vanish silently into the ring)
        and accumulate it into the trace's phase budget."""
        if phase not in PHASES:
            raise ValueError(f"unknown phase {phase!r} (declared: "
                             f"{', '.join(PHASES)})")
        if not self.enabled:
            return None
        dur = max(float(dur), 0.0)
        ev = dict(event="phase", phase=phase, ts=now(), dur=dur, **attrs)
        with self._lock:
            tr = self._live.get((str(engine), request_id))
            if tr is None:
                return None
            tr.phases[phase] = tr.phases.get(phase, 0.0) + dur
            tr.events.append(ev)
        self._fire(tr, ev)
        return ev

    def live_trace(self, request_id, engine=""):
        """The live RequestTrace for (engine, request_id), or None —
        export_requests reads trace_id/t_begin/phases off it to pack
        the stitch context onto the migrating Request."""
        with self._lock:
            return self._live.get((str(engine), request_id))

    def event(self, request_id, engine="", event="", **attrs):
        if not self.enabled:
            return None
        ev = dict(event=event, ts=now(), **attrs)
        with self._lock:
            tr = self._live.get((str(engine), request_id))
            if tr is None:
                return None
            tr.events.append(ev)
        self._fire(tr, ev)
        return ev

    def end(self, request_id, engine="", status="finished", **attrs):
        """Terminal event: stamps `status`, moves the trace to the done
        ring. Unknown ids are ignored (e.g. trace ring cleared while
        the request was in flight)."""
        if not self.enabled:
            return None
        ev = dict(event=status, ts=now(), **attrs)
        with self._lock:
            tr = self._live.pop((str(engine), request_id), None)
            if tr is None:
                return None
            tr.events.append(ev)
            tr.status = status
            tr.t_end = ev["ts"]
            self._done.append(tr)
        self._fire(tr, ev)
        return tr

    def terminal(self, request_id, engine="", status="rejected", **attrs):
        """One-shot trace for a request that never got a timeline —
        e.g. a queue-full rejection: begin + terminal event in one call,
        so `/requests` shows rejected traffic, not just admitted."""
        if not self.enabled:
            return None
        tr = RequestTrace(request_id, engine, **attrs)
        tr.events.append(dict(event=status, ts=now()))
        tr.status = status
        tr.t_end = tr.events[-1]["ts"]
        with self._lock:
            self._done.append(tr)
        self._fire(tr, tr.events[-1])
        return tr

    # -- hooks (the flight recorder subscribes here) -----------------------
    def add_hook(self, fn):
        """fn(trace, event_dict) on every recorded event (exceptions
        swallowed — an observer must never break serving)."""
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    def remove_hook(self, fn):
        with self._lock:
            if fn in self._hooks:
                self._hooks.remove(fn)

    def _fire(self, tr, ev):
        with self._lock:
            hooks = list(self._hooks)
        for fn in hooks:
            try:
                fn(tr, ev)
            except Exception:
                pass

    # -- views -------------------------------------------------------------
    def recent(self, n=50, include_live=True):
        """Most recent timelines as dicts, oldest first; live traces
        (no terminal event yet) ride at the end."""
        with self._lock:
            done = list(self._done)[-int(n):]
            live = sorted(self._live.values(),
                          key=lambda t: t.t_begin) if include_live else []
        return [t.to_dict() for t in done + live]

    @property
    def num_live(self):
        with self._lock:
            return len(self._live)

    def clear(self):
        with self._lock:
            self._live.clear()
            self._done.clear()


#: The process-global log every ServingEngine records into.
request_log = RequestTraceLog()

# stable perfetto track ids: request id -> tid, interned FIFO
_tids = {}
_tid_counter = itertools.count(1)
_tid_lock = threading.Lock()


def _tid(engine, request_id):
    key = (engine, request_id)
    with _tid_lock:
        t = _tids.get(key)
        if t is None:
            t = _tids[key] = next(_tid_counter)
            if len(_tids) > 4096:        # bound the intern table
                _tids.pop(next(iter(_tids)))
        return t


def _us(t):
    return t * 1e6


def chrome_trace(last_ms=None, requests=None, spans=None, max_requests=512):
    """Export request timelines + telemetry spans as a Chrome
    `trace_event` JSON object (the dict; json.dump it yourself or hit
    the live server's `/trace`). Loads directly in ui.perfetto.dev.

    Layout: one perfetto *process* per engine (pid = engine ordinal +
    1), one *track* per request (its whole lifetime is an "X" slice;
    queued/prefill/decode/verify phases nest inside it; terminal
    status is an instant event). Host `telemetry.span` ranges ride in
    pid 0 ("host spans"), one track per OS thread. `last_ms` keeps
    only events ending in the trailing window.
    """
    if requests is None:
        requests = request_log.recent(max_requests)
    if spans is None:
        from .tracing import events as _span_events
        spans = _span_events()
    cutoff = None if last_ms is None else now() - last_ms / 1e3
    out = []
    procs = {}                 # pid -> process_name
    seen_tracks = set()        # (pid, tid) -> thread_name emitted

    def emit_meta(pid, tid, pname, tname):
        if pid not in procs:
            procs[pid] = pname
            out.append({"name": "process_name", "ph": "M", "pid": pid,
                        "tid": 0, "args": {"name": pname}})
        if (pid, tid) not in seen_tracks:
            seen_tracks.add((pid, tid))
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": tname}})
            out.append({"name": "thread_sort_index", "ph": "M",
                        "pid": pid, "tid": tid,
                        "args": {"sort_index": tid}})

    for tr in requests:
        t_end = tr["t_end"] if tr["t_end"] is not None else now()
        if cutoff is not None and t_end < cutoff:
            continue
        try:
            pid = int(tr["engine"]) + 1
        except (TypeError, ValueError):
            pid = 1
        tid = _tid(tr["engine"], tr["request_id"])
        emit_meta(pid, tid, f"engine {tr['engine']}",
                  f"req {tr['request_id']}")
        args = {k: v for k, v in tr.items() if k not in
                ("events", "t_begin", "t_end")}
        out.append({"name": "request", "cat": "request", "ph": "X",
                    "ts": _us(tr["t_begin"]),
                    "dur": max(_us(t_end - tr["t_begin"]), 0.0),
                    "pid": pid, "tid": tid, "args": args})
        prev_ts = tr["t_begin"]
        for ev in tr["events"]:
            if cutoff is not None and ev["ts"] < cutoff:
                # keep the window export O(window), not O(history):
                # a long-lived request's old dispatches stay out, its
                # lifetime slice still spans the track
                if "dur" not in ev:
                    prev_ts = ev["ts"]
                continue
            name = ev["event"]
            eargs = {k: v for k, v in ev.items()
                     if k not in ("event", "ts", "dur")}
            if name == "enqueued":
                continue           # its span is the queued→admitted gap
            if name == "admitted":
                out.append({"name": "queued", "cat": "queue", "ph": "X",
                            "ts": _us(tr["t_begin"]),
                            "dur": max(_us(ev["ts"] - tr["t_begin"]), 0.0),
                            "pid": pid, "tid": tid, "args": eargs})
            elif "dur" in ev:      # prefill / decode / verify / phase spans
                dur = max(float(ev["dur"]), 0.0)
                ts0 = max(ev["ts"] - dur, prev_ts)
                cat = "dispatch"
                if name == "phase":
                    # TTFT phase-budget span: named slice on the
                    # request track so the waterfall reads directly
                    name = ev.get("phase", "phase")
                    cat = "phase"
                    eargs.pop("phase", None)
                out.append({"name": name, "cat": cat, "ph": "X",
                            "ts": _us(ts0),
                            "dur": _us(min(dur, t_end - ts0)),
                            "pid": pid, "tid": tid, "args": eargs})
            else:                  # instants: prefix_match, terminal, …
                out.append({"name": name, "cat": "lifecycle", "ph": "i",
                            "ts": _us(min(ev["ts"], t_end)), "s": "t",
                            "pid": pid, "tid": tid, "args": eargs})
            prev_ts = ev["ts"] if "dur" not in ev else prev_ts
    for ev in spans:
        if cutoff is not None and ev["ts"] < cutoff:
            continue
        tid = ev.get("thread", 0) % 100000
        emit_meta(0, tid, "host spans", f"thread {tid}")
        dur = max(float(ev.get("dur", 0.0)), 0.0)
        args = {k: v for k, v in ev.items()
                if k not in ("name", "ts", "dur", "thread")}
        out.append({"name": ev["name"], "cat": "span", "ph": "X",
                    "ts": _us(ev["ts"] - dur), "dur": _us(dur),
                    "pid": 0, "tid": tid, "args": args})
    out.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                            e.get("ts", 0.0)))
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"exporter": "mx.telemetry.chrome_trace",
                          "clock": "perf_counter re-anchored to unix"}}
