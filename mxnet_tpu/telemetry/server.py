"""Live introspection HTTP server — stdlib-only, daemon-threaded.

`mx.telemetry.serve(port)` exposes a running process to curl, a
Prometheus scraper, and ui.perfetto.dev without adding a dependency or
a thread the process must manage (docs/OBSERVABILITY.md "Live
introspection server"):

    /            tiny HTML index of the endpoints
    /healthz     200 "ok" — liveness; "degraded: <reasons>" (still
                 200, flagged body) while the flight recorder holds a
                 latched dump OR a component flagged itself degraded
                 via set_degraded() (the serving engine does under
                 sustained overload)
    /readyz      readiness, distinct from liveness: components
                 register a probe (register_ready_probe) reporting
                 {warmed, degraded, draining}; a component is ready
                 when warmed AND not degraded AND not draining. 200
                 while at least one registered component is ready
                 (or none registered), 503 otherwise — so ONE
                 intentionally-draining replica never flips the whole
                 process not-ready. ?component=<name> scopes the
                 answer to one component (503 when it is not ready or
                 unknown). External LBs and the ServingRouter consume
                 this; /healthz stays pure liveness.
    /metrics     Prometheus text exposition (0.0.4) of the registry
    /statusz     JSON: process info (uptime, RSS, python/jax versions),
                 registered component status (engine config/occupancy/
                 hit-rates), jit-cache stats, device-memory watermarks
    /requests    recent request timelines as JSON (?n=50)
    /trace       Chrome trace_event JSON of timelines + spans
                 (?last_ms=N) — load the response in ui.perfetto.dev
    /compilez    JSON: per-program compile attribution + registered
                 cost_analysis + MFU/roofline placement (telemetry.cost)
    /memz        JSON: the HBM ledger reconciled against live-array
                 bytes (telemetry.ledger)
    /sloz        JSON: declared SLO objectives + multi-window burn
                 rates (fast/slow windows, Google-SRE style) and which
                 objectives are currently fast-burning (telemetry.slo)
    /fleetz      JSON: the fleet collector's view — per-worker health/
                 role/staleness, fleet tokens/sec and tokens/sec/chip,
                 the fleet-global SLO snapshot (404 until a
                 FleetCollector registers via
                 register_fleetz_provider)

Every read is a snapshot under the instrument locks, so concurrent
scrapes during serving never tear (tests/test_introspection.py soaks
this). Components publish into `/statusz` and flight-recorder dumps by
registering a status provider; the registry holds weak references, so
a garbage-collected engine silently drops out.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

__all__ = ["serve", "stop_server", "get_server", "IntrospectionServer",
           "HttpServerThread",
           "register_status_provider", "unregister_status_provider",
           "collect_status", "set_degraded", "clear_degraded",
           "degraded_reasons", "register_ready_probe",
           "unregister_ready_probe", "readiness", "component_ready",
           "healthz_body", "readyz_body",
           "register_fleetz_provider", "unregister_fleetz_provider",
           "fleetz_payload"]

_T0 = time.time()
_providers_lock = threading.Lock()
_providers = {}            # name -> weakref-able callable () -> dict
_server = None             # the default server started by serve()
_server_lock = threading.Lock()
_degraded_lock = threading.Lock()
_degraded = {}             # component name -> reason
_ready_lock = threading.Lock()
_ready_probes = {}         # name -> weakref-able callable () -> dict


def set_degraded(name, reason="overload"):
    """Flag a component as gracefully degraded: /healthz answers
    `degraded: <name>=<reason>` (still 200 — the process is alive and
    serving, just not at full service) and /statusz grows a
    `degraded` block. Cleared with clear_degraded(name)."""
    with _degraded_lock:
        _degraded[str(name)] = str(reason)


def clear_degraded(name):
    """Remove a component's degradation flag (no-op when absent)."""
    with _degraded_lock:
        _degraded.pop(str(name), None)


def degraded_reasons():
    """{component: reason} of currently degraded components."""
    with _degraded_lock:
        return dict(_degraded)


def _weakly(fn):
    """Hold `fn` via WeakMethod when it is a bound method, so a dead
    owner drops its registration instead of leaking it."""
    if hasattr(fn, "__self__"):
        ref = weakref.WeakMethod(fn)
        return lambda: ref()
    return lambda: fn


def register_ready_probe(name, fn):
    """Publish a readiness probe for one component under `name`:
    `fn() -> {"warmed": bool, "degraded": bool-or-reason,
    "draining": bool}`. The component is READY when warmed and not
    degraded and not draining — /readyz serves the per-component
    conjunctions. Bound methods are held weakly (see
    register_status_provider)."""
    with _ready_lock:
        _ready_probes[str(name)] = _weakly(fn)


def unregister_ready_probe(name):
    with _ready_lock:
        _ready_probes.pop(str(name), None)


def readiness():
    """{component: {"warmed", "degraded", "draining", "ready"}} for
    every registered probe. Dead weakrefs drop out; a probe that
    raises reports ready=False with the error (a broken component is
    not ready, but must not break the endpoint)."""
    with _ready_lock:
        items = list(_ready_probes.items())
    out = {}
    dead = []
    for name, get in items:
        fn = get()
        if fn is None:
            dead.append(name)
            continue
        try:
            st = dict(fn())
            st["ready"] = bool(st.get("warmed")
                               and not st.get("degraded")
                               and not st.get("draining"))
        except Exception as e:
            st = {"ready": False,
                  "error": f"{type(e).__name__}: {e}"}
        out[name] = st
    if dead:
        with _ready_lock:
            for name in dead:
                _ready_probes.pop(name, None)
    return out


def component_ready(name):
    """One component's readiness (None when no such probe)."""
    st = readiness().get(str(name))
    return None if st is None else st["ready"]


def healthz_body():
    """The /healthz text body — shared by every HTTP surface (the
    introspection server and serving/frontend.py): 'ok' when nothing
    is flagged, else the degraded components and latched flight
    reasons. Always 200 — this is liveness, not readiness."""
    from . import flight
    reasons = list(flight.latched_reasons())
    reasons.extend(f"{n}={r}" for n, r
                   in sorted(degraded_reasons().items()))
    return "ok\n" if not reasons else \
        "degraded: " + ",".join(reasons) + "\n"


def readyz_body(component=None):
    """The /readyz JSON body and status code — (dict, 200|503) —
    shared by every HTTP surface. `component` scopes the answer to one
    registered probe (503 when it is not ready or unknown)."""
    comps = readiness()
    if component is not None:
        st = comps.get(component)
        ready = bool(st and st["ready"])
        body = {"component": component, "ready": ready, "state": st}
    else:
        ready = (not comps) or any(c["ready"] for c in comps.values())
        body = {"ready": ready, "components": comps}
    return body, (200 if ready else 503)


_fleetz_lock = threading.Lock()
_fleetz_provider = None    # () -> weakref-able callable () -> dict


def register_fleetz_provider(fn):
    """Publish `fn() -> dict` as the /fleetz payload — the fleet
    collector registers its `fleetz` bound method here (held weakly,
    like status providers, so a dead collector drops out). One
    provider per process: the latest registration wins."""
    global _fleetz_provider
    with _fleetz_lock:
        _fleetz_provider = _weakly(fn)


def unregister_fleetz_provider(fn=None):
    """Drop the /fleetz provider. With `fn` given, only drop it when
    it is still the registered one (a newer collector's registration
    survives an older collector's close)."""
    global _fleetz_provider
    with _fleetz_lock:
        if fn is not None and _fleetz_provider is not None \
                and _fleetz_provider() not in (fn, None):
            return
        _fleetz_provider = None


def fleetz_payload():
    """The /fleetz body, or None when no collector is registered (or
    the registered one has been garbage-collected)."""
    global _fleetz_provider
    with _fleetz_lock:
        get = _fleetz_provider
    if get is None:
        return None
    fn = get()
    if fn is None:
        with _fleetz_lock:
            if _fleetz_provider is get:
                _fleetz_provider = None
        return None
    return fn()


def register_status_provider(name, fn):
    """Publish `fn() -> dict` under `name` in /statusz and in flight
    dumps. Bound methods are held via WeakMethod — a dead owner drops
    the provider instead of leaking it."""
    if hasattr(fn, "__self__"):
        fn = weakref.WeakMethod(fn)
        get = lambda ref=fn: ref()                       # noqa: E731
    else:
        get = lambda f=fn: f                             # noqa: E731
    with _providers_lock:
        _providers[str(name)] = get


def unregister_status_provider(name):
    with _providers_lock:
        _providers.pop(str(name), None)


def collect_status():
    """{provider name: its dict} — dead weakrefs dropped, provider
    exceptions surfaced as {"error": ...} so one broken component
    can't blank the whole page."""
    with _providers_lock:
        items = list(_providers.items())
    out = {}
    dead = []
    for name, get in items:
        fn = get()
        if fn is None:
            dead.append(name)
            continue
        try:
            out[name] = fn()
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    if dead:
        with _providers_lock:
            for name in dead:
                _providers.pop(name, None)
    return out


def _rss_bytes():
    """Current resident set size. /proc on linux; ru_maxrss (the PEAK,
    in KiB on linux) as the portable fallback; None when unknowable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except Exception:
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def _versions():
    """Interpreter + key-library versions — only libraries this process
    already imported (probing must never initialize a backend)."""
    out = {"python": sys.version.split()[0]}
    for mod in ("jax", "jaxlib", "numpy"):
        m = sys.modules.get(mod)
        if m is not None:
            out[mod] = getattr(m, "__version__", "unknown")
    return out


def _statusz():
    from . import default_registry, flight

    def _counter(name):
        inst = default_registry.get(name)
        return None if inst is None else inst.value

    status = {
        "time": time.time(),
        "uptime_seconds": round(time.time() - _T0, 3),
        "argv": list(sys.argv),
        "pid": os.getpid(),
        "rss_bytes": _rss_bytes(),
        "versions": _versions(),
        "python": sys.version.split()[0],
        "jax_imported": "jax" in sys.modules,
        "flight_latched": flight.latched_reasons(),
        "degraded": degraded_reasons(),
        "readiness": readiness(),
        "components": collect_status(),
        "jit_cache": {
            "retraces": _counter("jit_cache_retraces_total"),
            "evictions": _counter("jit_cache_evictions_total"),
        },
    }
    # device-memory watermarks: sample only when jax is already live —
    # /statusz must never be the thing that initializes a backend
    if "jax" in sys.modules:
        try:
            from . import memory
            status["memory"] = memory.sample()
        except Exception as e:
            status["memory"] = {"error": str(e)}
    return status


_INDEX = """<!doctype html><title>mx.telemetry</title>
<h1>mx.telemetry introspection</h1><ul>
<li><a href="/metrics">/metrics</a> — Prometheus text exposition</li>
<li><a href="/statusz">/statusz</a> — engine/process status JSON</li>
<li><a href="/requests">/requests</a> — recent request timelines</li>
<li><a href="/trace">/trace</a> — Chrome trace JSON
 (open in <a href="https://ui.perfetto.dev">ui.perfetto.dev</a>;
 ?last_ms=N for the trailing window)</li>
<li><a href="/compilez">/compilez</a> — per-program compile
 attribution + MFU/roofline</li>
<li><a href="/memz">/memz</a> — HBM ledger vs live-array bytes</li>
<li><a href="/sloz">/sloz</a> — SLO objectives + multi-window
 burn rates</li>
<li><a href="/fleetz">/fleetz</a> — fleet collector view: per-worker
 health/staleness, fleet tokens/sec(/chip), fleet SLO (404 until a
 collector registers)</li>
<li><a href="/healthz">/healthz</a> — liveness (degraded while a
 flight dump is latched)</li>
<li><a href="/readyz">/readyz</a> — readiness (warmed &and; not
 degraded &and; not draining, per component; ?component=name)</li>
</ul>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "mx-telemetry/1.0"

    def log_message(self, fmt, *args):
        pass                        # scrapes must not spam stderr

    def _reply(self, body, ctype="application/json", code=200):
        if isinstance(body, str):
            body = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):              # noqa: N802 (stdlib handler name)
        from . import render_prometheus, snapshot  # noqa: F401
        from .request_trace import chrome_trace, request_log

        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path in ("/", "/index.html"):
                self._reply(_INDEX, "text/html; charset=utf-8")
            elif url.path == "/healthz":
                self._reply(healthz_body(), "text/plain; charset=utf-8")
            elif url.path == "/readyz":
                body, code = readyz_body(q.get("component", [None])[0])
                self._reply(json.dumps(body, sort_keys=True), code=code)
            elif url.path == "/metrics":
                self._reply(render_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/statusz":
                self._reply(json.dumps(_statusz(), indent=1,
                                       sort_keys=True, default=str))
            elif url.path == "/requests":
                n = int(q.get("n", ["50"])[0])
                self._reply(json.dumps(
                    {"requests": request_log.recent(n)}, default=str))
            elif url.path == "/trace":
                last_ms = q.get("last_ms", [None])[0]
                tr = chrome_trace(
                    last_ms=float(last_ms) if last_ms else None)
                self._reply(json.dumps(tr))
            elif url.path == "/compilez":
                from . import cost
                self._reply(json.dumps(cost.report(), indent=1,
                                       sort_keys=True, default=str))
            elif url.path == "/memz":
                from . import ledger
                self._reply(json.dumps(ledger.snapshot(), indent=1,
                                       sort_keys=True, default=str))
            elif url.path == "/sloz":
                from . import slo
                self._reply(json.dumps(slo.snapshot(), indent=1,
                                       sort_keys=True, default=str))
            elif url.path == "/fleetz":
                body = fleetz_payload()
                if body is None:
                    self._reply(json.dumps(
                        {"error": "no fleet collector registered in "
                                  "this process",
                         "hint": "FleetRouter.observe() or "
                                 "FleetCollector.start() registers "
                                 "one"}), code=404)
                else:
                    self._reply(json.dumps(body, indent=1,
                                           sort_keys=True, default=str))
            else:
                self._reply(json.dumps({"error": "not found",
                                        "path": url.path}), code=404)
        except Exception as e:   # a broken read must answer, not hang
            self._reply(json.dumps(
                {"error": f"{type(e).__name__}: {e}"}), code=500)


class HttpServerThread:
    """A ThreadingHTTPServer on a daemon thread — the shared lifecycle
    for every HTTP surface in the package (this introspection server,
    serving/frontend.py's ingress). port=0 picks a free port (read it
    back from `.port`). `close()` is DETERMINISTIC and idempotent: it
    stops the accept loop, releases the listening port, and joins the
    server thread, so tests never leak listeners; `stop()` is an alias
    and the instance is a context manager. Handlers reach the owning
    wrapper through `self.server.owner` (set before the thread
    starts, so the first request can never race it)."""

    handler_class = None            # subclasses set the handler
    name_prefix = "mx-http"

    def __init__(self, port=0, host="127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          self.handler_class)
        self._httpd.daemon_threads = True
        self._httpd.owner = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"{self.name_prefix}:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def stop(self):
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __repr__(self):
        return f"{type(self).__name__}({self.url})"


class IntrospectionServer(HttpServerThread):
    """The telemetry surface on the shared HttpServerThread lifecycle
    (see the module docstring for the endpoints)."""

    handler_class = _Handler
    name_prefix = "mx-telemetry-http"


def serve(port=0, host="127.0.0.1"):
    """Start (or return) the process's introspection server. Idempotent
    per process: a second call returns the live server (a port mismatch
    raises — two registries' worth of servers is never what you want;
    construct IntrospectionServer directly for that)."""
    global _server
    with _server_lock:
        if _server is not None:
            if port not in (0, _server.port):
                from ..base import MXNetError
                raise MXNetError(
                    f"introspection server already on port {_server.port}; "
                    f"stop_server() first to move it to {port}")
            return _server
        _server = IntrospectionServer(port, host)
        return _server


def get_server():
    return _server


def stop_server():
    """Stop the default server (no-op when none is running)."""
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()
