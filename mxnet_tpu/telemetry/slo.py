"""mx.telemetry.slo — declarative SLOs on multi-window burn rates.

An `SLO` names an objective over the serving stream — a TTFT latency
bound (`ttft_p99_ms`: the target fraction of requests must see first
token under the bound) and/or a per-request decode goodput floor
(`goodput_min`, tokens/s) — optionally split `per` request dimension
(priority and/or tenant), so one declaration yields one burn-rate
series per label value.

Evaluation is the Google SRE workbook's multi-window multi-burn-rate
scheme: each observation is classified good/bad against the objective,
and the **burn rate** over a trailing window is

    burn = bad_fraction(window) / (1 - target)

i.e. the rate at which the error budget is being consumed (1.0 =
exactly sustainable; 14.4 over 1 minute ≈ "2% of a 30-day budget in an
hour" — page territory). Two windows are kept per series: a FAST one
(default 60 s) that reacts to incidents, and a SLOW one (default
600 s) that suppresses blips. `fast_burning` — fast burn over its
threshold — is the actionable signal: it latches a flight-recorder
dump (`slo_burn:<objective>`, once per objective until rearmed) and
`SheddingPolicy(slo=...)` counts it toward the overload level.

The process-global `slo_engine` is fed by every ServingEngine
(`observe_ttft` at first token, `observe_goodput` at finish) exactly
like `request_log`; with no objectives configured every observe is a
cheap no-op, which is the A/B-overhead baseline. `/sloz` on the live
server serves `snapshot()`.

Zero heavy dependencies: stdlib only, like the rest of `mx.telemetry`.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from . import flight as _flight

__all__ = ["SLO", "SLOEngine", "slo_engine", "configure",
           "observe_ttft", "observe_goodput", "snapshot",
           "fast_burning"]

_DIMS = ("priority", "tenant")     # the request dimensions `per` may name


class SLO:
    """One declarative objective.

    name: label the burn series / flight dumps / `/sloz` report use.
    ttft_p99_ms: first-token latency bound — an observed TTFT above it
        is a bad event. goodput_min: per-request decode goodput floor
        (tokens/s) — a finished request below it is a bad event. At
        least one must be set; both may be.
    target: the good fraction the objective promises (0.99 = 1% error
        budget). per: iterable of request dimensions ("priority",
        "tenant") to split the series by.
    fast_window_s / slow_window_s: the two trailing windows.
    fast_burn / slow_burn: burn-rate thresholds per window; the fast
        one is the paging/shedding/flight signal.
    min_events: observations a window needs before it is trusted —
        burn reads 0.0 below it (a single early failure must not page).
    """

    def __init__(self, name, ttft_p99_ms=None, goodput_min=None,
                 target=0.99, per=(), fast_window_s=60.0,
                 slow_window_s=600.0, fast_burn=14.0, slow_burn=2.0,
                 min_events=10):
        if ttft_p99_ms is None and goodput_min is None:
            raise ValueError("SLO needs ttft_p99_ms and/or goodput_min")
        if not 0.0 < float(target) < 1.0:
            raise ValueError("target must be in (0, 1)")
        per = tuple(per)
        for d in per:
            if d not in _DIMS:
                raise ValueError(f"unknown SLO dimension {d!r} "
                                 f"(allowed: {', '.join(_DIMS)})")
        self.name = str(name)
        self.ttft_p99_ms = None if ttft_p99_ms is None \
            else float(ttft_p99_ms)
        self.goodput_min = None if goodput_min is None \
            else float(goodput_min)
        self.target = float(target)
        self.per = per
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.min_events = int(min_events)

    def key_of(self, priority=None, tenant=None):
        """The series key for one observation's label values."""
        vals = {"priority": priority, "tenant": tenant}
        return tuple((d, str(vals[d])) for d in self.per)


class _Series:
    """One (objective, label-key) observation ring: (ts, good) pairs,
    bounded by the slow window at eviction time."""

    __slots__ = ("events", "good_total", "bad_total")

    def __init__(self):
        self.events = deque()
        self.good_total = 0
        self.bad_total = 0

    def add(self, ts, good):
        self.events.append((ts, bool(good)))
        if good:
            self.good_total += 1
        else:
            self.bad_total += 1

    def prune(self, horizon):
        ev = self.events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    def window(self, t_now, window_s):
        """(events, bad) inside the trailing window."""
        lo = t_now - window_s
        n = bad = 0
        for ts, good in reversed(self.events):
            if ts < lo:
                break
            n += 1
            if not good:
                bad += 1
        return n, bad


def _burn(n, bad, budget, min_events):
    if n < min_events:
        return 0.0
    return (bad / n) / budget


class SLOEngine:
    """Evaluates a set of `SLO` objectives over observed events.

    clock: injectable (engine-style) for tests; default perf_counter.
    The burn-rate math only ever sees THIS clock, so hand-driven
    clocks give exact window arithmetic.

    metrics: optional {"events": Counter, "burn": Gauge, "burning":
    Gauge} to publish into, replacing the default `slo_*` families —
    the fleet collector injects literally-declared `slo_fleet_*`
    instruments so per-process and fleet-wide burn never share a
    series. on_fast_burn: optional `fn(objective_name, detail)`
    replacing the default flight-recorder trigger on a fresh fast
    burn — the fleet engine routes this into the correlated fleet
    dump instead of the local process's recorder.
    """

    def __init__(self, objectives=(), clock=None, metrics=None,
                 on_fast_burn=None):
        self._lock = threading.Lock()
        self._clock = clock if clock is not None else time.perf_counter
        self._objectives = []
        self._series = {}          # (name, key) -> _Series
        self._burning = set()      # objective names fast-burning now
        self._metrics = dict(metrics) if metrics is not None else None
        self._on_fast_burn = on_fast_burn
        self.configure(objectives)

    # -- setup -------------------------------------------------------------
    def configure(self, objectives, clock=None):
        """Replace the objective set (and optionally the clock);
        clears every observation series."""
        with self._lock:
            self._objectives = list(objectives)
            self._series = {}
            self._burning = set()
            if clock is not None:
                self._clock = clock

    def clear(self):
        """Drop observations + burning state; objectives survive
        (telemetry.reset() calls this)."""
        with self._lock:
            self._series = {}
            self._burning = set()

    @property
    def objectives(self):
        with self._lock:
            return list(self._objectives)

    def _families(self):
        # lazy: mx.telemetry must stay importable backend-free and the
        # registry is only touched once an objective actually observes
        if self._metrics is None:
            from . import counter, gauge
            self._metrics = {
                "events": counter(
                    "slo_events_total",
                    "SLO observations classified against each "
                    "objective (verdict=good|bad)",
                    ("objective", "verdict")),
                "burn": gauge(
                    "slo_burn_rate",
                    "error-budget burn rate per objective and window "
                    "(1.0 = consuming exactly the budget; worst "
                    "series when the objective is split per-dimension)",
                    ("objective", "window")),
                "burning": gauge(
                    "slo_fast_burning",
                    "1 while the objective's fast-window burn rate is "
                    "at/over its threshold, else 0",
                    ("objective",)),
            }
        return self._metrics

    # -- observation -------------------------------------------------------
    def observe_ttft(self, ttft_s, priority=None, tenant=None, t=None):
        """Classify one first-token latency against every TTFT
        objective. No-op (one attribute read) with none configured.
        `t` backdates the observation onto the engine's clock axis —
        the fleet collector stamps aligned event times so its burn
        windows stay exact under scrape lag."""
        if not self._objectives:
            return
        ms = float(ttft_s) * 1e3
        self._observe("ttft_p99_ms", lambda slo: ms <= slo.ttft_p99_ms,
                      priority, tenant, t)

    def observe_goodput(self, tokens_per_s, priority=None, tenant=None,
                        t=None):
        """Classify one finished request's decode goodput against
        every goodput objective."""
        if not self._objectives:
            return
        rate = float(tokens_per_s)
        self._observe("goodput_min", lambda slo: rate >= slo.goodput_min,
                      priority, tenant, t)

    def _observe(self, field, is_good, priority, tenant, t=None):
        t = self._clock() if t is None else float(t)
        fams = self._families()
        with self._lock:
            for slo in self._objectives:
                if getattr(slo, field) is None:
                    continue
                good = bool(is_good(slo))
                key = (slo.name, slo.key_of(priority, tenant))
                s = self._series.get(key)
                if s is None:
                    s = self._series[key] = _Series()
                s.add(t, good)
                s.prune(t - slo.slow_window_s)
                fams["events"].labels(
                    slo.name, "good" if good else "bad").inc()

    # -- evaluation --------------------------------------------------------
    def evaluate(self, t_now=None):
        """Burn rates for every (objective, series): list of dicts.
        Publishes the worst-series gauges per objective and latches a
        `slo_burn:<objective>` flight dump the moment an objective's
        fast burn crosses its threshold (once, until flight rearms)."""
        if t_now is None:
            t_now = self._clock()
        out = []
        newly = []
        fams = self._families() if self._objectives else None
        with self._lock:
            for slo in self._objectives:
                budget = 1.0 - slo.target
                worst_fast = worst_slow = 0.0
                found = False
                for (name, key), s in self._series.items():
                    if name != slo.name:
                        continue
                    found = True
                    nf, bf = s.window(t_now, slo.fast_window_s)
                    ns, bs = s.window(t_now, slo.slow_window_s)
                    fast = _burn(nf, bf, budget, slo.min_events)
                    slow = _burn(ns, bs, budget, slo.min_events)
                    worst_fast = max(worst_fast, fast)
                    worst_slow = max(worst_slow, slow)
                    out.append({
                        "objective": slo.name,
                        "labels": dict(key),
                        "fast": {"window_s": slo.fast_window_s,
                                 "events": nf, "bad": bf,
                                 "burn_rate": fast},
                        "slow": {"window_s": slo.slow_window_s,
                                 "events": ns, "bad": bs,
                                 "burn_rate": slow},
                        "fast_burning": fast >= slo.fast_burn,
                        "slow_burning": slow >= slo.slow_burn,
                    })
                if not found:
                    out.append({"objective": slo.name, "labels": {},
                                "fast": {"window_s": slo.fast_window_s,
                                         "events": 0, "bad": 0,
                                         "burn_rate": 0.0},
                                "slow": {"window_s": slo.slow_window_s,
                                         "events": 0, "bad": 0,
                                         "burn_rate": 0.0},
                                "fast_burning": False,
                                "slow_burning": False})
                burning = worst_fast >= slo.fast_burn
                if fams is not None:
                    fams["burn"].labels(slo.name, "fast").set(worst_fast)
                    fams["burn"].labels(slo.name, "slow").set(worst_slow)
                    fams["burning"].labels(slo.name).set(
                        1.0 if burning else 0.0)
                if burning and slo.name not in self._burning:
                    newly.append((slo.name, worst_fast, worst_slow))
                if burning:
                    self._burning.add(slo.name)
                else:
                    self._burning.discard(slo.name)
        for name, fast, slow in newly:
            # outside the lock: flight dumps walk telemetry state.
            # flight's own per-reason latch makes repeats no-ops until
            # the operator rearms, so a sustained burn dumps ONCE.
            detail = {"fast_burn": fast, "slow_burn": slow}
            if self._on_fast_burn is not None:
                try:
                    self._on_fast_burn(name, detail)
                except Exception:
                    pass           # a broken sink must not break eval
            else:
                _flight.trigger(f"slo_burn:{name}", detail)
        return out

    def fast_burning(self, t_now=None):
        """Names of objectives whose fast-window burn is at/over
        threshold — the SheddingPolicy overload input."""
        rows = self.evaluate(t_now)
        return sorted({r["objective"] for r in rows if r["fast_burning"]})

    def snapshot(self, t_now=None):
        """The `/sloz` payload: declared objectives + live burn rows."""
        rows = self.evaluate(t_now)
        decls = [{
            "name": s.name, "ttft_p99_ms": s.ttft_p99_ms,
            "goodput_min": s.goodput_min, "target": s.target,
            "per": list(s.per),
            "fast_window_s": s.fast_window_s,
            "slow_window_s": s.slow_window_s,
            "fast_burn": s.fast_burn, "slow_burn": s.slow_burn,
            "min_events": s.min_events,
        } for s in self.objectives]
        return {"objectives": decls, "series": rows,
                "fast_burning": sorted(
                    {r["objective"] for r in rows if r["fast_burning"]})}


#: The process-global SLO engine every ServingEngine observes into.
slo_engine = SLOEngine()


def configure(objectives, clock=None):
    """Replace the global engine's objectives (list of `SLO`)."""
    slo_engine.configure(objectives, clock=clock)


def observe_ttft(ttft_s, priority=None, tenant=None):
    slo_engine.observe_ttft(ttft_s, priority=priority, tenant=tenant)


def observe_goodput(tokens_per_s, priority=None, tenant=None):
    slo_engine.observe_goodput(tokens_per_s, priority=priority,
                               tenant=tenant)


def snapshot():
    return slo_engine.snapshot()


def fast_burning():
    return slo_engine.fast_burning()
