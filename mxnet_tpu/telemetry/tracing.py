"""Lightweight span tracing: nesting, JSONL event log, profiler interplay.

`span(name)` is a context manager that (a) nests via a thread-local
stack, (b) records its duration into the labeled
`span_duration_seconds{name=...}` histogram, (c) appends a structured
event to an in-process ring buffer (and, when `enable_jsonl(path)` is
armed, to a JSON-lines file), and (d) forwards into
`jax.profiler.TraceAnnotation` — but ONLY while the mx.profiler device
trace is running, so spans line up with the XLA timeline without paying
annotation-construction cost (or importing jax at all) in normal
operation. That gating mirrors the `sys.modules` probe the op-dispatch
funnel uses (ops/registry._profiler_active): a process that never starts
a device trace never constructs an annotation.

Event schema (one JSON object per line):
    {"name", "ts" (unix seconds at exit), "dur" (seconds), "depth",
     "parent" (enclosing span name or null), "thread", ...attrs}

A span exited by a raising block records `status="error"` plus the
exception type under `"error"` — the exception itself propagates
untouched (`__exit__` returns False). Observers can subscribe to every
finished span with `add_event_hook(fn)` (the flight recorder's feed);
hook exceptions are swallowed, an observer must never break the host.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque

__all__ = ["span", "events", "clear_events", "enable_jsonl",
           "disable_jsonl", "add_event_hook", "remove_event_hook"]

_tls = threading.local()
_events_lock = threading.Lock()
_events = deque(maxlen=4096)
_jsonl = {"fh": None, "path": None}
_event_hooks = []


def _span_hist():
    # late import: instruments ↔ tracing have no cycle, but the default
    # registry lives in the package __init__ which imports this module
    from . import histogram
    return histogram("span_duration_seconds",
                     "wall time of telemetry.span ranges",
                     labelnames=("name",))


def _device_trace_running():
    prof = sys.modules.get("mxnet_tpu.profiler")
    return prof is not None and prof._state.get("jax_trace", False)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class span:
    """with span("serving.decode_block", slot=3): ..."""

    __slots__ = ("name", "attrs", "_ann", "_t0", "_parent", "_depth")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self._ann = None

    def __enter__(self):
        st = _stack()
        self._parent = st[-1].name if st else None
        self._depth = len(st)
        st.append(self)
        if _device_trace_running():
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc_val, exc_tb)
            self._ann = None
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        _span_hist().labels(self.name).observe(dur)
        ev = {"name": self.name, "ts": time.time(), "dur": dur,
              "depth": self._depth, "parent": self._parent,
              "thread": threading.get_ident()}
        if exc_type is not None:
            # a raising block still records its span — tagged, so the
            # event log shows WHERE the stack unwound, not a silent gap
            ev["status"] = "error"
            ev["error"] = exc_type.__name__
        if self.attrs:
            ev.update(self.attrs)
        with _events_lock:
            _events.append(ev)
            fh = _jsonl["fh"]
            if fh is not None:
                try:
                    fh.write(json.dumps(ev) + "\n")
                    fh.flush()
                except Exception:
                    pass           # a full disk must not break serving
            hooks = list(_event_hooks)
        for fn in hooks:
            try:
                fn(ev)
            except Exception:
                pass               # observers must never break the host
        return False


def events():
    """The in-process span ring buffer (most recent 4096), oldest first."""
    with _events_lock:
        return list(_events)


def clear_events():
    with _events_lock:
        _events.clear()


def enable_jsonl(path):
    """Start appending every finished span to `path` as JSON lines."""
    with _events_lock:
        if _jsonl["fh"] is not None:
            _jsonl["fh"].close()
        _jsonl["fh"] = open(path, "a")
        _jsonl["path"] = path
    return path


def disable_jsonl():
    with _events_lock:
        if _jsonl["fh"] is not None:
            _jsonl["fh"].close()
        _jsonl["fh"] = None
        _jsonl["path"] = None


def add_event_hook(fn):
    """Call fn(event_dict) on every finished span (the flight
    recorder's subscription point). Exceptions in fn are swallowed."""
    with _events_lock:
        if fn not in _event_hooks:
            _event_hooks.append(fn)


def remove_event_hook(fn):
    with _events_lock:
        if fn in _event_hooks:
            _event_hooks.remove(fn)
