"""mx.test_utils — the numeric-correctness toolkit.

Reference parity: python/mxnet/test_utils.py — every operator there is
tested three ways (SURVEY.md §4): finite-difference vs autograd
(`check_numeric_gradient`), against a NumPy reference implementation
(`check_symbolic_forward`-style asserts), and across backends/dtypes
(`check_consistency`, THE cpu-vs-gpu oracle — here the oracle pair is
XLA:CPU vs whatever accelerator is attached, plus dtype sweeps).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError

__all__ = ["assert_almost_equal", "same", "almost_equal", "rand_ndarray",
           "rand_shape_nd", "default_tolerances", "check_numeric_gradient",
           "check_consistency", "default_context", "list_contexts"]

# dtype-aware default tolerances (parity: assert_almost_equal's internal
# rtol/atol table; bf16 added for TPU-first testing)
_DEFAULT_RTOL = {_np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-4,
                 _np.dtype(_np.float64): 1e-6}
_DEFAULT_ATOL = {_np.dtype(_np.float16): 1e-3, _np.dtype(_np.float32): 1e-5,
                 _np.dtype(_np.float64): 1e-8}
_BF16_RTOL, _BF16_ATOL = 2e-2, 2e-3


def _to_numpy(x):
    a = getattr(x, "asnumpy", None)
    if a is not None:
        x = a()
    x = _np.asarray(x)
    if x.dtype.kind == "V" or x.dtype.name == "bfloat16":
        x = x.astype(_np.float32)
    return x


def _dtype_of(a):
    dt = getattr(a, "dtype", None)
    return dt if dt is not None else _np.asarray(a).dtype


def default_tolerances(*arrays):
    rtol = atol = 0.0
    for a in arrays:
        dt = _dtype_of(a)  # dtype only — no device-to-host transfer
        if getattr(dt, "name", str(dt)) == "bfloat16":
            rtol, atol = max(rtol, _BF16_RTOL), max(atol, _BF16_ATOL)
            continue
        try:
            d = _np.dtype(dt)
        except TypeError:
            continue
        rtol = max(rtol, _DEFAULT_RTOL.get(d, 0.0))
        atol = max(atol, _DEFAULT_ATOL.get(d, 0.0))
    return (rtol or 1e-5), (atol or 1e-8)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Parity: test_utils.assert_almost_equal — dtype-aware tolerances."""
    drtol, datol = default_tolerances(a, b)
    rtol = drtol if rtol is None else rtol
    atol = datol if atol is None else atol
    an, bn = _to_numpy(a), _to_numpy(b)
    _np.testing.assert_allclose(
        an, bn, rtol=rtol, atol=atol, equal_nan=equal_nan,
        err_msg=f"{names[0]} !~ {names[1]} (rtol={rtol}, atol={atol})")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return _np.array_equal(_to_numpy(a), _to_numpy(b))


def rand_shape_nd(ndim, dim=10, allow_zero_size=False):
    low = 0 if allow_zero_size else 1
    return tuple(_np.random.randint(low, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", scale=1.0, ctx=None):
    from .ndarray import array
    data = _np.random.standard_normal(shape) * scale
    return array(data, dtype=dtype, ctx=ctx)


def default_context():
    from .device import current_context
    return current_context()


def list_contexts():
    """All distinct device platforms available (cpu always; tpu/gpu when
    attached) — the check_consistency sweep axis."""
    import jax
    from .device import Device
    out = []
    for plat in ("cpu", "tpu", "gpu"):
        try:
            devs = jax.devices(plat)
        except RuntimeError:
            continue
        if devs:
            out.append(Device(plat, 0))
    return out


def check_numeric_gradient(fn, inputs, eps=1e-4, rtol=1e-2, atol=1e-4,
                           argnums=None):
    """Finite-difference vs autograd oracle (parity:
    test_utils.check_numeric_gradient).

    fn: callable over NDArrays returning one NDArray (any shape; reduced
    by sum for the scalar loss). inputs: list of NDArrays (float64
    recommended for a tight eps). argnums: which inputs to check (default
    all)."""
    from . import autograd
    from .ndarray import array

    argnums = range(len(inputs)) if argnums is None else argnums
    inputs = list(inputs)
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [inputs[i].grad.asnumpy().astype(_np.float64)
                for i in argnums]

    def scalar_loss(arrays):
        return float(fn(*arrays).sum().asscalar())

    for gi, i in enumerate(argnums):
        base = inputs[i].asnumpy().astype(_np.float64)
        numeric = _np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            for sign in (+1, -1):
                pert = flat.copy()
                pert[j] += sign * eps
                arrs = list(inputs)
                arrs[i] = array(pert.reshape(base.shape),
                                dtype=str(base.dtype))
                num_flat[j] += sign * scalar_loss(arrs)
            num_flat[j] /= 2 * eps
        _np.testing.assert_allclose(
            analytic[gi], numeric, rtol=rtol, atol=atol,
            err_msg=f"analytic vs numeric gradient mismatch for input {i}")


def check_consistency(fn, inputs, ctx_list=None, dtypes=("float32",),
                      rtol=None, atol=None):
    """Run fn on every (context, dtype) pair and assert all outputs agree
    with the first (parity: test_utils.check_consistency; the reference's
    cpu-vs-gpu oracle, here cpu-XLA vs accelerator and dtype sweep)."""
    from .ndarray import array

    ctx_list = ctx_list or list_contexts()
    if not ctx_list:
        raise MXNetError("no contexts available for check_consistency")
    ref = None
    for ctx in ctx_list:
        for dt in dtypes:
            with ctx:
                arrs = [array(_to_numpy(x), dtype=dt) for x in inputs]
                out = fn(*arrs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            vals = [_to_numpy(o).astype(_np.float64) for o in outs]
            if ref is None:
                ref = vals
                continue
            for r, v in zip(ref, vals):
                a_rtol, a_atol = (rtol, atol)
                if a_rtol is None or a_atol is None:
                    drt, dat = default_tolerances(
                        _np.zeros((), dtype=dt if dt != "bfloat16"
                                  else "float16"))
                    a_rtol = drt if a_rtol is None else a_rtol
                    a_atol = dat if a_atol is None else a_atol
                _np.testing.assert_allclose(
                    r, v, rtol=a_rtol, atol=a_atol,
                    err_msg=f"inconsistent result on {ctx} dtype={dt}")
    return ref
