"""Test harness configuration.

Parity with the reference's strategy (SURVEY.md §4): XLA:CPU is the
deviceless test target (the analog of the reference's CPU-as-oracle), with
an 8-device virtual mesh for multi-chip sharding tests (the analog of
tests/nightly's multi-process-on-one-box kvstore tests).

Must set XLA flags BEFORE jax initialises, hence this runs at conftest
import time.
"""
import os
import sys

# The forcing recipe is shared with the driver entry point; it must run
# before jax initialises a backend, hence at conftest import time.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import _force_virtual_cpu_mesh  # noqa: E402

_force_virtual_cpu_mesh(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng(request):
    """Parity: tests/python/unittest/common.py with_seed() — deterministic
    seeding per test, seed logged on failure via -ra output."""
    import mxnet_tpu as mx

    seed = abs(hash(request.node.nodeid)) % (2 ** 31)
    mx.random.seed(seed)
    np.random.seed(seed % (2 ** 31))
    yield
