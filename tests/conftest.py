"""Test harness configuration.

Parity with the reference's strategy (SURVEY.md §4): XLA:CPU is the
deviceless test target (the analog of the reference's CPU-as-oracle), with
an 8-device virtual mesh for multi-chip sharding tests (the analog of
tests/nightly's multi-process-on-one-box kvstore tests).

Must set XLA flags BEFORE jax initialises, hence this runs at conftest
import time.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# jax may be PRE-IMPORTED at interpreter start (site hooks) with the env's
# JAX_PLATFORMS (e.g. a TPU tunnel); env edits alone are then ignored.
# Backends initialize lazily, so forcing the config here still wins as long
# as no jax computation ran yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "conftest could not force the 8-device virtual CPU mesh; "
    f"got {jax.devices()} — was a backend already initialized?")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng(request):
    """Parity: tests/python/unittest/common.py with_seed() — deterministic
    seeding per test, seed logged on failure via -ra output."""
    import mxnet_tpu as mx

    seed = abs(hash(request.node.nodeid)) % (2 ** 31)
    mx.random.seed(seed)
    np.random.seed(seed % (2 ** 31))
    yield
