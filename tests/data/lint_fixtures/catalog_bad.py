"""Seeded catalog violations: a runtime-formatted metric name and an
undocumented literal one. Parsed only, never imported."""
from mxnet_tpu import telemetry


def make_metrics(name):
    c = telemetry.counter
    dynamic = telemetry.counter(f"requests_{name}_total",
                                "name baked from runtime data")
    undoc = c("totally_undocumented_metric_total", "not in the docs")
    return dynamic, undoc
