"""Near-misses the catalog pass must NOT flag: a documented literal
instrument, numpy's histogram (not an instrument), and a .counter on
a non-telemetry object. Parsed only, never imported."""
import numpy as np

from mxnet_tpu import telemetry


def make_metrics(values, stats):
    documented = telemetry.counter("documented_metric_total", "ok")
    hist, edges = np.histogram(values)      # numpy, not an instrument
    other = stats.counter(values)           # unrelated receiver
    return documented, hist, edges, other
