"""Seeded ownership violations: a handler-thread path straight into a
@loop_only method, and a hook fired while holding a lock. Parsed
only, never imported."""
import threading

from mxnet_tpu.analysis import loop_only


class Engine:
    @loop_only
    def submit(self, req):
        self.q = req


class Handler:
    def do_GET(self):
        self.helper()

    def helper(self):
        # handler thread mutating loop-owned state directly
        self.server.engine.submit(None)


class BadLog:
    def __init__(self):
        self._lock = threading.Lock()
        self._hooks = []

    def fire(self, event):
        with self._lock:
            for hook in self._hooks:
                hook(event)             # hook invoked under the lock
