"""Near-misses the ownership pass must NOT flag: the handler path
stops at a @thread_safe enqueue boundary, and the hook list is
snapshotted under the lock but fired after release. Parsed only."""
import threading

from mxnet_tpu.analysis import loop_only, thread_safe


class Engine:
    @loop_only
    def submit(self, req):
        self.q = req


class Frontend:
    @thread_safe
    def enqueue(self, req):
        self.cmd_q.append(("submit", req))

    def drain_cmds(self):
        # loop thread only — not reachable from a handler root
        for _, req in self.cmd_q:
            self.engine.submit(req)


class Handler:
    def do_POST(self):
        self.server.fe.enqueue(None)    # boundary: traversal stops


class GoodLog:
    def __init__(self):
        self._lock = threading.Lock()
        self._hooks = []

    def fire(self, event):
        with self._lock:
            hooks = list(self._hooks)
        for hook in hooks:
            hook(event)                 # after release — safe
