"""Seeded phase-taxonomy violations: phase-name literals that are not
in telemetry.PHASES — a typo'd name and an invented one. Parsed only,
never imported."""


class LeakyEngine:
    def record_admit(self, req, dt):
        # typo: "queue_wiat" is not "queue_wait"
        self.request_log.phase(req.request_id, self.engine_id,
                               "queue_wiat", dt)

    def record_warmup(self, req, dt):
        # invented phase outside the five-name taxonomy
        self._phase(req, "warmup", dt)


def report(log, rid, eng, dt):
    # kwarg spelling of the same typo, on the bound log method
    log.phase(rid, eng, dt, phase="first_decod")
