"""Near-misses the phases pass must NOT flag: valid taxonomy literals,
a variable-carried name (the runtime check's job), a forwarding helper
piping its argument through, and an unrelated `.phase` receiver with no
string literal in the phase slot. Parsed only, never imported."""


class CleanEngine:
    def record_admit(self, req, dt):
        self.request_log.phase(req.request_id, self.engine_id,
                               "queue_wait", dt)

    def record_pagein(self, req, dt):
        self._phase(req, "host_pagein", dt)

    def _phase(self, req, name, dt):
        # forwarding helper: the name arrives in a variable
        self.request_log.phase(req.request_id, self.engine_id, name, dt)


def report(log, rid, eng, which, dt):
    log.phase(rid, eng, which, dt)          # variable: runtime's job
    log.phase(rid, eng, dt, phase="prefill_chunks")
    moon = object()
    return moon.phase                        # attribute, not a call
