"""Seeded resource-discipline violation: a lease taken with no
release on the exception edge. Parsed only, never imported."""


class Worker:
    def __init__(self, pool):
        self.pool = pool

    def grab(self, n):
        pages = self.pool.alloc(n)      # leaks if prepare() raises
        self.meta = prepare(pages)      # noqa: F821 — fixture
        return pages

    def pagein(self, key):
        payload = self.tier.checkout(key)   # pin leaks if land() raises
        land(payload)                       # noqa: F821 — fixture
        self.tier.release(key, drop=True)   # happy path only
        return payload
