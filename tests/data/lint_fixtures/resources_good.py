"""Near-misses the resource pass must NOT flag: try/finally coverage,
except-release-reraise, @supervised rollback, return-transfer, pool
internals, and plain lock acquire. Parsed only, never imported."""
import threading

from mxnet_tpu.analysis import supervised


class Pool:
    def alloc(self, n):
        self.used = self.used + n       # internals of the primitive
        return list(range(n))

    def release(self, pages):
        self.used = self.used - len(pages)


class Careful:
    def __init__(self, pool):
        self.pool = pool
        self._lock = threading.Lock()

    def grab_covered(self, n):
        pages = None
        try:
            pages = self.pool.alloc(n)
            return consume(pages)       # noqa: F821 — fixture
        finally:
            if pages is not None:
                self.pool.release(pages)

    def grab_reraise(self, n):
        try:
            leased = self.pool.alloc(n)
            return consume(leased)      # noqa: F821 — fixture
        except Exception:
            self.pool.release(locals().get("leased", []))
            raise

    def grab_transfer(self, n):
        return self.pool.alloc(n)       # ownership moves to the caller

    @supervised("rolled back by the supervisor audit (fixture)")
    def grab_supervised(self, n):
        pages = self.pool.alloc(n)
        self.meta = len(pages)
        return pages

    def locked(self):
        self._lock.acquire()            # a lock, not a lease
        try:
            return self.pool.used
        finally:
            self._lock.release()

    def pagein_covered(self, key):
        ok = False
        try:
            payload = self.tier.checkout(key)
            land(payload)               # noqa: F821 — fixture
            ok = True
        finally:
            self.tier.release(key, drop=ok)
        return payload

    def drop_covered(self, key):
        try:
            payload = self.tier.checkout(key)
            return consume(payload)     # noqa: F821 — fixture
        except Exception:
            self.tier.discard(key)      # discard counts as release
            raise
