"""Seeded trace-safety violations — every rule fires exactly where
tests/test_lint.py expects. NOT importable serving code; parsed only."""
import jax
import jax.numpy as jnp


@jax.jit
def leaky_step(x, y):
    if x > 0:                           # trace-host-branch: traced `if`
        y = y + 1
    scale = float(jnp.sum(y))           # trace-host-sync: float() syncs
    key = f"bucket-{x}"                 # trace-format: value in a key
    return y * scale, key
