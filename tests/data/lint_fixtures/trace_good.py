"""Near-misses the trace-safety pass must NOT flag: static args,
shape reads, container truthiness, isinstance, unpacked helper
results. Parsed only, never imported."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(1,))
def stable_step(x, n, *rest):
    if n > 2:                           # static_argnums arg: host value
        x = x * 2.0
    if x.ndim == 2:                     # shape/ndim reads are static
        x = x.sum(axis=-1)
    extras = tuple(rest)
    if extras:                          # container truthiness = length
        x = x + extras[0]
    if not extras:
        x = x - 1.0
    out = x if isinstance(x, jnp.ndarray) else jnp.asarray(x)
    leaves, treedef = jax.tree_util.tree_flatten(out)
    width = len(leaves)                 # host list from unpacked call
    label = f"rank-{x.ndim}"            # static attr in an f-string
    return out * width, label
