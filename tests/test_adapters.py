"""Multi-tenant LoRA serving tests (tier-1, ISSUE 10).

Covers: the AdapterPool slab ledger (register/acquire/release, LRU
eviction, pin-while-in-use exhaustion as backpressure, the audit
invariant check), the null-adapter bit-identity guarantee across the
plain / prefix-cache / speculative engines, the merged-weight dense
oracle (``W + (B A)^T * alpha/r``) including a mixed batch where every
slot wears a different adapter, compile-flat adapter churn (adapter
identity is runtime data, never a shape axis), per-tenant quotas +
deficit-weighted fair admission, and the router's adapter-affinity
placement key.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models import GPT2Config, GPT2ForCausalLM
from mxnet_tpu.serving import (AdapterPool, AdapterPoolExhausted,
                               Request, ServingEngine, SheddingPolicy,
                               SlotScheduler, TenantQuota,
                               TenantQuotaError, merged_weights,
                               random_lora)
from mxnet_tpu.telemetry import cost


def _tiny(vocab=97, layers=2, units=32, heads=2, max_len=64):
    cfg = GPT2Config(vocab_size=vocab, units=units, num_layers=layers,
                     num_heads=heads, max_length=max_len, dropout=0.0,
                     attention_dropout=0.0)
    net = GPT2ForCausalLM(cfg)
    mx.rng.seed(3)
    net.initialize(mx.init.Normal(0.05))
    return net, cfg


def _engine(net, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_length", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("decode_block", 4)
    kw.setdefault("attn_impl", "xla")
    return ServingEngine(net, **kw)


def _reqs(prompts, max_new=6, **kw):
    return [Request(p, max_new, request_id=f"r{i}", **kw)
            for i, p in enumerate(prompts)]


def _outputs(done):
    return {r.id: list(r.output_tokens) for r in done}


def _prompts(n=4, seed=0, lo=3, hi=14):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 97, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def _merged_net(weights):
    """A fresh tiny model with `weights`' LoRA deltas baked densely
    into every attention projection — the oracle engine's model."""
    net, _ = _tiny()
    for li, blk in enumerate(net.backbone.blocks()):
        attn = blk.attn
        for pname in ("query", "key", "value", "proj"):
            layer = getattr(attn, pname)
            w = layer.weight.data().asnumpy()
            layer.weight.set_data(
                mx.nd.array(merged_weights(w, weights, pname, li)))
    return net


# ---------------------------------------------------------------------------
# AdapterPool ledger
# ---------------------------------------------------------------------------

def test_pool_register_validation():
    _, cfg = _tiny()
    with pytest.raises(MXNetError):
        AdapterPool(cfg, slots=1)
    pool = AdapterPool(cfg, slots=3, max_rank=4)
    w = random_lora(cfg, rank=2)
    for bad_id in (None, 0):
        with pytest.raises(MXNetError):
            pool.register(bad_id, w)
    with pytest.raises(MXNetError):      # rank above the pad budget
        pool.register("big", random_lora(cfg, rank=8))
    shaped = dict(w, A=w["A"][:, :1])    # wrong layer count
    with pytest.raises(MXNetError):
        pool.register("shape", shaped)
    with pytest.raises(MXNetError):      # acquire before register
        pool.acquire("ghost")
    pool.register("ok", w)
    assert pool.has("ok") and pool.has(None) and pool.has(0)
    assert not pool.has("ghost")
    assert pool.num_registered == 1 and pool.num_resident == 0


def test_pool_acquire_release_lru_and_null():
    _, cfg = _tiny()
    pool = AdapterPool(cfg, slots=3, max_rank=2)   # 2 usable slots
    for name in ("a", "b", "c"):
        pool.register(name, random_lora(cfg, rank=2))
    assert pool.acquire(None) == 0 and pool.acquire(0) == 0
    sa = pool.acquire("a")
    sb = pool.acquire("b")
    assert sa != sb and 0 not in (sa, sb)
    assert pool.page_ins == 2 and pool.num_resident == 2
    pool.release("a")
    pool.release("b")
    # both stay warm until a page-in needs a slot; 'a' is the LRU
    assert pool.num_resident == 2 and pool.num_pinned == 0
    sc = pool.acquire("c")
    assert sc == sa and pool.evictions == 1
    assert pool.slot_of("a") is None and pool.slot_of("b") == sb
    # re-acquiring the warm resident is a hit: no page-in
    pins_before = pool.page_ins
    assert pool.acquire("b") == sb and pool.page_ins == pins_before
    assert pool.audit(assignments=["c", "b"]) == []


def test_pool_exhaustion_is_loud_and_pins_protect():
    _, cfg = _tiny()
    pool = AdapterPool(cfg, slots=3, max_rank=2)
    for name in ("a", "b", "c"):
        pool.register(name, random_lora(cfg, rank=2))
    pool.acquire("a")
    pool.acquire("b")
    with pytest.raises(AdapterPoolExhausted):
        pool.acquire("c")
    with pytest.raises(MXNetError):      # evicting a pinned adapter
        pool.evict("a")
    with pytest.raises(MXNetError):      # re-registering while pinned
        pool.register("a", random_lora(cfg, rank=2))
    pool.release("a")
    assert pool.acquire("c") is not None      # LRU-evicts unpinned 'a'
    with pytest.raises(MXNetError):           # pin underflow
        pool.release("a")
    pool.release("b")
    pool.release("c")
    assert pool.audit() == []


def test_pool_audit_catches_leaked_and_missing_pins():
    _, cfg = _tiny()
    pool = AdapterPool(cfg, slots=3, max_rank=2)
    pool.register("a", random_lora(cfg, rank=2))
    slot = pool.acquire("a")
    # pin with no active-slot assignment = a leak
    v = pool.audit(assignments=[])
    assert any("leaked" in s for s in v)
    # assignment without residency
    v = pool.audit(assignments=["a", "a"])
    assert any("pin count" in s for s in v)
    with pytest.raises(MXNetError):
        pool.audit(assignments=[], raise_on_error=True)
    pool.release("a")
    assert pool.audit(assignments=[]) == []
    # corrupt the ledger behind the API: double residency
    pool._adapter_at[slot] = "a"
    pool._adapter_at[2 if slot != 2 else 1] = "a"
    assert any("resident" in s for s in pool.audit())


# ---------------------------------------------------------------------------
# tenant quotas + fair-share admission (scheduler level)
# ---------------------------------------------------------------------------

def test_tenant_max_queue_bound_sheds_at_submit():
    s = SlotScheduler(2, tenant_quotas={"t": TenantQuota(max_queue=2)})
    s.submit(Request([1], 1, request_id="a", tenant="t"))
    s.submit(Request([1], 1, request_id="b", tenant="t"))
    with pytest.raises(TenantQuotaError) as ei:
        s.submit(Request([1], 1, request_id="c", tenant="t"))
    assert ei.value.reason == "tenant_quota"
    # other tenants are untouched by t's bound
    s.submit(Request([1], 1, request_id="d", tenant="u"))


def test_tenant_max_active_keeps_requests_queued():
    s = SlotScheduler(3, tenant_quotas={"t": TenantQuota(max_active=1)})
    for i in range(3):
        s.submit(Request([1], 1, request_id=f"t{i}", tenant="t"))
    s.submit(Request([1], 1, request_id="u0", tenant="u"))
    admitted = [r.id for _, r in s.admit(0.0)]
    # only ONE of t's requests may hold a slot; u fills another;
    # the third slot stays empty rather than over-admitting t
    assert sum(r.startswith("t") for r in admitted) == 1
    assert "u0" in admitted and len(admitted) == 2
    assert s.tenant_active("t") == 1 and s.tenant_queued("t") == 2


def test_deficit_weighted_fair_pick_follows_weights():
    s = SlotScheduler(1, tenant_quotas={
        "heavy": TenantQuota(weight=3.0),
        "light": TenantQuota(weight=1.0)})
    for i in range(40):
        s.submit(Request([1], 1, request_id=f"h{i}", tenant="heavy"))
        s.submit(Request([1], 1, request_id=f"l{i}", tenant="light"))
    order = []
    for _ in range(24):
        (slot, req), = s.admit(0.0)
        order.append(req.tenant)
        s.release(slot)
    # ~3:1 service ratio (boundary rounding aside), starvation-free:
    # light is served steadily, never parked behind heavy's backlog
    h, l = order.count("heavy"), order.count("light")
    assert h + l == 24 and h >= 2 * l and l >= 5
    for i in range(0, 24, 6):
        assert "light" in order[i:i + 6]


def test_tenancy_rides_through_snapshot():
    s = SlotScheduler(2, tenant_quotas={"t": TenantQuota(max_active=1)})
    s.submit(Request([1, 2], 2, request_id="a", tenant="t",
                     adapter_id="x"))
    s.admit(0.0)
    snap = s.snapshot()
    (active,) = snap["active"].values()
    assert active["tenant"] == "t" and active["adapter_id"] == "x"
    assert snap["tenants"]["t"]["max_active"] == 1
    assert snap["tenants"]["t"]["active"] == 1


# ---------------------------------------------------------------------------
# null-adapter bit-identity (the pre-PR engine is the oracle)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", [
    "plain", "prefix", pytest.param("spec", marks=pytest.mark.slow)])
def test_null_adapter_output_bit_identical(mode):
    net, cfg = _tiny()
    kw = {}
    if mode == "prefix":
        kw = dict(prefix_cache=True)
    elif mode == "spec":
        kw = dict(speculative=True, spec_tokens=3)
    prompts = _prompts(6, seed=4)
    mk = lambda: _reqs(prompts, max_new=7, do_sample=True,  # noqa: E731
                       temperature=0.8)
    for i, r in enumerate(mk()):
        r.seed = 50 + i
    want = _outputs(_engine(net, **kw).serve(mk()))

    pool = AdapterPool(cfg, slots=4, max_rank=4)
    pool.register("unused", random_lora(cfg, rank=4, seed=9))
    eng = _engine(net, adapter_pool=pool, **kw)
    reqs = mk()
    for r in reqs[::2]:
        r.adapter_id = 0          # explicit null spelling
    got = _outputs(eng.serve(reqs))
    assert got == want
    assert eng.audit_adapters() == [] and eng.audit_pages() == []


# ---------------------------------------------------------------------------
# merged-weight dense oracle
# ---------------------------------------------------------------------------

def test_adapter_matches_merged_weight_oracle():
    net, cfg = _tiny()
    w = random_lora(cfg, rank=3, seed=7, scale=0.05)
    pool = AdapterPool(cfg, slots=4, max_rank=4)
    pool.register("fin", w)
    prompts = _prompts(4, seed=1)
    got = _outputs(_engine(net, adapter_pool=pool).serve(
        _reqs(prompts, adapter_id="fin")))
    want = _outputs(_engine(_merged_net(w)).serve(_reqs(prompts)))
    assert got == want


def test_mixed_adapter_batch_each_slot_its_own_oracle():
    net, cfg = _tiny()
    adapters = {f"a{i}": random_lora(cfg, rank=2 + i % 3, seed=20 + i,
                                     scale=0.05) for i in range(3)}
    pool = AdapterPool(cfg, slots=5, max_rank=4)
    for name, w in adapters.items():
        pool.register(name, w)
    eng = _engine(net, num_slots=4, adapter_pool=pool)
    prompts = _prompts(4, seed=2)
    wear = ["a0", "a1", "a2", None]    # every slot a different adapter
    reqs = [Request(p, 6, request_id=f"m{i}", adapter_id=wear[i])
            for i, p in enumerate(prompts)]
    eng.serve(reqs)
    # co-batched: 4 slots, 4 requests — all decoded in one program
    assert eng.stats["prefills"] == 4
    for i, r in enumerate(reqs):
        oracle_net = net if wear[i] is None \
            else _merged_net(adapters[wear[i]])
        (want,) = _engine(oracle_net).serve(
            [Request(prompts[i], 6, request_id="o")])
        assert list(r.output_tokens) == list(want.output_tokens), \
            f"slot {i} adapter {wear[i]!r}"
    assert eng.audit_adapters() == []


# ---------------------------------------------------------------------------
# adapter churn: runtime data, never a shape axis
# ---------------------------------------------------------------------------

def test_adapter_churn_is_compile_flat():
    net, cfg = _tiny()
    pool = AdapterPool(cfg, slots=3, max_rank=2)   # 2 usable slots...
    names = [f"a{i}" for i in range(5)]            # ...5 adapters
    for i, name in enumerate(names):
        pool.register(name, random_lora(cfg, rank=2, seed=30 + i))
    eng = _engine(net, adapter_pool=pool)
    prompt = list(range(3, 11))

    def compiles():
        progs = cost.report()["programs"]
        return sum(s["compiles"] for p, s in progs.items()
                   if p.startswith(f"engine{eng._eid}/"))

    # warm every program shape once (one prefill bucket, greedy decode)
    eng.serve([Request(prompt, 4, request_id="warm", adapter_id="a0")])
    eng.mark_warm()
    c0 = compiles()
    for round_ in range(3):            # churn through ALL the adapters
        eng.serve([Request(prompt, 4, request_id=f"c{round_}/{n}",
                           adapter_id=n) for n in names])
    assert compiles() == c0, "adapter churn must not retrace"
    assert eng.warmed
    # the slab really thrashed: more page-ins than slots
    assert pool.page_ins > pool.slots
    assert eng.stats["adapter_page_ins"] == pool.page_ins
    assert eng.audit_adapters() == []


def test_adapter_slab_exhaustion_is_backpressure():
    net, cfg = _tiny()
    pool = AdapterPool(cfg, slots=2, max_rank=2)   # ONE usable slot
    pool.register("x", random_lora(cfg, rank=2, seed=1))
    pool.register("y", random_lora(cfg, rank=2, seed=2))
    eng = _engine(net, retry_backoff_s=0.0, adapter_pool=pool)
    done = eng.serve([Request([5, 6, 7], 4, request_id="rx",
                              adapter_id="x"),
                      Request([5, 6, 8], 4, request_id="ry",
                              adapter_id="y")])
    # both finish — exhaustion requeues (nobody blamed, no quarantine)
    assert {r.id: r.status for r in done} == {"rx": "finished",
                                              "ry": "finished"}
    assert eng.stats["requests_failed"] == 0
    assert eng.audit_adapters() == [] and eng.audit_pages() == []


def test_unknown_adapter_rejected_at_submit():
    net, cfg = _tiny()
    eng = _engine(net)                         # no pool at all
    with pytest.raises(MXNetError, match="adapter"):
        eng.submit(Request([1, 2], 2, request_id="a", adapter_id="x"))
    pool = AdapterPool(cfg, slots=3, max_rank=2)
    eng2 = _engine(net, adapter_pool=pool)
    with pytest.raises(MXNetError, match="not registered"):
        eng2.submit(Request([1, 2], 2, request_id="b", adapter_id="x"))
    assert eng2.stats["requests_rejected"] == 1


# ---------------------------------------------------------------------------
# engine-level tenancy: quota shed accounting + statusz
# ---------------------------------------------------------------------------

def test_engine_tenant_quota_shed_taxonomy():
    net, cfg = _tiny()
    eng = _engine(net, tenant_quotas={
        "over": TenantQuota(max_queue=1),
        "ok": TenantQuota(weight=2.0)})
    prompts = _prompts(6, seed=3)
    done, shed = [], []
    for i, p in enumerate(prompts):
        t = "over" if i % 2 else "ok"
        r = Request(p, 3, request_id=f"q{i}", tenant=t)
        try:
            eng.submit(r)
        except TenantQuotaError as e:
            assert e.reason == "tenant_quota" and e.tenant == "over"
            shed.append(r)
    assert shed and all(r.tenant == "over" for r in shed)
    while eng.has_work:
        done.extend(eng.step())
    ts = eng.tenant_stats()
    assert ts["over"]["shed"]["tenant_quota"] == len(shed)
    assert ts["ok"].get("shed", {}) == {}
    sz = eng._statusz()
    assert "over" in sz["tenants"] and sz["config"]["adapter_pool"] is False
    # the per-tenant shed family carries the same count
    fam = telemetry.get("serving_tenant_shed_total")
    assert fam.labels(eng._eid, "over", "tenant_quota").value == len(shed)


def test_policy_tenant_queue_share_sheds_hogs():
    net, _ = _tiny()
    eng = _engine(net, policy=SheddingPolicy(queue_low=2, queue_high=50,
                                             tenant_queue_share=0.5))
    # fill the queue with one tenant up to the elevated watermark
    eng.submit(Request([1, 2, 3], 2, request_id="h0", tenant="hog"))
    eng.submit(Request([1, 2, 3], 2, request_id="h1", tenant="hog"))
    # elevated now (queue_low=2), and hog holds 2/2 > 0.5 of the queue
    from mxnet_tpu.serving import ShedError
    with pytest.raises(ShedError) as ei:
        eng.submit(Request([1, 2, 3], 2, request_id="h2", tenant="hog"))
    assert ei.value.reason == "tenant_share"
    # a different tenant still gets in
    eng.submit(Request([1, 2, 3], 2, request_id="ok", tenant="calm"))
    eng.serve()
    assert eng.tenant_stats()["hog"]["shed"]["tenant_share"] == 1


# ---------------------------------------------------------------------------
# migration: adapter_id + tenant ride export/adopt bit-identically
# ---------------------------------------------------------------------------

def test_export_adopt_preserves_adapter_and_tenant():
    net, cfg = _tiny()
    w = random_lora(cfg, rank=2, seed=5, scale=0.05)

    def mk_engine():
        pool = AdapterPool(cfg, slots=3, max_rank=2)
        pool.register("fin", w)
        return _engine(net, adapter_pool=pool)

    prompts = _prompts(3, seed=6)
    mk = lambda: [Request(p, 6, request_id=f"g{i}", adapter_id="fin",  # noqa: E731
                          tenant="t0") for i, p in enumerate(prompts)]
    want = _outputs(mk_engine().serve(mk()))

    src, dst = mk_engine(), mk_engine()
    for r in mk():
        src.submit(r)
    src.step()                      # some requests now mid-flight
    moved = src.export_requests()
    assert src.audit_adapters() == []      # pins rolled back
    assert [r.adapter_id for r in moved] == ["fin"] * 3
    assert [r.tenant for r in moved] == ["t0"] * 3
    done = []
    for r in moved:
        dst.adopt(r, migrated_from="src")
    while dst.has_work:
        done.extend(dst.step())
    assert _outputs(done) == want
    assert dst.audit_adapters() == []


# ---------------------------------------------------------------------------
# router: adapter affinity in the placement key
# ---------------------------------------------------------------------------

def test_router_affinity_key_includes_adapter():
    from mxnet_tpu.serving import ServingRouter
    net, _ = _tiny()
    engines = [_engine(net) for _ in range(3)]
    router = ServingRouter(engines, require_warm=False)
    prompt = list(range(1, 9))
    cands = list(range(3))
    base = router._affinity_idx(Request(prompt, 2, request_id="n"),
                                cands)
    picks = {router._affinity_idx(
        Request(prompt, 2, request_id=f"a{i}", adapter_id=f"ad{i}"),
        cands) for i in range(8)}
    # deterministic per adapter...
    again = router._affinity_idx(
        Request(prompt, 2, request_id="x", adapter_id="ad0"), cands)
    assert again == router._affinity_idx(
        Request(prompt, 2, request_id="y", adapter_id="ad0"), cands)
    # ...and the adapter id actually moves placement for some adapters
    assert len(picks | {base}) > 1
    # null adapter spellings hash exactly like the pre-PR key
    assert router._affinity_idx(
        Request(prompt, 2, request_id="z", adapter_id=0), cands) == base
