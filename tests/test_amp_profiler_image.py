"""mx.amp / mx.profiler / mx.image tests (parity: tests/python/unittest/
test_amp.py, test_profiler.py, test_image.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


# ---------------------------------------------------------------------------
# amp
# ---------------------------------------------------------------------------

def _tiny_net():
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.add(nn.BatchNorm(in_channels=8))
    net.add(nn.Dense(2, in_units=8))
    net.initialize()
    return net


def test_amp_init_and_convert_model():
    mx.amp.init(target_dtype="bfloat16")
    net = _tiny_net()
    mx.amp.convert_model(net)
    params = net.collect_params()
    dense_w = [p for k, p in params.items() if k.endswith("weight")
               and p.shape is not None and len(p.shape) == 2]
    assert all(str(p.data().dtype) == "bfloat16" for p in dense_w)
    # norm params stay f32 (the FP32_FUNCS layer list)
    bn_gamma = [p for k, p in params.items() if "gamma" in k]
    assert all(str(p.data().dtype) == "float32" for p in bn_gamma)


def test_amp_fp16_loss_scaling_trains_and_handles_overflow():
    from mxnet_tpu.gluon import Trainer, nn

    mx.amp.init(target_dtype="float16")
    net = nn.Dense(1, in_units=2)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)
    mx.amp.init_trainer(tr)
    scaler = tr._amp_loss_scaler
    assert scaler.loss_scale > 1.0

    x = mx.nd.array([[1.0, 2.0]])
    w0 = net.weight.data().asnumpy().copy()
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
        with mx.amp.scale_loss(loss, tr) as scaled:
            mx.autograd.backward(scaled)
    tr.step(1)
    w1 = net.weight.data().asnumpy()
    assert not np.allclose(w0, w1)  # a real (unscaled) update happened
    # grad magnitude must be the UNSCALED one: compare vs no-amp reference
    net2 = nn.Dense(1, in_units=2)
    net2.initialize()
    net2.weight.set_data(mx.nd.array(w0))
    net2.bias.set_data(mx.nd.zeros((1,)))
    tr2 = Trainer(net2.collect_params(), "sgd", {"learning_rate": 0.1},
                  kvstore=None)
    with mx.autograd.record():
        loss2 = (net2(x) ** 2).sum()
    loss2.backward()
    tr2.step(1)
    np.testing.assert_allclose(w1, net2.weight.data().asnumpy(), rtol=1e-3)

    # overflow: inf grads → update skipped, scale halved
    before = scaler.loss_scale
    wpre = net.weight.data().asnumpy().copy()
    with mx.autograd.record():
        loss = (net(x) * np.inf).sum()
        with mx.amp.scale_loss(loss, tr) as scaled:
            mx.autograd.backward(scaled)
    tr.step(1)
    assert scaler.loss_scale == before / 2
    np.testing.assert_array_equal(net.weight.data().asnumpy(), wpre)


def test_amp_requires_init_trainer():
    from mxnet_tpu.gluon import Trainer, nn

    mx.amp.init()
    net = nn.Dense(1, in_units=1)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", kvstore=None)
    with pytest.raises(MXNetError, match="init_trainer"):
        with mx.amp.scale_loss(mx.nd.array([1.0]), tr):
            pass


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------

def test_profiler_aggregate_stats(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "prof.json"),
                           aggregate_stats=True)
    mx.profiler.set_state("run")
    try:
        a = mx.nd.array([1.0, 2.0])
        with mx.profiler.scope("my_region"):
            (a * 2 + 1).sum().asscalar()
    finally:
        mx.profiler.set_state("stop")
    table = mx.profiler.dumps()
    assert "Profile Statistics" in table
    assert "scope::my_region" in table
    stats = mx.profiler.dumps(format="json")
    import json
    parsed = json.loads(stats)
    assert any(k != "scope::my_region" for k in parsed)  # op rows recorded
    path = mx.profiler.dump()
    trace = json.loads(open(path).read())
    assert trace["traceEvents"], "chrome trace must contain events"
    assert mx.profiler.state() == "stop"


def test_profiler_pause_resume():
    mx.profiler.set_state("run")
    try:
        mx.profiler.pause()
        mx.nd.array([1.0]).sum().asscalar()
        paused_stats = mx.profiler.dumps(format="json")
        mx.profiler.resume()
        mx.nd.array([1.0]).sum().asscalar()
    finally:
        mx.profiler.set_state("stop")
    import json
    assert json.loads(paused_stats) == {}


def test_profiler_jax_device_trace(tmp_path):
    """trace_dir engages the jax/XLA device trace (TensorBoard xplane
    output) alongside the aggregate table."""
    tb = tmp_path / "tb"
    mx.profiler.set_config(trace_dir=str(tb))
    mx.profiler.set_state("run")
    try:
        with mx.profiler.scope("traced_region"):
            mx.nd.array([1.0, 2.0]).sum().asscalar()
    finally:
        mx.profiler.set_state("stop")
        mx.profiler.set_config(trace_dir=None)
    written = list(tb.rglob("*"))
    assert any(p.is_file() for p in written), written


def test_profiler_rejects_bad_config():
    with pytest.raises(MXNetError):
        mx.profiler.set_config(bogus_key=1)
    with pytest.raises(MXNetError):
        mx.profiler.set_state("bogus")


# ---------------------------------------------------------------------------
# image
# ---------------------------------------------------------------------------

def _png_bytes(h=8, w=6):
    import cv2
    img = np.arange(h * w * 3, dtype=np.uint8).reshape(h, w, 3)
    ok, buf = cv2.imencode(".png", img)
    assert ok
    return img, bytes(buf.tobytes())


def test_imdecode_imresize_roundtrip():
    bgr, buf = _png_bytes()
    img = mx.image.imdecode(buf)
    assert img.shape == (8, 6, 3)
    # reference semantics: decode is RGB (cv2 file order is BGR)
    np.testing.assert_array_equal(img.asnumpy(), bgr[..., ::-1])
    small = mx.image.imresize(img, 3, 4)
    assert small.shape == (4, 3, 3)
    short = mx.image.resize_short(img, 4)
    assert min(short.shape[:2]) == 4


def test_imread_and_crops(tmp_path):
    import cv2
    img = np.random.default_rng(0).integers(
        0, 255, (16, 12, 3)).astype(np.uint8)
    path = str(tmp_path / "t.png")
    cv2.imwrite(path, img)
    loaded = mx.image.imread(path)
    np.testing.assert_array_equal(loaded.asnumpy(), img[..., ::-1])
    c, rect = mx.image.center_crop(loaded, (8, 8))
    assert c.shape == (8, 8, 3) and rect == (2, 4, 8, 8)
    r, rect = mx.image.random_crop(loaded, (6, 6))
    assert r.shape == (6, 6, 3)
    f = mx.image.fixed_crop(loaded, 1, 2, 5, 6)
    np.testing.assert_array_equal(f.asnumpy(),
                                  loaded.asnumpy()[2:8, 1:6])


def test_to_tensor_normalize():
    img = mx.nd.array(np.full((4, 5, 3), 255, np.uint8), dtype="uint8")
    t = mx.image.to_tensor(img)
    assert t.shape == (3, 4, 5)
    np.testing.assert_allclose(t.asnumpy(), 1.0)
    n = mx.image.normalize(t, mean=(1.0, 1.0, 1.0), std=(2.0, 2.0, 2.0))
    np.testing.assert_allclose(n.asnumpy(), 0.0)


def test_augmenter_pipeline():
    img = mx.nd.array(np.random.default_rng(1).integers(
        0, 255, (40, 30, 3)), dtype="uint8")
    augs = mx.image.CreateAugmenter(data_shape=(3, 24, 24), resize=26,
                                    rand_crop=True, rand_mirror=True,
                                    brightness=0.1, contrast=0.1,
                                    saturation=0.1, pca_noise=0.05,
                                    mean=np.zeros(3, np.float32),
                                    std=np.ones(3, np.float32))
    out = img
    for aug in augs:
        out = aug(out)
    assert out.shape == (24, 24, 3)
    assert str(out.dtype) == "float32"
    assert augs[0].dumps()  # serializable descriptions
