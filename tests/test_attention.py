"""Attention kernels: flash (blockwise scan) vs the XLA einsum baseline.

Parity target: the 'fully-masked rows yield zeros on every path' contract
of dot_product_attention (ops/nn.py) across implementations.
"""
import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ops import attention as att
from mxnet_tpu.ops.nn import dot_product_attention


def _qkv(B=1, H=2, Tq=8, Tk=32, D=4, seed=0):
    r = np.random.default_rng(seed)
    q = jnp.asarray(r.standard_normal((B, H, Tq, D)), jnp.float32)
    k = jnp.asarray(r.standard_normal((B, H, Tk, D)), jnp.float32)
    v = jnp.asarray(r.standard_normal((B, H, Tk, D)), jnp.float32)
    return q, k, v


def test_flash_matches_xla_with_mask():
    q, k, v = _qkv()
    r = np.random.default_rng(1)
    mask = jnp.asarray(r.random((1, 1, 8, 32)) > 0.3)
    ref = dot_product_attention.raw_fn(q, k, v, mask=mask, impl="xla")
    out = att.flash_attention_data(q, k, v, mask=mask, block_k=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fully_masked_rows_are_zero():
    q, k, v = _qkv()
    mask = np.ones((1, 1, 8, 32), bool)
    mask[..., 2, :] = False          # query row 2 attends nothing
    mask[..., 5, :] = False
    mask = jnp.asarray(mask)
    out = np.asarray(att.flash_attention_data(q, k, v, mask=mask, block_k=8))
    ref = np.asarray(dot_product_attention.raw_fn(q, k, v, mask=mask,
                                                  impl="xla"))
    np.testing.assert_array_equal(out[:, :, 2, :], 0.0)
    np.testing.assert_array_equal(out[:, :, 5, :], 0.0)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_causal_matches_xla():
    q, k, v = _qkv(Tq=16, Tk=16)
    ref = dot_product_attention.raw_fn(q, k, v, causal=True, impl="xla")
    out = att.flash_attention_data(q, k, v, causal=True, block_k=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_op_accepts_ndarray_kwarg():
    q, k, v = _qkv()
    mask = jnp.asarray(np.random.default_rng(2).random((1, 1, 8, 32)) > 0.3)
    nq, nk, nv = mx.nd.array(q), mx.nd.array(k), mx.nd.array(v)
    nm = mx.nd.array(mask)
    pos = dot_product_attention(nq, nk, nv, nm)
    kw = dot_product_attention(nq, nk, nv, mask=nm)
    np.testing.assert_allclose(kw.asnumpy(), pos.asnumpy())


def test_op_ndarray_kwarg_is_taped():
    q, k, v = _qkv()
    nq, nk, nv = mx.nd.array(q), mx.nd.array(k), mx.nd.array(v)
    nm = mx.nd.array(np.ones((1, 1, 8, 32), bool))
    for p in (nq, nk, nv):
        p.attach_grad()
    with autograd.record():
        out = dot_product_attention(nq, nk, nv, mask=nm)
        loss = out.sum()
    loss.backward()
    g = nq.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
