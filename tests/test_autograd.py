"""Autograd tests (parity: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_backward():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain_rule():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y * x  # x^3 -> dz/dx = 3x^2
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [12.0])


def test_multiple_inputs():
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [3, 4])
    np.testing.assert_allclose(b.grad.asnumpy(), [1, 2])


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 2 * x
    y.backward(mx.nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [20, 200])


def test_grad_req_add():
    x = mx.nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 5 * x
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [15.0])


def test_grad_req_write_overwrites():
    x = mx.nd.array([1.0])
    x.attach_grad()  # write
    for _ in range(3):
        with autograd.record():
            y = 5 * x
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [5.0])


def test_detach():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])  # only d(y_const*x)


def test_stop_gradient_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        z = nd.stop_gradient(x * x) * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_pause_scope():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            w = x * 10  # not recorded
        z = y + w.detach()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_is_training_scopes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_autograd_grad_api():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    (g,) = autograd.grad([y], [x])
    np.testing.assert_allclose(g.asnumpy(), [6.0])
    # .grad buffer untouched by grad()
    np.testing.assert_allclose(x.grad.asnumpy(), [0.0])


def test_backward_through_shapes():
    x = mx.nd.array(np.ones((2, 3), np.float32))
    x.attach_grad()
    with autograd.record():
        y = x.reshape((3, 2)).transpose().sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones((2, 3)))


def test_backward_through_concat_split():
    x = mx.nd.array([[1.0, 2.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.concat(x, x * 2, dim=0)
        z = y.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[3.0, 3.0]])


def test_retain_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), g1)


def test_double_backward_raises_without_retain():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    y.backward()
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_mutation_during_record():
    # in-place update on a recorded array routes grads to the new value
    x = mx.nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1
        z = y * 3
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_custom_function():
    class MyMul(autograd.Function):
        def forward(self, a, b):
            self.save_for_backward(a, b)
            return a * b

        def backward(self, dout):
            a, b = self.saved_tensors
            return dout * b, dout * a

    a = mx.nd.array([2.0])
    b = mx.nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    f = MyMul()
    with autograd.record():
        c = f(a, b)
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [3.0])
    np.testing.assert_allclose(b.grad.asnumpy(), [2.0])


def test_backward_nonscalar_default_ones():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward()  # ones head grad, MXNet convention
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0])


def test_diamond_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 2
        b = x * 3
        z = a * b  # 6x^2 -> dz = 12x = 24
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [24.0])


def test_mark_variables():
    x = mx.nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])
