"""BLEU metric tests (oracle: hand-computed corpus BLEU)."""
import math

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.metric import BLEU


def test_perfect_match_is_one():
    m = BLEU()
    m.update([[1, 2, 3, 4, 5]], [[1, 2, 3, 4, 5]])
    name, v = m.get()
    assert name == "bleu"
    np.testing.assert_allclose(v, 1.0)


def test_known_value():
    # hyp: [1,2,3,4], ref: [1,2,3,5]
    # 1-gram 3/4; 2-gram 2/3; 3-gram 1/2; 4-gram 0 → BLEU 0 (no smoothing)
    m = BLEU()
    m.update([[1, 2, 3, 5]], [[1, 2, 3, 4]])
    assert m.get()[1] == 0.0
    # with max_n=3: exp(mean(log(3/4), log(2/3), log(1/2))), bp=1
    m = BLEU(max_n=3)
    m.update([[1, 2, 3, 5]], [[1, 2, 3, 4]])
    want = math.exp((math.log(3 / 4) + math.log(2 / 3) +
                     math.log(1 / 2)) / 3)
    np.testing.assert_allclose(m.get()[1], want, rtol=1e-9)


def test_brevity_penalty_and_corpus_accumulation():
    m = BLEU(max_n=1)
    m.update([[1, 2, 3, 4]], [[1, 2]])  # short hyp: bp = exp(1-4/2)
    np.testing.assert_allclose(m.get()[1], math.exp(1 - 2.0), rtol=1e-9)
    # second sentence accumulates corpus-level (not averaged per-sentence)
    m.update([[5, 6]], [[5, 6]])
    # matches 4/4, hyp_len 4, ref_len 6 → bp = exp(1-6/4)
    np.testing.assert_allclose(m.get()[1], math.exp(1 - 6 / 4), rtol=1e-9)


def test_padded_batch_and_ignore():
    # stripped sentences are 3 and 2 tokens — use max_n=2 so n-gram
    # totals are nonzero
    m = BLEU(max_n=2, ignore=(0, 3))  # PAD=0, EOS=3
    labels = np.array([[7, 8, 9, 3, 0], [4, 5, 3, 0, 0]])
    preds = np.array([[7, 8, 9, 3, 0], [4, 5, 3, 0, 0]])
    m.update(labels, preds)
    np.testing.assert_allclose(m.get()[1], 1.0)


def test_list_of_sequences_batch():
    """Every sentence in a list batch must score (review regression:
    only the first was counted)."""
    m = BLEU(max_n=1)
    m.update([[1, 2, 3, 4], [5, 6, 7, 8]], [[1, 2, 3, 4], [5, 6, 9, 9]])
    assert m.num_inst == 2
    np.testing.assert_allclose(m.get()[1], 6 / 8)
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="references"):
        m.update([[1, 2]], [[1, 2], [3, 4]])


def test_batch_array_in_list_and_registry():
    """EvalMetric/update_dict convention: a (B, T) array wrapped in a
    list must score B sentences, not one flattened blob; and BLEU must
    be constructible from the string registry."""
    m = mx.metric.create("bleu", max_n=1)
    batch = np.array([[1, 2, 3, 4], [5, 6, 7, 8]])
    m.update([batch], [batch])
    assert m.num_inst == 2
    np.testing.assert_allclose(m.get()[1], 1.0)


def test_reset_and_nan_when_empty():
    m = BLEU()
    assert math.isnan(m.get()[1])
    m.update([[1, 2, 3, 4]], [[1, 2, 3, 4]])
    m.reset()
    assert math.isnan(m.get()[1])
