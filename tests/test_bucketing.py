"""TrainStep shape-keyed program cache + padded-bucket utilities
(parity: BucketingModule, SURVEY.md §3.3 / §7.3.2; VERDICT r3 weak #3)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt, parallel as par
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import BucketingScheme, loss as gloss, nn


def test_bucketing_scheme():
    s = BucketingScheme([16, 32, 64])
    assert s.bucket_for(1) == 16
    assert s.bucket_for(16) == 16
    assert s.bucket_for(17) == 32
    with pytest.raises(MXNetError, match="exceeds"):
        s.bucket_for(65)
    ids = mx.nd.array(np.ones((2, 20)), dtype="int32")
    vl = mx.nd.array(np.full((2,), 20), dtype="int32")
    (pids, pvl), bucket, realized = s.pad_batch(ids, vl, axis=1)
    assert bucket == 32 and realized == 20
    assert pids.shape == (2, 32)
    assert pvl.shape == (2,)  # non-seq array passed through
    np.testing.assert_array_equal(pids.asnumpy()[:, 20:], 0)


def _mk_step():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False, in_units=4))
    net.add(nn.Dense(3, flatten=False, in_units=8))
    mx.rng.seed(0)
    net.initialize(mx.init.Normal(0.1))
    return par.TrainStep(net, gloss.L2Loss(), opt.SGD(learning_rate=0.01),
                         mesh=None)


def test_trainstep_program_per_bucket():
    """Two batch shapes coexist: each gets its own compiled program, the
    parameters are shared, and compiled_cost_analysis reports the right
    program per signature (r1-r3 carryover: the cache was keyed on
    nothing and silently reused the first arity/shapes)."""
    step = _mk_step()
    r = np.random.default_rng(0)
    x16 = mx.nd.array(r.standard_normal((2, 16, 4)), dtype="float32")
    y16 = mx.nd.array(r.standard_normal((2, 16, 3)), dtype="float32")
    x32 = mx.nd.array(r.standard_normal((2, 32, 4)), dtype="float32")
    y32 = mx.nd.array(r.standard_normal((2, 32, 3)), dtype="float32")

    l1 = float(step(x16, y16).asscalar())
    sig16 = step._last_sig
    c16 = step.compiled_cost_analysis()
    l2 = float(step(x32, y32).asscalar())
    sig32 = step._last_sig
    c32 = step.compiled_cost_analysis()
    assert len(step._programs) == 2
    assert sig16 != sig32
    # flops scale with the doubled sequence dim; verify per-sig reporting
    if c16 and c32 and c16.get("flops") and c32.get("flops"):
        assert c32["flops"] > 1.5 * c16["flops"]
        again16 = step.compiled_cost_analysis(sig16)
        assert again16["flops"] == c16["flops"]
    # alternating shapes keeps training (shared params, no rebuild)
    l3 = float(step(x16, y16).asscalar())
    assert len(step._programs) == 2
    assert np.isfinite([l1, l2, l3]).all()
    assert l3 < l1  # parameters advanced across both programs


def test_trainstep_bucketed_bert_style():
    """End-to-end: raw lengths 9/20/33 through a 3-bucket scheme compile
    exactly 3 programs, not 3-per-unique-length on repeats."""
    step = _mk_step()
    scheme = BucketingScheme([16, 32, 64])
    r = np.random.default_rng(1)
    seen = set()
    for length in (9, 20, 33, 12, 30, 60):
        x = mx.nd.array(r.standard_normal((2, length, 4)), dtype="float32")
        y = mx.nd.array(r.standard_normal((2, length, 3)), dtype="float32")
        (xp, yp), bucket, _ = scheme.pad_batch(x, y, axis=1)
        # labels share the seq axis here, so pad them too
        yp = mx.gluon.bucketing.pad_to_bucket(y, bucket, axis=1)
        loss = step(xp, yp)
        assert np.isfinite(float(loss.asscalar()))
        seen.add(bucket)
    assert len(step._programs) == len(seen) == 3
