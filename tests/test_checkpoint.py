"""Checkpoint/resume tests: async sharded save + RESUME-EXACT restore
(SURVEY.md §5.4 — closing the reference's no-cursor/no-RNG gap)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt, parallel as par
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import TrainCheckpoint
from mxnet_tpu.gluon import loss as gloss, nn


def _mk_step(mesh=None, dropout=0.1):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8))
    net.add(nn.Dropout(dropout))  # RNG state must survive the resume
    net.add(nn.Dense(4, in_units=16))
    mx.rng.seed(42)
    net.initialize(mx.init.Normal(0.1))
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(),
                         opt.Adam(learning_rate=1e-2), mesh=mesh)
    return net, step


def _batch(seed=0):
    r = np.random.default_rng(seed)
    x = mx.nd.array(r.standard_normal((8, 8)), dtype="float32")
    y = mx.nd.array(r.integers(0, 4, (8,)), dtype="int32")
    return x, y


def test_resume_exact(tmp_path):
    """N steps → snapshot → M more steps must equal the uninterrupted
    N+M run bit-for-bit (params, opt state, step count, RNG)."""
    x, y = _batch()

    # uninterrupted reference run
    mx.rng.seed(7)
    _, step_ref = _mk_step()
    ref_losses = [float(step_ref(x, y).asscalar()) for _ in range(8)]

    # interrupted run: 4 steps, save, run 1 garbage step, restore, resume
    mx.rng.seed(7)
    _, step_a = _mk_step()
    for _ in range(4):
        step_a(x, y)
    ckpt = TrainCheckpoint(str(tmp_path / "ckpt"))
    ckpt.save(4, step_a, data_cursor={"epoch": 2, "batch": 17}, wait=True)
    step_a(x, y)  # diverge state after the snapshot
    cursor = ckpt.restore(step_a)
    assert cursor == {"epoch": 2, "batch": 17}
    assert step_a.step_count == 4
    resumed = [float(step_a(x, y).asscalar()) for _ in range(4)]
    np.testing.assert_allclose(resumed, ref_losses[4:], rtol=1e-6,
                               atol=1e-7)
    ckpt.close()


def test_async_save_multiple_and_retention(tmp_path):
    x, y = _batch(1)
    _, step = _mk_step(dropout=0.0)
    ckpt = TrainCheckpoint(str(tmp_path / "c"), max_to_keep=2)
    for s in range(1, 5):
        step(x, y)
        ckpt.save(s, step)  # async: loop continues immediately
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 4
    assert ckpt.all_steps() == [3, 4]  # retention pruned to max_to_keep
    ckpt.close()


def test_restore_missing_raises(tmp_path):
    _, step = _mk_step(dropout=0.0)
    ckpt = TrainCheckpoint(str(tmp_path / "empty"))
    with pytest.raises(MXNetError, match="no checkpoint"):
        ckpt.restore(step)
    ckpt.close()


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs virtual mesh")
def test_sharded_save_restore_keeps_shardings(tmp_path):
    """fsdp-sharded TrainStep state round-trips with shardings intact and
    training numerics preserved."""
    mesh = par.make_mesh(dp=2, fsdp=2, devices=jax.devices()[:4])
    x, y = _batch(2)

    mx.rng.seed(3)
    net, step = _mk_step()
    par.apply_sharding_rules(net, par.fsdp_rules(min_size=8))
    step = par.TrainStep(net, gloss.SoftmaxCrossEntropyLoss(),
                         opt.Adam(learning_rate=1e-2), mesh=mesh,
                         batch_specs=(par.PartitionSpec("dp"),
                                      par.PartitionSpec("dp")))
    for _ in range(2):
        step(x, y)
    before = [np.asarray(a) for a in step._param_arrays]
    shardings = [a.sharding for a in step._param_arrays]
    ckpt = TrainCheckpoint(str(tmp_path / "s"))
    ckpt.save(2, step, wait=True)
    step(x, y)  # diverge
    ckpt.restore(step)
    for a, b, s in zip(step._param_arrays, before, shardings):
        np.testing.assert_array_equal(np.asarray(a), b)
        assert a.sharding == s
    loss = float(step(x, y).asscalar())
    assert np.isfinite(loss)
    ckpt.close()
