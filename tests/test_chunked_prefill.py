"""ISSUE 11: the unified chunked-prefill dispatch.

Two layers of oracle. The span kernel (per-slot query counts) is
checked against the dense XLA reference over mixed batches — decode,
verify, prefill-chunk, and idle rows riding ONE dispatch — plus the
q_counts edge cases and bf16. The engine is checked against a GOLDEN
token capture (tests/data/chunked_prefill_golden.json) recorded from
the pre-unification bucketed engine on mixed greedy/sampled traffic
across the plain, prefix-cache (incl. fully-cached CoW), speculative,
and adapter paths: the unified engine must reproduce every stream
bit-for-bit, at ANY chunk_tokens setting.

Plus the chunked-admission fairness bar: a long prompt streaming in
chunks must not stall other slots' decode — every running request
keeps emitting one token per dispatch while the long prefill is in
flight.
"""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models import GPT2Config, GPT2ForCausalLM
from mxnet_tpu.ops import pallas_attention as pa
from mxnet_tpu.serving import Request, ServingEngine
from mxnet_tpu.serving.adapters import AdapterPool

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "chunked_prefill_golden.json")


def _tiny(vocab=97, layers=2, units=32, heads=2, max_len=64, seed=3):
    cfg = GPT2Config(vocab_size=vocab, units=units, num_layers=layers,
                     num_heads=heads, max_length=max_len, dropout=0.0,
                     attention_dropout=0.0)
    net = GPT2ForCausalLM(cfg)
    mx.rng.seed(seed)
    net.initialize(mx.init.Normal(0.05))
    return net, cfg


# ---------------------------------------------------------------------------
# span kernel vs the dense oracle
# ---------------------------------------------------------------------------

def _pool(B=5, H=2, D=16, S=8, P=4, Sq=8, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    N = B * P
    q = jnp.asarray(rng.standard_normal((B, Sq, H, D)), dtype)
    kp = jnp.asarray(rng.standard_normal((N, S, H, D)), dtype)
    vp = jnp.asarray(rng.standard_normal((N, S, H, D)), dtype)
    table = jnp.asarray(rng.permutation(N).reshape(B, P), jnp.int32)
    return q, kp, vp, table


def test_span_kernel_mixed_batch_one_dispatch():
    """One dispatch carrying every work kind at once: decode (1),
    verify (4), full-width prefill chunk (Sq), idle (0), and a
    non-page-aligned chunk tail (5) — kernel vs dense oracle, and dead
    rows emit EXACT zeros."""
    q, kp, vp, table = _pool()
    L = jnp.asarray([9, 17, 1, 30, 12], jnp.int32)
    qc = jnp.asarray([1, 4, 8, 0, 5], jnp.int32)
    ref = pa._ragged_span_reference(q, kp, vp, table, L, qc,
                                    1.0 / np.sqrt(16))
    out = pa.ragged_span_attention(q, kp, vp, table, L, q_counts=qc,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    dead = np.arange(8)[None, :] >= np.asarray(qc)[:, None]
    assert (np.asarray(out)[dead] == 0).all()
    assert (np.asarray(ref)[dead] == 0).all()


@pytest.mark.parametrize("qc", [[0, 0, 0, 0, 0], [1, 1, 1, 1, 1],
                                [8, 8, 8, 8, 8], [3, 7, 2, 6, 1]])
def test_span_kernel_q_counts_edges(qc):
    """q_counts edges: all-idle, all-decode, all-full, and ragged
    non-aligned tails."""
    q, kp, vp, table = _pool(seed=1)
    L = jnp.asarray([5, 1, 24, 13, 8], jnp.int32)
    qcj = jnp.asarray(qc, jnp.int32)
    ref = pa._ragged_span_reference(q, kp, vp, table, L, qcj,
                                    1.0 / np.sqrt(16))
    out = pa.ragged_span_attention(q, kp, vp, table, L, q_counts=qcj,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_span_kernel_full_counts_match_mq_kernel():
    """q_counts = Sq everywhere IS the multi-query verify kernel —
    same mask, same online-softmax walk, bitwise."""
    q, kp, vp, table = _pool(seed=2)
    L = jnp.asarray([4, 11, 27, 2, 19], jnp.int32)
    full = pa.ragged_span_attention(
        q, kp, vp, table, L, q_counts=jnp.full((5,), 8, jnp.int32),
        interpret=True)
    mq = pa.ragged_mq_decode_attention(q, kp, vp, table, L,
                                       interpret=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(mq))


def test_span_kernel_bf16_tolerance():
    q, kp, vp, table = _pool(dtype=jnp.bfloat16, seed=3)
    L = jnp.asarray([7, 20, 13, 3, 26], jnp.int32)
    qc = jnp.asarray([2, 8, 0, 1, 6], jnp.int32)
    ref = pa._ragged_span_reference(
        q.astype(jnp.float32), kp.astype(jnp.float32),
        vp.astype(jnp.float32), table, L, qc, 1.0 / np.sqrt(16))
    out = pa.ragged_span_attention(q, kp, vp, table, L, q_counts=qc,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_span_kernel_rows_equal_isolated_chunks():
    """Chunk-size invariance at the kernel level: rows [0, c) computed
    in one call with q_counts=c must equal the same rows computed as
    two smaller spans (the second at lengths + c1) — the algebra the
    engine's bit-identity across chunk_tokens settings rests on."""
    q, kp, vp, table = _pool(seed=4)
    L = jnp.asarray([4, 9, 1, 15, 22], jnp.int32)
    whole = pa.ragged_span_attention(
        q, kp, vp, table, L, q_counts=jnp.full((5,), 6, jnp.int32),
        interpret=True)
    first = pa.ragged_span_attention(
        q[:, :4], kp, vp, table, L, q_counts=jnp.full((5,), 4, jnp.int32),
        interpret=True)
    second = pa.ragged_span_attention(
        q[:, 4:6], kp, vp, table, L + 4,
        q_counts=jnp.full((5,), 2, jnp.int32), interpret=True)
    np.testing.assert_allclose(np.asarray(whole[:, :4]),
                               np.asarray(first), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(whole[:, 4:6]),
                               np.asarray(second), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# engine bit-identity vs the pre-unification golden capture
# ---------------------------------------------------------------------------
# The workloads below are byte-for-byte the ones the golden file was
# captured with on the bucketed (pre-ISSUE 11) engine at its last
# commit — same model seed, same request streams, same sampling
# settings. Do not change them without re-deriving the golden file.

def _plain_reqs(cfg, rng, tag, n=6, sampled_every=2):
    out = []
    for i in range(n):
        plen = int(rng.integers(1, 30))
        p = rng.integers(0, cfg.vocab_size, plen).tolist()
        out.append(Request(p, int(rng.integers(2, 10)),
                           do_sample=(i % sampled_every == 0),
                           temperature=0.8, top_k=20, top_p=0.95,
                           seed=1000 + i, request_id=f"{tag}-{i}"))
    return out


@pytest.fixture(scope="module")
def golden():
    return json.load(open(GOLDEN))


def _serve(eng, rs):
    eng.serve(rs)
    return {r.id: r.output_tokens for r in rs}


@pytest.mark.parametrize("chunk_tokens", [None, 4, 64])
def test_engine_plain_bit_identity(golden, chunk_tokens):
    """Mixed greedy/sampled traffic: the unified engine reproduces the
    bucketed engine's streams bit-for-bit — and the chunk size is
    invisible in the tokens (1-token-at-a-time prefill, page-sized,
    and whole-prompt chunks all emit the same streams)."""
    net, cfg = _tiny()
    rng = np.random.default_rng(42)
    eng = ServingEngine(net, num_slots=3, max_length=64, page_size=8,
                        attn_impl="xla", chunk_tokens=chunk_tokens)
    assert _serve(eng, _plain_reqs(cfg, rng, "plain")) == golden["plain"]


def test_engine_prefix_cache_bit_identity(golden):
    """Shared-prefix traffic (incl. a fully-cached prompt -> CoW
    resume): cache hits seed the chunk cursor past the shared pages
    and the emitted streams stay bit-identical."""
    net, cfg = _tiny()
    rng = np.random.default_rng(42)
    _plain_reqs(cfg, rng, "burn")           # advance rng as captured
    eng = ServingEngine(net, num_slots=2, max_length=64, page_size=8,
                        attn_impl="xla", prefix_cache=True)
    base = rng.integers(0, cfg.vocab_size, 16).tolist()
    prs = [Request(base + rng.integers(0, cfg.vocab_size,
                                       int(rng.integers(0, 6))).tolist(),
                   6, do_sample=(i % 2 == 0), temperature=0.9, top_k=15,
                   seed=2000 + i, request_id=f"px-{i}")
           for i in range(5)]
    prs.append(Request(base, 4, request_id="px-full"))  # fully cached
    assert _serve(eng, prs) == golden["prefix"]


def test_engine_speculative_bit_identity(golden):
    """Speculative engines dispatch the SAME unified program with
    n_draft=0 during prefill (and in degraded mode) — verify rows and
    the final-chunk first-token sample stay bit-identical."""
    net, cfg = _tiny()
    eng = ServingEngine(net, num_slots=2, max_length=64, page_size=8,
                        attn_impl="xla", speculative=True, spec_tokens=4)
    pat = [5, 6, 7, 8]
    srs = [Request(pat * 3, 8, do_sample=(i == 0), temperature=0.7,
                   top_k=12, seed=3000 + i, request_id=f"sp-{i}")
           for i in range(4)]
    assert _serve(eng, srs) == golden["spec"]


def test_engine_adapter_bit_identity(golden):
    net, cfg = _tiny()
    rng = np.random.default_rng(42)
    _plain_reqs(cfg, rng, "burn")
    rng.integers(0, cfg.vocab_size, 16)     # prefix-base draw
    for i in range(5):
        rng.integers(0, cfg.vocab_size, int(rng.integers(0, 6)))
    pool = AdapterPool(cfg, slots=2, max_rank=4)
    wrng = np.random.default_rng(7)
    r = 2
    pool.register("ad1", {
        "A": wrng.standard_normal(
            (4, cfg.num_layers, cfg.units, r)).astype(np.float32) * 0.05,
        "B": wrng.standard_normal(
            (4, cfg.num_layers, r, cfg.units)).astype(np.float32) * 0.05,
        "alpha": 4.0, "rank": r})
    eng = ServingEngine(net, num_slots=2, max_length=64, page_size=8,
                        attn_impl="xla", adapter_pool=pool)
    ars = [Request(rng.integers(0, cfg.vocab_size, 7).tolist(), 5,
                   do_sample=(i == 1), temperature=0.8, top_k=10,
                   seed=4000 + i, request_id=f"ad-{i}",
                   adapter_id="ad1" if i % 2 else None)
           for i in range(4)]
    assert _serve(eng, ars) == golden["adapter"]


# ---------------------------------------------------------------------------
# chunked-admission fairness: long prefills must not starve decoders
# ---------------------------------------------------------------------------

def test_long_prefill_does_not_starve_decoders():
    """The starvation bar: while a long prompt streams its chunks, the
    already-running slots keep emitting EXACTLY one token per dispatch
    — chunked prefill rides along, it never displaces decode rows.
    (The bucketed engine froze every decoder for the whole monolithic
    prefill dispatch.)"""
    net, cfg = _tiny()
    rng = np.random.default_rng(9)
    eng = ServingEngine(net, num_slots=3, max_length=64, page_size=8,
                        attn_impl="xla", chunk_tokens=8)
    short = [Request(rng.integers(0, cfg.vocab_size, 3).tolist(), 20,
                     request_id=f"s{i}") for i in range(2)]
    for r in short:
        eng.submit(r)
    eng.step()                        # both shorts prefill (one chunk)
    eng.step()                        # ...and start decoding
    counts = {r.id: len(r.output_tokens) for r in short}
    assert all(c >= 1 for c in counts.values())
    long = Request(rng.integers(0, cfg.vocab_size, 48).tolist(), 2,
                   request_id="long")
    eng.submit(long)
    # 48 tokens / chunk_tokens=8 -> 6 chunk dispatches before the
    # long prompt's first token; the shorts advance 1/dispatch anyway
    steps_to_first = 0
    while not long.output_tokens:
        eng.step()
        steps_to_first += 1
        for r in short:
            if r.status == "running":
                got = len(r.output_tokens) - counts[r.id]
                assert got == 1, \
                    f"{r.id} got {got} tokens while long prefill ran"
                counts[r.id] = len(r.output_tokens)
    assert steps_to_first == 48 // 8
    assert eng.stats["prefill_chunks"] >= 6 + 2
    assert eng.stats["prefill_pending"] == 0


def test_prefill_chunk_budget_round_robins_concurrent_prompts():
    """Two long prompts under a budget that covers only ONE chunk per
    dispatch: the rotating cursor alternates slots, both finish, and
    no dispatch exceeds the budget."""
    net, cfg = _tiny()
    rng = np.random.default_rng(10)
    eng = ServingEngine(net, num_slots=2, max_length=64, page_size=8,
                        attn_impl="xla", chunk_tokens=8,
                        prefill_chunk_budget=8)
    longs = [Request(rng.integers(0, cfg.vocab_size, 24).tolist(), 2,
                     request_id=f"L{i}") for i in range(2)]
    for r in longs:
        eng.submit(r)
    steps = 0
    pending_seen = []
    while eng.has_work:
        eng.step()
        steps += 1
        pending_seen.append(eng.stats["prefill_pending"])
        assert steps < 50
    # 2 prompts x 3 chunks = 6 chunk dispatches minimum at 1/dispatch
    assert eng.stats["prefill_chunks"] == 6
    for r in longs:
        assert r.status == "finished"
        assert len(r.output_tokens) == 2
    # the queue drained monotonically 8 tokens a step while prefilling
    assert pending_seen[0] == 48 - 8
    assert pending_seen[1] == 48 - 16


def test_prefill_pending_gauge_and_ttft_histogram():
    """The chunk-queue gauge rises at admission and drains to zero;
    the per-prompt-length TTFT histogram lands the request in its
    power-of-two bucket."""
    from mxnet_tpu import telemetry

    net, cfg = _tiny()
    rng = np.random.default_rng(11)
    eng = ServingEngine(net, num_slots=1, max_length=64, page_size=8,
                        attn_impl="xla", chunk_tokens=8)
    eng.serve([Request(rng.integers(0, cfg.vocab_size, 20).tolist(), 2,
                       request_id="t")])
    assert eng.stats["prefill_pending"] == 0
    assert eng.stats["prefill_chunks"] == 3      # ceil(20 / 8)
    h = telemetry.get("serving_ttft_by_prompt_seconds")
    child = h.labels(str(eng._eid), "le32", "cold")   # 16 < 20 <= 32
    assert child.count == 1
