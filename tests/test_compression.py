"""2-bit gradient compression tests (parity:
src/kvstore/gradient_compression.cc semantics)."""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gradient_compression import TwoBitCompressor


def test_quantize_roundtrip_and_wire_size():
    c = TwoBitCompressor(threshold=0.5)
    g = jnp.asarray([0.7, -0.9, 0.1, -0.2] * 8, jnp.float32)
    packed = c.compress("k", g)
    assert packed.dtype == jnp.uint32
    assert packed.size == 2  # 32 values → 2 uint32 words (16x smaller)
    assert c.wire_bytes(g.shape) == 8
    deq = c.decompress(packed, g.shape)
    np.testing.assert_array_equal(
        np.asarray(deq), np.asarray([0.5, -0.5, 0.0, 0.0] * 8))


def test_error_feedback_transmits_small_gradients():
    """A gradient below threshold must accumulate in the residual and
    eventually transmit (error-feedback contract)."""
    c = TwoBitCompressor(threshold=1.0)
    g = jnp.full((16,), 0.3, jnp.float32)
    sent = np.zeros(16, np.float32)
    for _ in range(10):
        packed = c.compress("w", g)
        sent += np.asarray(c.decompress(packed, g.shape))
    # 10 steps x 0.3 = 3.0 total signal; transmitted total must track it
    np.testing.assert_allclose(sent, 3.0, atol=1.0)


def test_compressor_validates():
    with pytest.raises(MXNetError):
        TwoBitCompressor(threshold=0.0)
    store = mx.kv.create("local")
    with pytest.raises(MXNetError, match="2bit"):
        store.set_gradient_compression({"type": "1bit"})
    with pytest.warns(UserWarning, match="single-process"):
        store.set_gradient_compression({"type": "2bit", "threshold": 0.5})


def test_odd_sizes_pad_correctly():
    c = TwoBitCompressor(threshold=0.25)
    g = jnp.asarray(np.linspace(-1, 1, 37), jnp.float32)
    packed = c.compress("k", g)
    deq = np.asarray(c.decompress(packed, g.shape))
    want = np.where(np.linspace(-1, 1, 37) >= 0.25, 0.25,
                    np.where(np.linspace(-1, 1, 37) <= -0.25, -0.25, 0.0))
    np.testing.assert_allclose(deq, want)
