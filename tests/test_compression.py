"""Gradient compression tests: the reference's 2-bit threshold
quantizer (parity: src/kvstore/gradient_compression.cc) and the
EQuARX-style blockwise int8 compressor (ISSUE 19), plus the wire
contract both share: `compress(...).nbytes == wire_bytes(shape)` and
the kvstore allreduce meters exactly wire_bytes — compressed bytes on
the wire, never the logical gradient size."""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gradient_compression import (Int8BlockCompressor,
                                            TwoBitCompressor)


def test_quantize_roundtrip_and_wire_size():
    c = TwoBitCompressor(threshold=0.5)
    g = jnp.asarray([0.7, -0.9, 0.1, -0.2] * 8, jnp.float32)
    packed = c.compress("k", g)
    assert packed.dtype == jnp.uint32
    assert packed.size == 2  # 32 values → 2 uint32 words (16x smaller)
    assert c.wire_bytes(g.shape) == 8
    deq = c.decompress(packed, g.shape)
    np.testing.assert_array_equal(
        np.asarray(deq), np.asarray([0.5, -0.5, 0.0, 0.0] * 8))


def test_error_feedback_transmits_small_gradients():
    """A gradient below threshold must accumulate in the residual and
    eventually transmit (error-feedback contract)."""
    c = TwoBitCompressor(threshold=1.0)
    g = jnp.full((16,), 0.3, jnp.float32)
    sent = np.zeros(16, np.float32)
    for _ in range(10):
        packed = c.compress("w", g)
        sent += np.asarray(c.decompress(packed, g.shape))
    # 10 steps x 0.3 = 3.0 total signal; transmitted total must track it
    np.testing.assert_allclose(sent, 3.0, atol=1.0)


def test_compressor_validates():
    with pytest.raises(MXNetError):
        TwoBitCompressor(threshold=0.0)
    store = mx.kv.create("local")
    with pytest.raises(MXNetError, match="2bit"):
        store.set_gradient_compression({"type": "1bit"})
    with pytest.warns(UserWarning, match="single-process"):
        store.set_gradient_compression({"type": "2bit", "threshold": 0.5})


def test_odd_sizes_pad_correctly():
    c = TwoBitCompressor(threshold=0.25)
    g = jnp.asarray(np.linspace(-1, 1, 37), jnp.float32)
    packed = c.compress("k", g)
    deq = np.asarray(c.decompress(packed, g.shape))
    want = np.where(np.linspace(-1, 1, 37) >= 0.25, 0.25,
                    np.where(np.linspace(-1, 1, 37) <= -0.25, -0.25, 0.0))
    np.testing.assert_allclose(deq, want)


# ---------------------------------------------------------------------------
# EQuARX-style blockwise int8 (ISSUE 19)
# ---------------------------------------------------------------------------

def test_int8_roundtrip_within_block_scale_bound():
    """Tolerance oracle: every dequantized value is within half the
    owning block's quantization step of the input (plus residual=0 on
    the first call), and the payload is one uint8 array."""
    c = Int8BlockCompressor(block=32)
    g = np.random.default_rng(0).standard_normal(100).astype(np.float32)
    payload = c.compress("k", jnp.asarray(g))
    assert payload.dtype == jnp.uint8
    assert int(payload.nbytes) == c.wire_bytes(g.shape)
    deq = np.asarray(c.decompress(payload, g.shape))
    gb = np.pad(g, (0, 28)).reshape(-1, 32)
    scale = np.maximum(np.abs(gb).max(axis=1), 1e-12) / 127.0
    bound = np.repeat(scale, 32)[:100]
    assert (np.abs(deq - g) <= bound / 2 + 1e-7).all()


def test_int8_error_feedback_transmits_residual():
    """The block quantization error rides the per-key residual into
    the next step, so the transmitted total tracks the true signal."""
    c = Int8BlockCompressor(block=16)
    g = jnp.full((16,), 0.3, jnp.float32)
    sent = np.zeros(16, np.float32)
    for _ in range(10):
        payload = c.compress("w", g)
        sent += np.asarray(c.decompress(payload, g.shape))
    np.testing.assert_allclose(sent, 3.0, atol=0.05)


def test_int8_validates_and_kvstore_accepts():
    with pytest.raises(MXNetError):
        Int8BlockCompressor(block=0)
    store = mx.kv.create("local")
    with pytest.warns(UserWarning, match="single-process"):
        store.set_gradient_compression({"type": "int8", "block": 64})
    assert isinstance(store._compressor, Int8BlockCompressor)
    assert store._compressor.block == 64


@pytest.mark.parametrize("mk,kw", [
    (TwoBitCompressor, {"threshold": 0.5}),
    (Int8BlockCompressor, {"block": 64}),
])
def test_wire_bytes_is_payload_nbytes(mk, kw):
    """The shared wire contract: for every compressor and every shape,
    the payload's nbytes equal wire_bytes(shape) — what the kvstore
    meters — and both are well under the logical f32 size."""
    c = mk(**kw)
    for n in (16, 37, 64, 333):
        g = jnp.asarray(np.linspace(-1, 1, n), jnp.float32)
        p = c.compress(f"k{n}", g)
        assert int(p.nbytes) == c.wire_bytes(g.shape), n
        if n >= 64:     # below one block, padding dominates
            assert c.wire_bytes(g.shape) < n * 4, n


@pytest.mark.parametrize("params,expect", [
    ({"type": "2bit", "threshold": 0.5}, "2bit"),
    ({"type": "int8", "block": 64}, "int8"),
])
def test_dist_allreduce_meters_wire_bytes(monkeypatch, params, expect):
    """The compressed allreduce path meters wire_bytes — NOT the
    logical gradient bytes — and the reduced value equals
    num_workers x dequant(quant(grad)). Two fake processes via a
    monkeypatched allgather on a dist-shaped store."""
    from jax.experimental import multihost_utils
    from mxnet_tpu import kvstore as kvs
    store = object.__new__(kvs._DistSyncKVStore)
    kvs.KVStore.__init__(store, "dist_sync")
    store._rank, store._size = 0, 2
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.stack([np.asarray(x)] * 2))
    store.set_gradient_compression(params)
    assert store._compression["type"] == expect
    g = jnp.asarray(
        np.random.default_rng(3).standard_normal(200), jnp.float32)
    before = kvs._allreduce_bytes.labels("dist_sync").value
    out = store._allreduce(g, key="w")
    delta = kvs._allreduce_bytes.labels("dist_sync").value - before
    comp = store._compressor
    assert delta == comp.wire_bytes(g.shape)
    assert delta < int(g.size) * 4          # << logical f32 bytes
    fresh = type(comp)(**{k: v for k, v in params.items() if k != "type"})
    want = 2 * np.asarray(fresh.decompress(fresh.compress("w", g),
                                           g.shape))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6,
                               atol=1e-6)
